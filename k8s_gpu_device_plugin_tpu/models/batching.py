"""Continuous batching for serving: slot-based prefill/insert/decode.

TPU-first design (the JetStream/static-shape idiom): a serving engine
must keep the chip busy while requests arrive and finish at different
times. XLA wants static shapes, and the HBM for a fixed number of
concurrent sequences can be preallocated outright. So:

- The KV cache is dense ``(L, n_slots, max_len, Hkv, hd)`` by default;
  a *slot* is one concurrent sequence's reserved cache rows. An opt-in
  **paged layout** (``kv_layout="paged"``; the Ragged-Paged-Attention
  direction, PAPERS.md) keeps every shape just as static but maps each
  slot's virtual positions onto a shared ``(n_pages, page_size)`` pool
  through per-slot int32 page tables: HBM scales with live tokens,
  admission gates on pool pressure (models/paging.py), and prefix-cache
  reuse becomes zero-copy page aliasing with COW tails — token/logprob
  streams bit-identical to dense (tests/test_paged_kv.py).
- Every slot decodes at its OWN absolute position: ``lengths`` is a
  (B,) vector, attention masks per row, rope takes per-row positions,
  and the cache write is a vmapped per-row dynamic_update_slice
  (generate.py's ``_cache_write``/``_cached_attention`` generalize over
  scalar-vs-vector ``length``; this module is why).
- **Prefill-then-insert**: a new request prefills against a fresh
  single-row cache sized to its padded bucket, and the filled rows are
  inserted into its slot. Prompt lengths are bucketed to powers of two
  so the prefill jit compiles once per bucket, not once per length.
- **The decode step never changes shape**: finished/empty slots keep
  computing (their outputs are masked) — the fixed-shape trade every
  TPU decode loop makes, now applied across requests instead of within
  one batch.

The host-side :class:`ContinuousBatcher` owns the request queue, slot
assignment and per-request budgets; the device state is a plain pytree
(:class:`BatchState`) so the jitted step stays purely functional.

- **The decode loop is pipelined** (``pipeline_depth=1``, the default):
  each ``step()`` dispatches decode step t+1 before reading step t back,
  so stop-sequence matching, retirement, metrics and stream publishing
  overlap the device's next step instead of serializing with it. Budget
  gating and the seeded draw index live ON DEVICE (``BatchState.budget``
  / ``.draws``) and the membership mask / knobs / adapter / bias / seed
  arrays are cached device residents, so the steady-state loop performs
  ZERO per-step host->device transfers; the caches are invalidated only
  on admit/retire/cancel, and the in-flight step is flushed only before
  an admission that would reuse one of ITS live slots. The one-step lag
  is exact: a just-retired slot's in-flight token is dropped on
  readback, the same argument that already covers inactive-slot writes.
  ``pipeline_depth=0`` restores the fully synchronous loop (debugging;
  greedy and seeded token/logprob streams are bit-identical either way).

Capability parity note: the reference repo (a device plugin) has no
serving engine; this extends the workload stack the same way the
allocator extends its scheduling (SURVEY §2 'Parallelism substrate').
"""

from __future__ import annotations

import time
import weakref
from contextlib import nullcontext
from dataclasses import dataclass, field, replace
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from k8s_gpu_device_plugin_tpu.obs.trace import attach, get_tracer
from k8s_gpu_device_plugin_tpu.utils.log import get_logger

from k8s_gpu_device_plugin_tpu.models.generate import (
    KVCache,
    _forward_cached,
)
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig
from k8s_gpu_device_plugin_tpu.models.paging import (
    PagePool,
    kv_shard_token_bytes,
    kv_token_bytes,
    pack_kv_wire,
    unpack_kv_wire,
)
from k8s_gpu_device_plugin_tpu.models.sampling import (
    Sampler,
    sample_and_mark_dyn,
    sampler_knobs,
    token_logprob,
)


@dataclass(frozen=True)
class BatchState:
    """Device-side state of the serving batch (a pytree; jit-carried)."""

    cache: KVCache
    lengths: jax.Array     # (B,) int32: valid cache rows per slot
    last_token: jax.Array  # (B,) int32: input to the next decode step
    active: jax.Array      # (B,) bool: slot is mid-generation
    presence: jax.Array    # (B, V) bool: repetition-penalty context mask
    key: jax.Array         # PRNG key (split per step, folded per slot)
    # Per-slot generation budget, ON DEVICE: remaining tokens a slot may
    # still emit, decremented inside the jitted decode step and gating
    # emission exactly like ``active``. Host-side retirement used to be
    # the only budget authority; carrying it here lets the pipelined
    # loop dispatch step t+1 before reading step t without ever emitting
    # (or paying a transfer for) a token beyond any slot's budget.
    budget: jax.Array      # (B,) int32: tokens the slot may still emit
    # Per-slot draw index for seeded sampling (fold_in(key(seed), i)),
    # also ON DEVICE: it advances exactly once per emitted token, so the
    # steady-state decode loop needs no host-rebuilt (B,) draws transfer
    # and the pipelined dispatch always samples draw i with the true i.
    draws: jax.Array       # (B,) int32: next seeded-draw index per slot
    # Paged KV layout only (None on the dense layout): per-slot page
    # tables mapping virtual position p to pool page pages[slot, p // ps]
    # (models/paging.py owns the allocation; the table rows change only
    # at admission/alias time — the steady-state decode transfers
    # nothing, same lifecycle as the membership mask). Entry 0 is the
    # reserved trap page, so an unset table row is harmlessly readable.
    pages: jax.Array | None = None  # (B, max_len // page_size) int32


jax.tree_util.register_dataclass(
    BatchState,
    ("cache", "lengths", "last_token", "active", "presence", "key",
     "budget", "draws", "pages"),
    (),
)


def init_batch_state(
    cfg: LlamaConfig, n_slots: int, max_len: int, seed: int = 0,
    n_pages: int = 0,
) -> BatchState:
    paged = cfg.kv_layout == "paged"
    return BatchState(
        cache=(
            KVCache.init_paged(cfg, n_pages, cfg.kv_page_size) if paged
            else KVCache.init(cfg, n_slots, max_len)
        ),
        lengths=jnp.zeros((n_slots,), jnp.int32),
        last_token=jnp.zeros((n_slots,), jnp.int32),
        active=jnp.zeros((n_slots,), bool),
        presence=jnp.zeros((n_slots, cfg.vocab_size), bool),
        key=jax.random.key(seed),
        budget=jnp.zeros((n_slots,), jnp.int32),
        draws=jnp.zeros((n_slots,), jnp.int32),
        pages=(
            jnp.zeros((n_slots, max_len // cfg.kv_page_size), jnp.int32)
            if paged else None
        ),
    )


def _scatter_rows_paged(cache, rows, row, p: int, ps: int):  # graftlint: hot-path=traced
    """Scatter ``p`` contiguous single-row cache rows (L, 1, p, H, d)
    through a slot's page table ``row``: token i lands in page
    ``row[i // ps]`` at offset ``i % ps``. The one definition of the
    paged insert indexing — prefill_insert and the manual-prefix insert
    both write through it (traced inside their jits)."""
    idx = jnp.arange(p, dtype=jnp.int32)
    pidx, off = row[idx // ps], idx % ps

    def ins(full, part):
        if full is None:  # bf16 cache: no scale planes
            return None
        return full.at[:, pidx, off].set(part[:, 0])

    return jax.tree.map(ins, cache, rows, is_leaf=lambda x: x is None)


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(1,))
def prefill_insert(
    params,
    state: BatchState,
    prompt: jax.Array,       # (P,) int32, padded to a bucket size
    prompt_len: jax.Array,   # scalar int32: real length (<= P)
    slot: jax.Array,         # scalar int32
    cfg: LlamaConfig,
    knobs: jax.Array,        # (4,) f32 sampler knobs for THIS request
    max_new: jax.Array,      # scalar int32: the request's token budget
    sel: jax.Array | None = None,  # (1, N) adapter one-hot for THIS request
    bias: jax.Array | None = None,  # (1, V) logit bias for THIS request
    seed: jax.Array | None = None,  # (1,) i32 per-request seed (draw 0)
) -> tuple[BatchState, jax.Array, jax.Array]:
    """Prefill one request and insert it into ``slot``.

    Runs the prompt through a fresh single-row cache of capacity P (the
    padded bucket — P is ``prompt.shape[0]``, so each bucket compiles
    once), writes rows [0, P) into the slot's cache (rows past
    ``prompt_len`` are garbage but provably never attended: every later
    read masks to ``lengths[slot]``), seeds the slot's sampling state,
    and returns (state, first generated token, its logprob).
    """
    p = prompt.shape[0]
    scratch = KVCache.init(cfg, 1, p)
    # project ONLY the last real prompt position (select_pos): the padded
    # bucket's other rows never reach the lm_head matmul or logits HBM
    logits, scratch = _forward_cached(
        params, prompt[None, :], scratch, jnp.int32(0), cfg,
        select_pos=prompt_len - 1, lora_sel=sel,
    )
    first_logits = logits[0, 0]  # (V,)

    # presence mask over the real prompt only (padding must not count as
    # seen context for the repetition penalty); .max = scatter-OR, so a
    # token appearing both in the prompt and the padding stays True
    seen = jnp.zeros((cfg.vocab_size,), bool).at[prompt].max(
        jnp.arange(p) < prompt_len
    )

    key, sub = jax.random.split(state.key)
    tok, seen = sample_and_mark_dyn(
        first_logits[None, :], sub, knobs[None, :], seen[None, :], bias,
        seed,  # draw index defaults to 0 (the first draw) in the sampler
    )
    logp = token_logprob(first_logits[None, :], tok)[0]
    tok = tok[0]

    if cfg.kv_layout == "paged":
        # the pages behind state.pages[slot] were reserved by the
        # batcher before this dispatch
        cache = _scatter_rows_paged(
            state.cache, scratch, state.pages[slot], p, cfg.kv_page_size
        )
    else:
        def insert_rows(full, rows):
            if full is None:  # bf16 cache: no scale planes
                return None
            # (L, B, S, H, d) <- (L, 1, P, H, d) at (0, slot, 0, 0, 0)
            return jax.lax.dynamic_update_slice(
                full, rows, (0, slot, 0, 0, 0)
            )

        cache = jax.tree.map(
            insert_rows, state.cache, scratch,
            is_leaf=lambda x: x is None,
        )

    write = jnp.int32(slot)
    return BatchState(
        cache=cache,
        lengths=state.lengths.at[write].set(prompt_len),
        last_token=state.last_token.at[write].set(tok),
        active=state.active.at[write].set(True),
        presence=state.presence.at[write].set(seen[0]),
        key=key,
        # the prefill itself emitted token 1 of max_new (seeded draw 0)
        budget=state.budget.at[write].set(max_new - 1),
        draws=state.draws.at[write].set(1),
        pages=state.pages,
    ), tok, logp


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(1,))
def decode_step(  # graftlint: hot-path
    params,
    state: BatchState,
    allowed: jax.Array,  # (B,) bool: host-side membership gate per slot
    eos_id: jax.Array,   # scalar int32 (-1 disables EOS stopping)
    cfg: LlamaConfig,
    knobs: jax.Array,    # (B, 4) f32 per-slot sampler knobs
    sel: jax.Array | None = None,  # (B, N) per-slot adapter one-hots
    bias: jax.Array | None = None,  # (B, V) per-slot logit biases
    seeds: jax.Array | None = None,  # (B,) i32 seeds (-1 = unseeded)
) -> tuple[BatchState, jax.Array, jax.Array]:
    """One token for every slot (inactive slots compute-and-discard).

    Returns (state, emitted (B,) int32, logps (B,) f32) where emitted[i]
    is -1 for slots that were not active this step. EOS tokens ARE
    emitted (matching ``generate``'s keep-the-EOS semantics) and
    deactivate the slot after.

    ``allowed`` carries ONLY running-set membership (it changes on
    admit/retire/cancel, never per step — the batcher caches the device
    array); per-token budget gating and the seeded draw index live in
    ``state`` so the steady-state loop transfers nothing to the device.
    """
    was_active = state.active & allowed & (state.budget > 0)
    # Inactive slots still compute (fixed shapes) but must not WRITE at
    # their stale lengths: a mid-chunked-prefill neighbor's freshly
    # prefilled rows live there (reviewed failure: fresh slot at length 0
    # gets its prompt row 0 clobbered by the garbage K/V write). Redirect
    # inactive slots' writes to the last cache row — provably harmless:
    # any sequence only attends that row at q_pos >= max_len-1, and the
    # decode step that reaches it overwrites it first. On the paged
    # layout the hazard is sharper — a retired slot's stale table may
    # name pages since REALLOCATED to a live neighbor — so inactive
    # rows' whole table is redirected to the trap page 0 instead (never
    # allocated, never attended unmasked).
    if cfg.kv_layout == "paged":
        cache_len = state.pages.shape[1] * cfg.kv_page_size
        pages = jnp.where(was_active[:, None], state.pages, 0)
    else:
        cache_len = state.cache.k.shape[2]
        pages = None
    write_pos = jnp.where(was_active, state.lengths, cache_len - 1)
    logits, cache = _forward_cached(
        params, state.last_token[:, None], state.cache, write_pos, cfg,
        lora_sel=sel, pages=pages,
    )
    key, sub = jax.random.split(state.key)
    tok, presence = sample_and_mark_dyn(
        logits[:, -1], sub, knobs, state.presence, bias, seeds, state.draws
    )
    logps = token_logprob(logits[:, -1], tok)
    hit_eos = (tok == eos_id) & (eos_id >= 0)
    full = state.lengths + 1 >= cache_len
    emitted = jnp.where(was_active, tok, -1)
    budget = jnp.where(was_active, state.budget - 1, state.budget)
    return BatchState(
        cache=cache,
        lengths=jnp.where(was_active, state.lengths + 1, state.lengths),
        last_token=jnp.where(was_active, tok, state.last_token),
        active=was_active & ~hit_eos & ~full & (budget > 0),
        presence=jnp.where(was_active[:, None], presence, state.presence),
        key=key,
        budget=budget,
        draws=jnp.where(was_active, state.draws + 1, state.draws),
        pages=state.pages,
    ), emitted, logps


# distinguishes "cache invalid" (None) from a cached "no plane needed"
# answer in the per-slot cache slots below, so the steady-state dispatch
# never re-scans the running set to rediscover that nobody is seeded or
# biased — one sentinel check per step instead of an O(slots) any()
_NONE_CACHED = object()


#: the one bucket ladder: prefill compiles, prefix-cache promotion
#: boundaries and precompute_prefix padding all quantize to it (shared
#: here so the serving layer can build a PrefixCache with the same
#: boundaries the batcher will promote at)
DEFAULT_PROMPT_BUCKETS: tuple[int, ...] = (32, 64, 128, 256, 512, 1024)


def _bucket(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds largest bucket {buckets[-1]}")


def effective_prefix_reuse(matched: int, prompt_len: int, chunk: int) -> int:
    """Prefill compute a ``matched``-token prefix actually skips for a
    ``prompt_len``-token prompt under chunked prefill, in tokens of
    dispatched chunk work. The scheduler dispatches fixed-C intermediate
    chunks from the prefix boundary and the SAME back-scheduled finish
    chunk either way, so savings materialize only as whole skipped
    intermediate chunks: a 64-token match against chunk=256 skips
    nothing (the chunk grid just shifts), while a 256-token match skips
    exactly one 256-token dispatch. The ONE definition of this —
    cached_tokens, the prefix_reused metric and the cache's tokens_saved
    all report it (``chunk=0`` = no cap, returns ``matched``)."""
    if not chunk:
        return matched

    def n_chunks(start: int) -> int:
        # intermediate chunks _prefill_one_chunk dispatches from
        # ``start``: one per C while start + C < prompt_len
        return max(0, -(-(prompt_len - start) // chunk) - 1)

    return (n_chunks(0) - n_chunks(matched)) * chunk


class RequestTooLargeError(ValueError):
    """A request no amount of deferral can ever admit: its worst case
    outsizes the slot row or the whole page pool. Carries the numbers
    the refusal was computed from so both HTTP surfaces can serialize a
    structured ``request_too_large`` body (``{prompt_tokens, max_new,
    limit}`` — ``limit`` in TOKENS: the largest ``prompt + max_new``
    this server could ever hold) instead of a bare message."""

    def __init__(self, message: str, *, prompt_tokens: int, max_new: int,
                 limit: int):
        super().__init__(message)
        self.prompt_tokens = int(prompt_tokens)
        self.max_new = int(max_new)
        self.limit = int(limit)

    def body(self) -> dict:
        """The structured fields, serializer-ready."""
        return {
            "prompt_tokens": self.prompt_tokens,
            "max_new": self.max_new,
            "limit": self.limit,
        }


@dataclass
class _Request:
    rid: int
    prompt: list[int]          # FULL prompt (shared prefix + suffix)
    max_new: int
    out: list[int] = field(default_factory=list)
    # log P(out[i]) under the raw model distribution, parallel to out
    out_logp: list[float] = field(default_factory=list)
    slot: int = -1
    prefix: "PrefixState | None" = None  # rows already prefilled once
    # multi-token stop sequences (host-side suffix match; the matched
    # tokens are KEPT in the output, like the EOS-keep semantics)
    stop: tuple[tuple[int, ...], ...] = ()
    # per-request sampler override (None = the batcher's default); rides
    # the decode step as traced per-slot knobs, so mixed settings share
    # one compile
    sampler: "Sampler | None" = None
    # stacked-LoRA adapter index (models/lora_serving.py); -1 = base
    # model. Rides the decode step as a per-slot one-hot selection, so a
    # mixed batch of adapters shares one compile.
    adapter: int = -1
    # OpenAI-style logit bias: ((token_id, bias), ...) added to the RAW
    # logits before sampling. Rides the decode step as a per-slot dense
    # (V,) plane, built host-side like the sampler knobs.
    bias: tuple = ()
    # per-request sampling seed (None = shared step key): the i-th draw
    # uses fold_in(key(seed), i), i = len(out) host-side — the sampled
    # stream reproduces regardless of batch composition or timing
    seed: "int | None" = None
    # prompt tokens served from prefilled prefix rows instead of being
    # recomputed (an automatic prefix-cache hit, or a manual prefix);
    # surfaced as OpenAI usage prompt_tokens_details.cached_tokens
    cached_tokens: int = 0
    # request-lifecycle observability: submit/last-token perf_counter
    # marks (TTFT + inter-token histograms) and the request's span tree
    # (obs/trace.py; None everywhere when tracing is off)
    t_submit: float = 0.0
    t_last_tok: float = 0.0
    span: object = None
    decode_span: object = None
    # paged-KV admission bookkeeping: the prefix-cache match runs once
    # (``matched``) even if pool pressure defers the admission; a match
    # under the paged layout PINS the entry's pages (one pool reference
    # each) so a mid-queue eviction cannot free rows the request will
    # alias; ``_new_pages`` carries a successful reservation from the
    # pool-pressure check to the table install; ``defer_counted`` keeps
    # the rejected{pool_pressure} counter at one per deferred spell.
    matched: bool = False
    defer_counted: bool = False
    _pinned_pages: "list[int] | None" = None
    _new_pages: "list[int] | None" = None
    # the speculative batcher's draft-pool twin of ``_new_pages``: a
    # successful draft reservation carried to the draft-table install
    _draft_new_pages: "list[int] | None" = None
    # matched prefix depth carried from the (uncounted) queue-head match
    # to the slot-assignment commit, where the hit/miss disposition is
    # recorded — a deferred request can still be cancelled, and a
    # counted hit for a request that never ran would be a phantom
    _match_depth: "int | None" = None
    # SLO scheduling identity (serving/scheduler.py): tenant + priority
    # class (lower = more urgent) ride every request; ``deadline`` is an
    # ABSOLUTE perf_counter instant (None = no deadline). The fifo
    # default ignores all three beyond accounting.
    tenant: str = "default"
    priority: int = 1
    deadline: "float | None" = None
    # preemption/resume bookkeeping: ``prefilled_out`` counts emitted
    # tokens folded back into ``prompt`` by _preempt_slot (the resumed
    # prefill recomputes their K/V; the finish chunk's sampled token is
    # emission — and seeded draw — number prefilled_out).
    prefilled_out: int = 0
    preemptions: int = 0
    # engine restarts this request lived through MID-FLIGHT (the
    # supervisor's crash-recovery resume, serving/supervisor.py): the
    # flight recorder always retains these, and the scheduler treats
    # the re-admission like a preemption resume (no re-charge)
    restarts: int = 0
    # set when the scheduler rejects a queued request (defer budget):
    # surfaced through the stream info so the HTTP planes answer 429
    reject_reason: "str | None" = None
    # first-token and retirement perf_counter marks (the open-loop
    # bench reads TTFT / completion-vs-deadline off retired requests)
    t_first_tok: float = 0.0
    t_done: float = 0.0
    # per-request latency attribution (obs/attribution.py): a
    # RequestTimeline while attribution is enabled, else None — the
    # None check IS the hot path's entire cost when the layer is off
    timeline: object = None
    # prompt tokens that actually RAN through the model for this
    # request (chunk-overlap recompute included; prefix-reused rows
    # excluded) — the MFU layer's per-tenant prefill charge: a request
    # rejected while queued or cancelled mid-prefill must be charged
    # for what it computed, not its whole prompt
    prefill_computed: int = 0
    # KV-transfer install (disaggregated prefill/decode): the decoded
    # ``(meta, planes)`` of a kv_pages wire blob riding a resume
    # submission. Consumed (and cleared) by ``install_kv_pages`` at
    # admission; a request that never reaches install just drops it.
    _kv_wire: "tuple | None" = None



class ContinuousBatcher:
    """Host-side orchestrator: request queue -> slots -> token streams.

    Usage::

        cb = ContinuousBatcher(params, cfg, n_slots=4, max_len=256)
        rid = cb.submit([1, 5, 7], max_new=32)
        results = cb.run()          # {rid: [tok, ...], ...}

    ``run`` drains the queue: admits pending requests whenever slots are
    free (one bucketed prefill each), then steps the whole batch one
    token at a time, finishing requests on EOS or their ``max_new``
    budget. Submitting more requests than slots is the point — slot
    reuse IS continuous batching.
    """

    #: requests may carry their own Sampler (the speculative subclass
    #: turns this off: its draft/verify distributions are built from ONE
    #: static sampler)
    per_request_sampler = True
    #: per-request logit_bias planes (the speculative round doesn't
    #: thread them; it turns this off)
    per_request_bias = True
    #: per-request sampling seeds (same story)
    per_request_seed = True
    #: automatic prefix caching rides chunked prefill + _insert_prefix;
    #: a subclass whose prefill path cannot mirror prefix rows may turn
    #: this off (the speculative batcher supports it: the target aliases
    #: cached rows/pages and the draft cheaply re-prefills the prefix)
    supports_prefix_cache = True
    #: the paged KV layout (kv_layout="paged"); a subclass without page
    #: plumbing may turn this off (the speculative batcher supports it
    #: with a second, draft-sized pool)
    supports_paged_kv = True
    #: the slo scheduler may evict a decoding slot and resume it later
    #: via re-prefill (requires chunked prefill); a subclass whose
    #: device state cannot be rebuilt that way turns this off (the
    #: speculative batcher: the draft cache has no resume path)
    supports_preemption = True

    def __init__(
        self,
        params,
        cfg: LlamaConfig,
        n_slots: int,
        max_len: int,
        sampler: Sampler | None = None,
        eos_id: int | None = None,
        prompt_buckets: tuple[int, ...] = DEFAULT_PROMPT_BUCKETS,
        chunked_prefill: int = 0,
        seed: int = 0,
        metrics=None,
        adapters=None,  # lora_serving.AdapterSet | AdapterStore: multi-LoRA
        lora_slots: int | None = None,  # K compact adapter slots; None =
        #   n_slots (gathered O(active) serving); 0 = legacy dense-N stacks
        adapter_cache_mb: int = 0,  # AdapterStore HBM budget; 0 = unlimited
        pipeline_depth: int = 1,
        trace_steps: bool = False,
        prefix_cache=None,  # serving.prefix_cache.PrefixCache (or None)
        kv_layout: str | None = None,   # None = take cfg.kv_layout
        kv_page_size: int | None = None,  # None = take cfg.kv_page_size
        kv_pages: int = 0,  # paged pool size; 0 = dense-equivalent HBM
        prefill_reserve_chunks: int = 2,  # windowed admission: chunks of
        #   prompt the initial page tranche covers (--prefillReserveChunks)
        scheduler=None,  # serving.scheduler.Scheduler (or None = FIFO)
        tp: int | None = None,  # None = take cfg.tp (1 = single chip)
        attribution=None,  # obs.attribution.RequestAttributor (or None)
        mfu=None,  # metrics.roofline.MfuAccumulator (or None)
        faults=None,  # serving.faults.FaultPlane (or None = disarmed)
        devices=None,  # device.allocation.AllocatedDevices (or None)
    ):
        # the KV layout rides in the (static) cfg so every jitted step
        # branches on it at trace time; the explicit kwargs are sugar so
        # callers need not dataclasses.replace the config themselves
        if kv_layout is not None or kv_page_size is not None:
            cfg = replace(
                cfg,
                kv_layout=cfg.kv_layout if kv_layout is None else kv_layout,
                kv_page_size=(
                    cfg.kv_page_size if kv_page_size is None
                    else int(kv_page_size)
                ),
            )
        # tensor parallelism rides in the static cfg the same way: every
        # jitted step's tp constraints branch on it at trace time, and
        # tp=1 (the default) traces EXACTLY the single-chip graph
        if tp is not None and int(tp) != cfg.tp:
            cfg = replace(cfg, tp=int(tp))
        # the mesh (and the startup divisibility validation — tp must
        # divide the device count and the KV-head count) comes first:
        # everything below device_puts against it
        self.mesh = None
        if cfg.tp > 1:
            from k8s_gpu_device_plugin_tpu.parallel.tp_serving import (
                serving_mesh,
            )

            self.mesh = serving_mesh(cfg.tp, cfg.n_kv_heads)
        if cfg.kv_layout == "paged":
            if not self.supports_paged_kv:
                raise ValueError(
                    "this batcher does not support kv_layout='paged' "
                    "(no page tables to route its cache writes through)"
                )
            from k8s_gpu_device_plugin_tpu.models.quantized_serving import (
                check_cache_quant_kv_layout,
            )

            # the quantized-serving opt-out lives with the quantized
            # code (one definition, the admission-rule pattern)
            check_cache_quant_kv_layout(cfg)
            if max_len % cfg.kv_page_size:
                raise ValueError(
                    f"kv_page_size={cfg.kv_page_size} must divide "
                    f"max_len={max_len}: the page table's virtual extent "
                    "is exactly the slot capacity"
                )
        # Multi-LoRA: two serving modes behind one `adapters` kwarg.
        # GATHERED (the default, lora_slots=None or K>0): an AdapterStore
        # is the HBM-residency source and params carry compact (L, K, ...)
        # stacks holding only the batch-active adapters — per-step LoRA
        # cost scales with the active set, never the registry
        # (lora_serving.py, "N-vs-K cost model"). DENSE-N (lora_slots=0):
        # the full (L, N, ...) stacks attach once — the bit-identity
        # oracle and the tiny-N fallback.
        self.adapter_store = None   # lora_serving.AdapterStore | None
        self.lora_slots = 0         # K: compact stack width (0 = dense-N)
        self._lora_active: tuple[int, ...] = ()  # registry ids behind K slots
        self._adapter_names_static: tuple[str, ...] = ()
        self._gather_count = 0      # owner: engine (adapter_stats)
        self._gather_s = 0.0        # owner: engine
        if lora_slots is not None and lora_slots < 0:
            raise ValueError(f"lora_slots must be >= 0, got {lora_slots}")
        if adapters is not None:
            from k8s_gpu_device_plugin_tpu.models.lora_serving import (
                AdapterStore,
                attach_adapters,
            )

            store = None
            if isinstance(adapters, AdapterStore):
                if lora_slots == 0:
                    raise ValueError(
                        "lora_slots=0 (the dense-N path) needs a static "
                        "AdapterSet: an AdapterStore's registry can "
                        "outgrow any dense stack"
                    )
                store = adapters
            elif lora_slots == 0:
                params = attach_adapters(params, adapters)
                self._adapter_names_static = adapters.names
            else:
                store = AdapterStore.from_set(
                    cfg, adapters,
                    cache_bytes=int(adapter_cache_mb) << 20,
                )
            if store is not None:
                if store.rank_cap is None:
                    raise ValueError(
                        "the AdapterStore holds no registered adapters; "
                        "register at least one before serving (the "
                        "compact stacks' shape freezes at first "
                        "registration)"
                    )
                # K defaults to the slot count: a batch can never hold
                # more DISTINCT adapters than slots. An explicit K may
                # exceed today's registry (sized for later registrations)
                # but never needs to exceed n_slots.
                self.lora_slots = max(1, min(
                    n_slots if lora_slots is None else int(lora_slots),
                    n_slots,
                ))
                self.adapter_store = store
                store.bind(self._dev, metrics)
                params = {**params, "layers": {
                    **params["layers"],
                    **store.gather((), self.lora_slots),
                }}
        elif adapter_cache_mb:
            raise ValueError(
                "adapter_cache_mb is an AdapterStore budget; it needs "
                "adapters"
            )
        self._sel_cache: jax.Array | None = None  # (n_slots, N), like knobs; owner: engine
        self._bias_cache: jax.Array | None = None  # (n_slots, V), like knobs; owner: engine
        if self.mesh is not None:
            # load-time weight shard (the pjit/NamedSharding pattern):
            # column-cut projections + lm_head, replicated reduction
            # weights — the bit-identity-safe recipe; adapter stacks
            # (attached above) and quantized leaves replicate
            from k8s_gpu_device_plugin_tpu.parallel.tp_serving import (
                shard_serving_params,
            )

            params = shard_serving_params(params, cfg, self.mesh)
        self.params = params
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_len = max_len
        self.sampler = sampler or Sampler()
        self.eos_id = -1 if eos_id is None else eos_id
        # device-resident eos scalar: the decode dispatch must not pay
        # even a scalar H2D per step (the zero-transfer steady state);
        # under tp it commits replicated onto the mesh once, here
        self._eos_dev = self._dev(jnp.int32(self.eos_id))
        # chunked_prefill=C > 0: admission runs in C-token chunks
        # interleaved with decode steps (one chunk per step) instead of
        # one bucketed prefill dispatch — running slots' per-token latency
        # is bounded by a chunk, and the bucket ladder disappears (two
        # compiles total: chunk + finish)
        self.chunk = int(chunked_prefill)
        if self.chunk > max_len:
            raise ValueError(
                f"chunked_prefill={self.chunk} exceeds max_len={max_len}"
            )
        self.buckets = tuple(b for b in prompt_buckets if b <= max_len)
        if not self.chunk and not self.buckets:
            raise ValueError(
                f"no prompt bucket fits max_len={max_len} "
                f"(buckets={prompt_buckets})"
            )
        # Automatic prefix caching (serving/prefix_cache.py): submit
        # matches every prompt against it, the completed-prefill hook
        # promotes into it. Duck-typed (match/record_match/
        # on_prefill_done, plus evict_one under pool pressure) so this
        # module keeps its no-serving-imports layering.
        if prefix_cache is not None:
            if not self.supports_prefix_cache:
                raise ValueError(
                    "this batcher does not support an automatic prefix "
                    "cache (no way to serve a request from cached "
                    "prefix rows)"
                )
            if not self.chunk:
                raise ValueError(
                    "automatic prefix caching requires chunked_prefill=C "
                    "(the chunk scheduler is what continues a prefill "
                    "from the matched boundary)"
                )
            if not self.buckets:
                raise ValueError(
                    f"automatic prefix caching needs a prompt bucket <= "
                    f"max_len={max_len} (buckets={prompt_buckets}): "
                    "promotion boundaries are the bucket ladder"
                )
            # the cache's match gate, savings accounting and promotion
            # boundaries all depend on THIS batcher's chunk window and
            # bucket ladder; bind both here rather than trusting the
            # construction site to pass matching values (a cache that
            # already holds entries promoted on a different ladder
            # cannot be re-keyed — its tree edges span those boundaries)
            if prefix_cache.stats.nodes and \
                    tuple(prefix_cache.buckets) != self.buckets:
                raise ValueError(
                    "prefix cache already holds entries promoted on a "
                    f"different bucket ladder {prefix_cache.buckets} "
                    f"(this batcher's: {self.buckets})"
                )
            if prefix_cache.stats.entries and (
                getattr(prefix_cache.cfg, "kv_layout", "dense") == "paged"
            ):
                # page ids index the POOL of the batcher that promoted
                # them; no new batcher owns that pool, so aliasing them
                # would serve another pool's rows (and eviction would
                # decref pages this pool never allocated)
                raise ValueError(
                    "prefix cache already holds paged entries: their "
                    "page ids belong to the pool of the batcher that "
                    "promoted them — attach a fresh PrefixCache"
                )
            if prefix_cache.stats.entries and (
                getattr(prefix_cache.cfg, "kv_layout", "dense")
                != cfg.kv_layout
            ):
                raise ValueError(
                    "prefix cache already holds entries materialized "
                    f"under kv_layout={prefix_cache.cfg.kv_layout!r} "
                    f"(this batcher's: {cfg.kv_layout!r}); dense rows "
                    "and page-id tuples are not interchangeable"
                )
            if prefix_cache.stats.entries and (
                getattr(prefix_cache.cfg, "tp", 1) != cfg.tp
            ):
                # dense entries hold rows sharded over the promoting
                # batcher's mesh; re-aliasing them under a different
                # (or no) mesh would silently reshard mid-stream
                raise ValueError(
                    "prefix cache already holds entries materialized "
                    f"under tp={getattr(prefix_cache.cfg, 'tp', 1)} "
                    f"(this batcher's: {cfg.tp}); attach a fresh "
                    "PrefixCache"
                )
            prefix_cache.chunk = self.chunk
            prefix_cache.buckets = self.buckets
            # rebind the byte-accounting config too: paged entries round
            # their residency up to whole pages (prefix_kv_bytes)
            prefix_cache.cfg = cfg
            if cfg.kv_layout == "paged":
                # promoted entries hold page REFERENCES, not rows: the
                # cache stores PagedPrefixState and gives the pages back
                # through release_entry at eviction
                prefix_cache.entry_factory = (
                    lambda rows, tokens, presence, adapter:
                    PagedPrefixState(page_ids=tuple(rows), tokens=tokens,
                                     presence=presence, adapter=adapter)
                )
                prefix_cache.release_entry = _paged_release_hook(self)
            else:
                # a cache previously attached to a paged batcher (and
                # emptied) may carry that batcher's hooks; restore the
                # dense row-entry defaults
                prefix_cache.entry_factory = PrefixState
                prefix_cache.release_entry = None
        self.prefix_cache = prefix_cache
        # paged KV: the host-side page pool (free list + refcounts).
        # kv_pages sizes the HBM pool; the default reserves the same
        # capacity the dense layout would (plus the trap page), so
        # flipping the layout alone can never ADMIT less — operators
        # shrink kv_pages to overcommit HBM against live tokens.
        self.pool: PagePool | None = None  # owner: engine
        self._slot_pages: dict[int, list[int]] = {}  # owner: engine
        n_pages = 0
        if cfg.kv_layout == "paged":
            if kv_pages < 0:
                raise ValueError(
                    f"kv_pages must be >= 0 (0 = dense-equivalent pool), "
                    f"got {kv_pages} — a negative value would silently "
                    "serve the default pool size"
                )
            per_slot = max_len // cfg.kv_page_size
            n_pages = int(kv_pages) if kv_pages > 0 else n_slots * per_slot + 1
            self.pool = PagePool(n_pages, cfg.kv_page_size)
        # Sliding-window serving (long-context): attn_window > 0 bounds
        # every row's LIVE cache to its trailing window, so the paged
        # layout can admit prompts far past the pool's worst-case wall —
        # admission reserves only the first chunks, _prefill_one_chunk
        # grows the reservation as the cursor advances, and pages that
        # fall out of every future query's window recycle back to the
        # free list (host free-list math only; the windowed kernel's DMA
        # clamp never reads below the window and the gather masks those
        # rows to exact-zero weight, so no device cleanup is needed).
        self.window = int(getattr(cfg, "sliding_window", 0) or 0)
        self.reserve_chunks = max(1, int(prefill_reserve_chunks))
        # incremental reservation needs all three legs: a window to bound
        # the live span, chunked prefill to grow against, and the paged
        # pool to grow from. The speculative subclass opts out (its
        # verify window writes gamma rows past the accepted length and
        # its draft cache has no recycling plumbing).
        self._incremental_reserve = (
            self.pool is not None and self.window > 0 and self.chunk > 0
        )
        self._pages_recycled = 0  # owner: engine
        self._chunks_deferred = 0  # owner: engine
        self._recycle_lo: dict[int, int] = {}  # slot -> first live page idx
        # owner: engine (snapshot via kv_stats() for cross-thread reads)
        self.state = init_batch_state(cfg, n_slots, max_len, seed,
                                      n_pages=n_pages)
        if self.mesh is not None:
            # every BatchState leaf gets an EXPLICIT sharding at init —
            # cache (dense rows or the paged pool) on the KV-head axis,
            # everything else (lengths/masks/key/budgets and the one
            # replicated host-side page table) replicated — and every
            # jitted step preserves them, so prefill/decode/spec-verify
            # dispatch as sharded jits with the zero-H2D carry intact
            from k8s_gpu_device_plugin_tpu.parallel.tp_serving import (
                shard_batch_state,
            )

            self.state = shard_batch_state(self.state, self.mesh)
        self.pending: list[_Request] = []  # owner: engine
        # Pluggable admission policy (serving/scheduler.py), duck-typed
        # like the prefix cache and metrics so this module keeps its
        # no-serving-imports layering. None = today's FIFO admission
        # with ZERO added calls; the fifo Scheduler object is behavior-
        # identical (it never reorders, never preempts) but keeps the
        # SLO ledgers, so streams are pinned bit-identical either way.
        # Its own mutable state is engine-owned; cross-thread readers go
        # through scheduler.sched_stats().
        self.scheduler = scheduler
        if scheduler is not None and getattr(scheduler, "preempt_enabled",
                                             False):
            if not self.supports_preemption:
                # demote loudly-but-safely is wrong here: an operator
                # who asked for preemption must know this engine cannot
                raise ValueError(
                    "this batcher does not support preemption (no "
                    "resume path for its device state); use the slo "
                    "scheduler with preempt=False or the fifo policy"
                )
        self.running: dict[int, _Request] = {}    # slot -> decoding request; owner: engine
        self.prefilling: dict[int, _Request] = {}  # slot -> mid-prefill req; owner: engine
        self._prefill_pos: dict[int, int] = {}     # slot -> next chunk start; owner: engine
        self.done: dict[int, list[int]] = {}  # owner: engine
        # full retired _Request objects (tokens + logprobs); the serving
        # engine pops from BOTH maps per request to keep memory bounded
        self.done_requests: dict[int, "_Request"] = {}  # owner: engine
        self._next_rid = 0
        # Chip attribution (device/allocation.py): the physical chips
        # this batcher's arrays live on, frozen at startup. Immutable
        # (a frozen dataclass), so cross-thread reads are safe without
        # a snapshot method. Set before metrics: the startup KV gauge
        # report below already renders the per-shard chip mapping.
        self.devices = devices
        # optional metrics.ServingMetrics (or anything with its hooks);
        # None = zero overhead, no prometheus dependency on this path
        self.metrics = metrics
        if metrics is not None:
            # both layouts report their static KV reservation so dense
            # vs paged HBM is comparable on /metrics (duck-typed: fakes
            # without the hook cost nothing)
            set_res = getattr(metrics, "set_kv_reserved_bytes", None)
            if set_res is not None:
                set_res(self.kv_stats()["reserved_bytes"])
            self._report_kv_gauges()
        # Attention-backend visibility: the unified dispatcher's STATIC
        # plan (ops/attention.attention_backend_plan) — which backend
        # decode / verify / prefill route to and WHY. Logged at startup
        # (an opted-in kernel falling back to the XLA gather used to be
        # silent — the PR-8 tp>1 degradation), exported as the
        # decode_attn_backend gauge, surfaced on /v1/health through
        # attn_backend_stats().
        from k8s_gpu_device_plugin_tpu.ops.attention import (
            attention_backend_plan,
        )

        self.attn_plan = attention_backend_plan(
            decode_attn=cfg.decode_attn, prefill_attn=cfg.prefill_attn,
            kv_layout=cfg.kv_layout, max_len=max_len,
            page_size=cfg.kv_page_size if cfg.kv_layout == "paged" else 0,
            n_heads=cfg.n_heads, n_kv_heads=cfg.n_kv_heads,
            head_dim=cfg.head_dim, cache_quant=cfg.cache_quant,
            tp=cfg.tp, chunk=self.chunk, window=self.window,
        )
        log = get_logger()
        for mode, plan in self.attn_plan.items():
            wanted = (cfg.prefill_attn if mode == "prefill"
                      else cfg.decode_attn)
            # an explicit kernel request that fell back is a WARNING (the
            # operator asked for speed they are not getting — the
            # previously-silent degradation); routine routing is debug
            emit = (
                log.warning
                if wanted == "ragged" and plan["backend"] != "pallas"
                else log.debug
            )
            emit(
                "attention backend: %s -> %s (%s)",
                mode, plan["backend"], plan["reason"],
                extra={"fields": {"mode": mode,
                                  "backend": plan["backend"],
                                  "reason": plan["reason"]}},
            )
        if metrics is not None:
            set_attn = getattr(metrics, "set_decode_attn_backend", None)
            if set_attn is not None:
                set_attn(self.attn_plan)
        # cached (n_slots, 4) device array for the decode step; running-
        # set membership changes (admit/retire/cancel) invalidate it, so
        # steady-state decode pays no per-token host build + transfer
        self._knobs_cache: jax.Array | None = None  # owner: engine
        # same lifecycle for the (n_slots,) membership mask and seeds:
        # allowed is pure running-set membership (budget gating moved
        # into BatchState), so it too only changes on admit/retire/cancel
        self._allowed_cache: jax.Array | None = None  # owner: engine
        self._seeds_cache: jax.Array | None = None  # owner: engine
        # pipeline_depth=1 (the serving default): each step() dispatches
        # decode step t+1 BEFORE reading step t back, so host per-token
        # work (stop matching, retirement, metrics, streaming) overlaps
        # the device's next step. 0 = today's fully synchronous loop
        # (debugging). Token streams are bit-identical between the two
        # for greedy and seeded requests — the speculative subclass
        # rides the same machinery through the dispatch/apply seams.
        if pipeline_depth not in (0, 1):
            raise ValueError(
                f"pipeline_depth must be 0 or 1, got {pipeline_depth}"
            )
        self.pipeline_depth = int(pipeline_depth)
        # the (at most one) dispatched-but-unread decode step:
        # (step_no, emitted, logps) device arrays
        self._inflight: tuple | None = None  # owner: engine
        self._step_no = 0
        # Per-request latency attribution + live MFU/roofline accounting
        # (obs/attribution.py, metrics/roofline.py). Duck-typed and
        # optional like metrics: None (the default) leaves the hot path
        # with nothing but `is not None` checks — the bit-identity /
        # no-overhead house pin. Both objects' mutable state is engine-
        # thread-owned; cross-thread readers go through the batcher's
        # attribution_stats()/mfu_stats() snapshot methods.
        self.attribution = attribution
        self.mfu = mfu
        # duck-typed handoff of the chip set to the attributor so
        # retired-request timelines name their silicon
        if devices is not None and attribution is not None:
            set_devices = getattr(attribution, "set_devices", None)
            if set_devices is not None:
                set_devices(devices)
        # process-global tracer: every site below guards on .enabled, so
        # the default-off path is one attribute read per potential span
        self.tracer = get_tracer()
        # per-step decode_dispatch/decode_readback spans are opt-in on
        # top of tracing (they are batch-scoped root traces — always-on
        # they would crowd the per-request trees out of the trace ring)
        self.trace_steps = bool(trace_steps)
        # Seeded fault injection (serving/faults.py), duck-typed like
        # metrics so this module keeps its no-serving-imports layering:
        # each seam resolves its point ONCE here — None when disarmed,
        # so the steady-state cost of the whole plane is one
        # is-not-None compare per seam (microbenched in bench-chaos,
        # the attribution-guard pattern). ``_fault_error`` hands the
        # injected-exception TYPE over the same duck-typed seam (the
        # pool.alloc site catches it without importing serving code).
        point = faults.point if faults is not None else (lambda name: None)
        self._flt_pool_alloc = point("pool.alloc")
        self._flt_prefill = point("prefill.dispatch")
        self._flt_decode = point("decode.apply")
        self._flt_promote = point("prefix.promote")
        self._flt_adapter_upload = point("adapter.upload")
        self._adapter_deferrals: dict[str, int] = {}  # owner: engine
        self._fault_error = (
            getattr(faults, "error", None) if faults is not None else None
        )

    def validate(self, prompt_len: int, max_new: int) -> None:
        """Raise ValueError iff submit(prompt of this length) would.

        The ONE admission rule, shared by submit and by the serving
        engine's request thread (which must reject before handing work
        to the engine thread — an admission error THERE would kill the
        step loop)."""
        if prompt_len + max_new > self.max_len:
            raise RequestTooLargeError(
                f"prompt {prompt_len} + max_new {max_new} exceeds "
                f"slot capacity {self.max_len}",
                prompt_tokens=prompt_len, max_new=max_new,
                limit=self.max_len,
            )
        if self.pool is not None:
            # the paged wall is POOL pressure, not the per-slot ceiling:
            # a request whose worst case outsizes the whole pool can
            # never be admitted and must be refused here (transient
            # pressure defers in _admit instead)
            tokens = self._kv_need_tokens(prompt_len, max_new)
            if self._incremental_reserve:
                # windowed rows never hold their whole prompt: the peak
                # is the trailing window plus the in-flight chunks (or
                # the decode span), so the wall moves from O(prompt) to
                # O(window + chunk) — the long-context admission rule
                tokens = min(tokens, self._windowed_peak_tokens(max_new))
            need = self.pool.pages_for_tokens(tokens)
            if need > self.pool.capacity:
                self._count_kv_rejection("request_too_large")
                # the token limit the refusal reports: the largest
                # prompt + max_new THIS pool could ever cover (windowed
                # admissions are bounded by the peak formula instead,
                # so their wall is effectively max_len — caught above)
                raise RequestTooLargeError(
                    f"request needs {need} KV pages (prompt {prompt_len} "
                    f"+ max_new {max_new} @ page_size "
                    f"{self.pool.page_size}) but the pool holds "
                    f"{self.pool.capacity}; raise kv_pages or shrink "
                    "the request",
                    prompt_tokens=prompt_len, max_new=max_new,
                    limit=self.pool.capacity * self.pool.page_size,
                )
        if not self.chunk:
            _bucket(prompt_len, self.buckets)

    def validate_bias(self, logit_bias) -> tuple:
        """Normalize/validate a logit_bias mapping (the admission-rule
        pattern: shared with the serving engine's request thread).
        Accepts {token_id: bias} or an iterable of pairs; OpenAI bounds:
        at most 300 entries, bias in [-100, 100], ids in-vocab."""
        if not logit_bias:
            return ()
        items = (
            logit_bias.items() if isinstance(logit_bias, dict)
            else list(logit_bias)
        )
        out = []
        for tok, b in items:
            tok = int(tok)
            b = float(b)
            if not (0 <= tok < self.cfg.vocab_size):
                raise ValueError(
                    f"logit_bias token {tok} outside vocab "
                    f"[0, {self.cfg.vocab_size})"
                )
            if not (-100.0 <= b <= 100.0):
                raise ValueError(
                    f"logit_bias value {b} outside [-100, 100]"
                )
            out.append((tok, b))
        if len(out) > 300:
            raise ValueError(
                f"logit_bias supports at most 300 entries (got {len(out)})"
            )
        return tuple(out)

    @staticmethod
    def validate_seed(seed) -> "int | None":
        """The seed half of the admission rule (static: the bound is a
        property of the key scheme, not of any batcher instance). Shared
        by submit, the engine's request thread, and both HTTP parsers —
        one definition of a valid seed."""
        if seed is None:
            return None
        seed = int(seed)
        if not (0 <= seed < 2**31):
            raise ValueError(f"seed must be in [0, 2^31), got {seed}")
        return seed

    @staticmethod
    def validate_sched(tenant, priority, deadline_ms) -> tuple:
        """The scheduling half of the admission rule (static, like
        ``validate_seed``): one definition of a valid (tenant, priority,
        deadline_ms) triple, shared by submit, the serving engine's
        request thread, and both HTTP parsers. Returns the normalized
        triple; ``deadline_ms`` None/0 means no deadline."""
        if tenant is None or tenant == "":
            tenant = "default"
        if not isinstance(tenant, str) or len(tenant) > 64:
            raise ValueError(
                "tenant must be a string of at most 64 characters"
            )
        if not tenant.isprintable():
            # the tenant rides metric LABELS ({tenant=...}) and JSON log
            # fields: control characters would be escaped differently by
            # every consumer (Prometheus text vs JSON vs trace attrs) —
            # refuse at the one admission rule instead
            raise ValueError(
                "tenant must contain printable characters only"
            )
        priority = 1 if priority is None else int(priority)
        if not (0 <= priority <= 9):
            raise ValueError(
                f"priority must be in [0, 9] (lower = more urgent), "
                f"got {priority}"
            )
        if deadline_ms is not None:
            deadline_ms = int(deadline_ms)
            if deadline_ms < 0:
                raise ValueError(
                    f"deadline_ms must be >= 0 (0 = none), got {deadline_ms}"
                )
            if deadline_ms == 0:
                deadline_ms = None
        return tenant, priority, deadline_ms

    def validate_resume(
        self, resume_out, resume_logp, max_new: int, prefix=None,
    ) -> "tuple[list[int], list[float]]":
        """The resume half of the admission rule (shared with the
        serving engine's request thread, like ``validate``): normalize
        and validate the already-emitted token/logprob lists of a
        cross-incarnation resume. Returns ``([], [])`` when no resume
        was requested."""
        toks = list(resume_out or ())
        if not toks:
            if resume_logp:
                raise ValueError(
                    "resume_logprobs without resume_out makes no sense"
                )
            return [], []
        if not self.chunk:
            raise ValueError(
                "stream resume requires chunked_prefill=C (the chunk "
                "scheduler is what re-prefills the folded output)"
            )
        if prefix is not None:
            raise ValueError(
                "resume_out composes with the AUTOMATIC prefix cache "
                "(re-matched over the folded prompt), not with a manual "
                "prefix"
            )
        if not all(
            isinstance(t, int) and not isinstance(t, bool) for t in toks
        ):
            raise ValueError("resume_out must be a list of token ids")
        if len(toks) >= max_new:
            raise ValueError(
                f"resume_out carries {len(toks)} tokens but max_new is "
                f"{max_new}: nothing left to resume"
            )
        lps = [float(x) for x in (resume_logp or ())]
        if lps and len(lps) != len(toks):
            raise ValueError(
                f"resume_logprobs length {len(lps)} != resume_out "
                f"length {len(toks)}"
            )
        if not lps:
            # the caller never saw logprobs (it didn't ask for them):
            # placeholders keep out/out_logp paired — indices below
            # prefilled_out are never re-published
            lps = [0.0] * len(toks)
        return toks, lps

    def validate_kv_pages(
        self, kv_pages, prompt_len: int, resume_len: int,
    ) -> "tuple | None":
        """The KV-transfer half of the admission rule (shared with the
        serving engine's request thread, like ``validate``): decode a
        :func:`~.paging.pack_kv_wire` blob and check it against THIS
        batcher's pool geometry and cache planes. Returns the decoded
        ``(meta, planes)`` pair that ``install_kv_pages`` consumes, or
        None when no blob was passed."""
        if kv_pages is None:
            return None
        if self.pool is None:
            raise ValueError(
                "kv_pages requires the paged KV layout on the receiving "
                "replica (kv_layout='paged' / --kvLayout paged); this "
                "batcher serves the dense layout — resubmit without "
                "kv_pages to re-prefill instead"
            )
        if not resume_len:
            raise ValueError(
                "kv_pages without resume_out: pages are exported after "
                "the first emitted token, so an install always resumes "
                "at least one token"
            )
        if isinstance(kv_pages, tuple):
            # already decoded (the serving engine validates on the
            # request thread and hands the decoded pair through the
            # submit queue — no second base64 pass on the engine thread)
            meta, planes = kv_pages
        else:
            meta, planes = unpack_kv_wire(kv_pages)
        if int(meta["page_size"]) != self.pool.page_size:
            raise ValueError(
                f"kv wire blob uses page_size={meta['page_size']} but "
                f"this pool uses {self.pool.page_size}: pages only "
                "transfer between identically paged replicas"
            )
        if meta.get("cache_quant") != self.cfg.cache_quant:
            raise ValueError(
                f"kv wire blob was exported from a "
                f"cache_quant={meta.get('cache_quant')!r} pool; this "
                f"batcher serves cache_quant={self.cfg.cache_quant!r}"
            )
        want = {
            name: leaf
            for name, leaf in (
                ("k", self.state.cache.k), ("v", self.state.cache.v),
                ("k_scale", self.state.cache.k_scale),
                ("v_scale", self.state.cache.v_scale),
            )
            if leaf is not None
        }
        if set(planes) != set(want):
            raise ValueError(
                f"kv wire blob carries planes {sorted(planes)} but this "
                f"pool holds {sorted(want)} (quantization mismatch?)"
            )
        for name, arr in planes.items():
            leaf = want[name]
            ref = (leaf.shape[0],) + tuple(leaf.shape[2:])
            got = (arr.shape[0],) + tuple(arr.shape[2:])
            if got != ref or str(arr.dtype) != str(leaf.dtype):
                raise ValueError(
                    f"kv wire plane {name!r} is {tuple(arr.shape)} "
                    f"{arr.dtype}; this pool's rows are "
                    f"(L={leaf.shape[0]}, n, {leaf.shape[2]}, "
                    f"{leaf.shape[3]}, {leaf.shape[4]}) {leaf.dtype}"
                )
        valid = int(meta["tokens"])
        folded = prompt_len + resume_len
        if valid != folded - 1:
            raise ValueError(
                f"kv wire blob covers {valid} cache rows but the folded "
                f"prompt ({prompt_len} prompt + {resume_len} resumed "
                f"tokens) needs {folded - 1} (the newest resumed "
                "token's row is written by the finish chunk)"
            )
        if int(meta["n_pages"]) != self.pool.pages_for_tokens(valid):
            raise ValueError(
                f"kv wire blob ships {meta['n_pages']} pages for "
                f"{valid} rows; page_size {self.pool.page_size} needs "
                f"{self.pool.pages_for_tokens(valid)}"
            )
        return meta, planes

    def kv_install_headroom(
        self, prompt_len: int, max_new: int,
    ) -> "tuple[int, int]":
        """``(pages needed, pages free)`` for an incoming KV-page
        install — the submit-time pressure gate on the transfer seam.
        Cross-thread safe by the thread-ownership contract:
        ``pages_for_tokens`` is pure arithmetic on immutable pool
        geometry and ``free_pages`` is one GIL-atomic ``len()`` of the
        free list (the same approximate-read contract as ``stats()``);
        the engine-thread reservation in ``_reserve_pages`` stays
        authoritative if a burst races past this read."""
        if self.pool is None:
            return (0, 0)
        need = self.pool.pages_for_tokens(
            self._kv_need_tokens(prompt_len, max_new)
        )
        return need, self.pool.free_pages

    @property
    def adapter_names(self) -> tuple:
        """Positional adapter names (the index requests select by).
        Frozen for a static AdapterSet; DYNAMIC under an AdapterStore
        (registration appends, unregistration leaves a "" tombstone so
        live indices never shift)."""
        if self.adapter_store is not None:
            return self.adapter_store.names_tuple
        return self._adapter_names_static

    @property
    def n_adapters(self) -> int:
        return len(self.adapter_names)

    def validate_adapter(self, adapter: int) -> None:
        """The adapter half of the admission rule (shared with the
        serving engine's request thread, like ``validate``)."""
        if adapter < 0:
            return
        if adapter >= self.n_adapters:
            raise ValueError(
                f"adapter index {adapter} out of range: this batcher "
                f"serves {self.n_adapters} adapter(s)"
            )
        if (self.adapter_store is not None
                and not self.adapter_store.is_registered(adapter)):
            raise ValueError(
                f"adapter index {adapter} was unregistered"
            )

    def submit(
        self,
        prompt: list[int],
        max_new: int,
        prefix: "PrefixState | None" = None,
        stop: list[list[int]] | None = None,
        sampler: "Sampler | None" = None,
        adapter: int = -1,
        logit_bias=None,
        seed: "int | None" = None,
        tenant: str = "default",
        priority: int = 1,
        deadline_ms: "int | None" = None,
        resume_out: "list[int] | None" = None,
        resume_logp: "list[float] | None" = None,
        kv_pages=None,
    ) -> int:
        """Queue a request. ``prefix`` (precompute_prefix) prepends a
        SHARED prefilled prefix: its rows are copied into the slot at
        admission and only ``prompt`` (the suffix) runs through prefill
        — N requests sharing a P-token system prompt pay one P-token
        prefill total. Requires chunked_prefill (the chunk scheduler is
        what continues from an arbitrary offset). ``adapter`` selects a
        stacked LoRA adapter (-1 = base model).

        With an automatic ``prefix_cache`` attached, a request that
        names no explicit prefix is matched against it at ADMISSION
        (``_admit``): the longest cached prefix of its prompt
        (adapter-keyed, so the weights guard below can never fire on a
        cache hit) becomes the request's prefix and only the suffix is
        chunk-prefilled — the same path as a manual prefix, so the
        token/logprob streams are bit-identical with the cache on or
        off. Matching at admission rather than here means a queued burst
        behind one system prompt hits as soon as the first prefill
        promotes it, and nothing is counted for requests that are
        rejected below or cancelled while still pending.

        ``resume_out`` is the cross-incarnation RESUME seam (the fleet
        router's mid-stream replica-death recovery, serving/router.py):
        tokens this request already emitted somewhere else. They ride
        the PR-7 preemption fold — folded into the prompt, pre-seeded
        into ``out``/``out_logp`` with ``prefilled_out`` set — so the
        finish chunk samples emission (and seeded draw) number
        ``len(resume_out)`` against the REMAINING budget: greedy AND
        seeded continuations are bit-identical to an uninterrupted run,
        and stop-sequence matching spans the resume boundary.
        ``resume_logp`` carries the already-emitted logprobs (zeros
        when the caller never saw them — indices below ``prefilled_out``
        are never re-published).

        ``kv_pages`` upgrades a resume from "re-prefill the folded
        prompt" to "install the transferred pages" (disaggregated
        prefill/decode): a :func:`~.paging.pack_kv_wire` blob exported
        by another replica's ``export_kv_pages`` is scattered into
        freshly allocated pages at admission, and the chunk scheduler
        starts at the finish chunk instead of position 0 — same
        emissions, same seeded draws, bit-identical streams, without
        recomputing the prompt's K/V. Requires ``resume_out`` (pages
        export only after the first emitted token) and the paged layout
        on this batcher."""
        if prefix is not None and not self.chunk:
            raise ValueError("prefix sharing requires chunked_prefill=C")
        if isinstance(prefix, PagedPrefixState):
            # paged entries hold POOL-INTERNAL page references whose
            # lifetime the attached cache owns (pinned at match time,
            # released at eviction); a manually submitted one reaches
            # admission unpinned, where the pressure-relief eviction
            # could free and reallocate its pages out from under it —
            # refuse loudly instead of corrupting KV
            raise ValueError(
                "PagedPrefixState cannot be submitted manually: paged "
                "prefix entries are owned by the attached prefix cache "
                "(manual prefixes carry dense rows from precompute_prefix)"
            )
        resume_out, resume_logp = self.validate_resume(
            resume_out, resume_logp, max_new, prefix=prefix
        )
        total = (
            len(prompt) + len(resume_out)
            + (len(prefix.tokens) if prefix else 0)
        )
        # reject here, not in _admit: a mid-run() failure would strand
        # every in-flight neighbor. A resumed request's folded tokens
        # sit in the prompt AND count against max_new — validate the
        # REMAINING budget so the row total matches the original
        # request's worst case exactly (the _reserve_pages rule).
        self.validate(total, max_new - len(resume_out))
        kv_wire = self.validate_kv_pages(
            kv_pages, len(prompt), len(resume_out)
        )
        self.validate_adapter(adapter)
        bias = self.validate_bias(logit_bias)
        seed = self.validate_seed(seed)
        tenant, priority, deadline_ms = self.validate_sched(
            tenant, priority, deadline_ms
        )
        if prefix is not None and prefix.adapter != adapter:
            # the prefix rows were prefilled under ONE set of weights;
            # reusing them under another would serve wrong K/V silently
            raise ValueError(
                f"prefix was prefilled with adapter {prefix.adapter}, "
                f"request uses {adapter}"
            )
        rid = self._next_rid
        self._next_rid += 1
        # the preemption fold, applied at the submit edge: emitted
        # tokens become prompt rows, prefilled_out tells prefill_finish
        # which emission (and seeded draw) comes next
        full = (
            (list(prefix.tokens) if prefix else [])
            + list(prompt) + resume_out
        )
        now = time.perf_counter()
        req = _Request(
            rid, full, max_new, prefix=prefix,
            stop=tuple(tuple(s) for s in (stop or ()) if s),
            sampler=sampler, adapter=adapter, bias=bias, seed=seed,
            tenant=tenant, priority=priority,
            # the deadline anchors at submit receipt: queue wait counts
            # against it (that is the point of deadline scheduling)
            deadline=(
                now + deadline_ms / 1000.0 if deadline_ms else None
            ),
            # manual prefixes report EFFECTIVE reuse too (auto-matched
            # ones are set at admission): rows the finish window
            # recomputes anyway are not served-from-cache
            cached_tokens=(
                effective_prefix_reuse(
                    len(prefix.tokens), len(full), self.chunk
                ) if prefix else 0
            ),
        )
        if resume_out:
            # exactly the shape _preempt_slot leaves behind: out holds
            # every emitted token (stop matching spans the boundary),
            # the fold above put them in the prompt, and retirement at
            # len(out) >= max_new needs no special case
            req.out = list(resume_out)
            req.out_logp = list(resume_logp)
            req.prefilled_out = len(resume_out)
            req._kv_wire = kv_wire
        req.t_submit = now
        if self.scheduler is not None:
            # admission control (queue cap, quota charge) BEFORE the
            # request queues or counts anywhere; a raise here leaves the
            # batcher untouched (SchedulerOverloadError -> HTTP 429)
            self.scheduler.on_submit(req, self)
        if self.tracer.enabled:
            # root of the request's span tree; parent (if any) is the
            # ambient context — the HTTP handler's span attached around
            # this call by the serving engine. tenant/priority ride the
            # span attrs AND the log fields so log correlation can slice
            # by SLO identity, not just trace_id/span_id.
            req.span = self.tracer.span(
                "request", component="serving", rid=rid,
                prompt_len=len(full), max_new=max_new,
                tenant=tenant, priority=priority,
            )
            with attach(req.span):  # the log line carries the trace ids
                get_logger().debug(
                    "request submitted",
                    extra={"fields": {"rid": rid, "prompt_len": len(full),
                                      "max_new": max_new, "tenant": tenant,
                                      "priority": priority}},
                )
        if self.attribution is not None:
            req.timeline = self.attribution.start(
                req,
                trace_id=req.span.trace_id if req.span is not None else "",
            )
        self.pending.append(req)
        if self.metrics:
            self.metrics.on_submit()
        return rid

    # --- internals ---

    def _dev(self, x) -> jax.Array:
        """Host value -> resident device array. tp=1: a plain asarray,
        exactly the old upload. tp>1: committed REPLICATED onto the tp
        mesh — jit requires one device assembly across its args, and an
        uncommitted single-device array would be re-transferred on
        every call, quietly breaking the zero-per-step-H2D contract the
        hot-path-h2d checker pins."""
        x = jnp.asarray(x)
        if self.mesh is None:
            return x
        from k8s_gpu_device_plugin_tpu.parallel.tp_serving import replicate

        return replicate(x, self.mesh)

    def _dispatch_scope(self):  # graftlint: hot-path
        """The mesh scope every device dispatch runs under: tp>1 traces
        bind the tp-axis sharding constraints in models/generate.py
        inside it; tp=1 returns a nullcontext and traces exactly the
        pre-tp graphs (the constraints no-op without a mesh). Runs once
        per step — registered hot so no transfer ever sneaks in."""
        return self.mesh if self.mesh is not None else nullcontext()

    def _req_knobs(self, req: _Request) -> jax.Array:
        return self._dev(jnp.asarray(
            sampler_knobs(req.sampler or self.sampler), jnp.float32
        ))

    def _batch_knobs(self) -> jax.Array:
        """(n_slots, 4) per-slot sampler knobs for the decode step (the
        batcher default everywhere a request didn't override); cached
        until the running set changes."""
        if self._knobs_cache is None:
            arr = np.tile(
                np.asarray(sampler_knobs(self.sampler), np.float32),
                (self.n_slots, 1),
            )
            for slot, req in self.running.items():
                if req.sampler is not None:
                    arr[slot] = sampler_knobs(req.sampler)
            self._knobs_cache = self._dev(arr)
        return self._knobs_cache

    def _req_bias(self, req: _Request) -> "jax.Array | None":
        """(1, V) dense bias plane for one request's prefill sampling
        (None when the request carries no bias — the common compiled
        path stays bias-free)."""
        if not req.bias:
            return None
        arr = np.zeros((1, self.cfg.vocab_size), np.float32)
        for tok, b in req.bias:
            arr[0, tok] += b
        return self._dev(arr)

    def _batch_bias(self) -> "jax.Array | None":
        """(n_slots, V) per-slot bias planes for the decode step; None
        when NO running request has a bias (the bias-free compile).
        Cached until the running set changes — same lifecycle as the
        knobs/sel caches (invalidated together); the no-bias answer is
        cached too (the _NONE_CACHED sentinel), so the steady-state
        dispatch never re-scans the running set."""
        if self._bias_cache is None:
            if any(req.bias for req in self.running.values()):
                arr = np.zeros(
                    (self.n_slots, self.cfg.vocab_size), np.float32
                )
                for slot, req in self.running.items():
                    for tok, b in req.bias:
                        arr[slot, tok] += b
                self._bias_cache = self._dev(arr)
            else:
                self._bias_cache = _NONE_CACHED
        return None if self._bias_cache is _NONE_CACHED else self._bias_cache

    def _req_seed(self, req: _Request) -> "jax.Array | None":
        """(1,) seed for one request's prefill sampling (draw 0)."""
        if req.seed is None:
            return None
        return self._dev(jnp.asarray([req.seed], jnp.int32))

    def _batch_seeds(self):
        """(B,) per-slot seeds for the decode step — or None when no
        running request is seeded (the unchanged compile). The draw
        index rides in ``BatchState.draws`` on device, so unlike the old
        host-rebuilt (seeds, draws) pair this is cached until the
        running set changes: the steady-state loop transfers nothing
        (and, via the _NONE_CACHED sentinel, re-scans nothing)."""
        if self._seeds_cache is None:
            if any(req.seed is not None for req in self.running.values()):
                seeds = np.full((self.n_slots,), -1, np.int32)
                for slot, req in self.running.items():
                    if req.seed is not None:
                        seeds[slot] = req.seed
                self._seeds_cache = self._dev(seeds)
            else:
                self._seeds_cache = _NONE_CACHED
        return None if self._seeds_cache is _NONE_CACHED else self._seeds_cache

    def _batch_allowed(self) -> jax.Array:
        """(B,) bool running-set membership mask for the decode step;
        cached until the running set changes (one H2D per membership
        event, zero in steady state — budget gating lives on device)."""
        if self._allowed_cache is None:
            allowed_np = np.zeros((self.n_slots,), bool)
            allowed_np[list(self.running)] = True
            self._allowed_cache = self._dev(allowed_np)
        return self._allowed_cache

    def _invalidate_slot_caches(self) -> None:
        """Drop every per-slot device-array cache (knobs, adapter
        one-hots, bias planes, membership mask, seeds). The ONE
        invalidation point for running-set membership changes — a new
        cache added here can't miss a site. The GATHERED compact adapter
        stacks ride this lifecycle one level down: the sel rebuild that
        follows an invalidation runs ``_ensure_gathered``, which
        re-gathers only if the membership change actually changed the
        batch's ACTIVE ADAPTER set — steady-state decode touches none
        of it (zero per-step H2D either way)."""
        self._knobs_cache = None
        self._sel_cache = None
        self._bias_cache = None
        self._allowed_cache = None
        self._seeds_cache = None

    def _active_adapters(self, extra: int = -1) -> tuple:
        """The distinct adapter indices live in the batch (running +
        mid-prefill), ascending, optionally plus one about-to-dispatch
        request's — the set the compact stacks must cover."""
        s = {r.adapter for r in self.running.values() if r.adapter >= 0}
        s.update(
            r.adapter for r in self.prefilling.values() if r.adapter >= 0
        )
        if extra >= 0:
            s.add(extra)
        return tuple(sorted(s))

    def _ensure_gathered(self, extra: int = -1) -> None:
        """Swap fresh compact (L, K, ...) adapter stacks under
        ``params["layers"]`` iff the batch's active set changed since
        the last gather. Pure device-to-device below the store (resident
        blocks are already in HBM); params keeps one static pytree
        structure, so no recompile — and since params is a jit ARGUMENT
        (never donated), an in-flight pipelined step still reads the
        stacks it dispatched with. Runs only from the invalidation-gated
        sel rebuilds, never per decode step."""
        active = self._active_adapters(extra)
        if active == self._lora_active:
            return
        t0 = time.perf_counter()
        leaves = self.adapter_store.gather(active, self.lora_slots)
        if self.mesh is not None:
            leaves = {k: self._dev(v) for k, v in leaves.items()}
        self.params = {
            **self.params,
            "layers": {**self.params["layers"], **leaves},
        }
        self._lora_active = active
        self._sel_cache = None  # positions remapped with the stacks
        self._gather_count += 1
        self._gather_s += time.perf_counter() - t0
        if self.metrics is not None:
            count = getattr(self.metrics, "on_adapter_gather", None)
            if count is not None:
                count()

    def _req_sel(self, req: _Request) -> "jax.Array | None":
        """(1, K|N) adapter one-hot for one request's prefill dispatches
        (None when this batcher serves no adapters). Gathered mode first
        ensures the compact stacks cover this request's adapter, then
        selects its COMPACT position — the dense path selects the
        registry index directly."""
        if self.adapter_store is None and not self.n_adapters:
            return None
        from k8s_gpu_device_plugin_tpu.models.lora_serving import one_hot_sel

        if self.adapter_store is not None:
            self._ensure_gathered(extra=req.adapter)
            n = self.lora_slots
            pos = (
                self._lora_active.index(req.adapter)
                if req.adapter >= 0 else -1
            )
        else:
            n, pos = self.n_adapters, req.adapter
        return self._dev(jnp.asarray(one_hot_sel(pos, n))[None, :])

    def _batch_sel(self) -> "jax.Array | None":
        """(n_slots, K|N) per-slot adapter one-hots for the decode step;
        cached until the running set changes (invalidated alongside
        ``_knobs_cache`` — same sites, same lifecycle). Empty slots read
        base-model zeros; their outputs are discarded anyway."""
        if self.adapter_store is None and not self.n_adapters:
            return None
        if self._sel_cache is None:
            from k8s_gpu_device_plugin_tpu.models.lora_serving import (
                one_hot_sel,
            )

            if self.adapter_store is not None:
                self._ensure_gathered()
                pos = {a: i for i, a in enumerate(self._lora_active)}
                arr = np.zeros((self.n_slots, self.lora_slots), np.float32)
                for slot, req in self.running.items():
                    if req.adapter >= 0:
                        arr[slot, pos[req.adapter]] = 1.0
            else:
                arr = np.zeros(
                    (self.n_slots, self.n_adapters), np.float32
                )
                for slot, req in self.running.items():
                    arr[slot] = one_hot_sel(req.adapter, self.n_adapters)
            self._sel_cache = self._dev(arr)
        return self._sel_cache

    def _count_adapter_deferral(self, reason: str) -> None:
        """adapter_miss (HBM residency upload in flight) or
        adapter_slots (more distinct adapters than K compact slots) —
        the adapter twins of ``pool_pressure``."""
        self._adapter_deferrals[reason] = (
            self._adapter_deferrals.get(reason, 0) + 1
        )
        if self.metrics is not None:
            count = getattr(self.metrics, "on_adapter_deferred", None)
            if count is not None:
                count(reason)

    def _admit_adapter(self, req: _Request) -> bool:
        """Adapter gate for one admission, the residency twin of
        ``_reserve_pages``: False defers the request at the queue head.
        Two transient causes: the compact stacks have no slot for a NEW
        distinct adapter (frees as its current holders retire), or the
        adapter is registered but not HBM-resident — the store starts
        the upload on a daemon thread and this admission pass moves on
        (the hot loop NEVER blocks on an adapter H2D; the request
        admits a pass or two later when the upload lands). Deferral
        counting dedupes per episode through ``defer_counted``, the
        same flag the scheduler's defer-budget expiry watches — an
        adapter-deferred request ages out into a 429 exactly like a
        pool-starved one."""
        if self.adapter_store is None or req.adapter < 0:
            return True
        if self._flt_adapter_upload is not None:
            try:
                self._flt_adapter_upload.fire()
            except self._fault_error:
                # injected residency miss: defer head-of-line exactly
                # like a real in-flight upload — admits when the
                # schedule relents
                if not req.defer_counted:
                    req.defer_counted = True
                    self._count_adapter_deferral("adapter_miss")
                return False
        active = self._active_adapters()
        if (req.adapter not in active
                and len(active) >= self.lora_slots):
            if not req.defer_counted:
                req.defer_counted = True
                self._count_adapter_deferral("adapter_slots")
            return False
        if not self.adapter_store.ensure_resident(req.adapter):
            if not req.defer_counted:
                req.defer_counted = True
                self._count_adapter_deferral("adapter_miss")
            return False
        req.defer_counted = False
        return True

    def _admit(self) -> None:
        if self.scheduler is not None and (self.pending or self.running):
            # one scheduling pass per admission pass: the policy may
            # reorder ``pending`` in place (the head IS the admission
            # order), expire over-budget pool-pressure deferrals, and
            # name at most one running slot to preempt for the head
            now = time.perf_counter()
            rejects, preempt_slot = self.scheduler.plan(self, now)
            for req in rejects:
                self.pending.remove(req)
                self._release_pinned(req)  # paged: match-time page pins
                req.reject_reason = "pool_pressure"
                self._retire_rejected(req, now)
            if preempt_slot is not None:
                self._preempt_slot(preempt_slot)
        free = [
            s for s in range(self.n_slots)
            if s not in self.running and s not in self.prefilling
        ]
        while free and self.pending:
            req = self.pending[0]
            if (self.chunk and req.prefix is None
                    and self.prefix_cache is not None
                    and len(req.prompt) > 1 and not req.matched
                    and req._kv_wire is None):
                # (a kv-transfer install skips matching outright: its
                # rows arrive materialized, so aliasing cached pages
                # under them would be pure bookkeeping with nothing to
                # save — and the install path owns the slot's presence)
                # THE automatic match site: at admission the request
                # is past validation and sees every prefix promoted
                # since it queued (a whole burst behind one system
                # prompt pays one prefill, not queue-depth), so the
                # hit/miss counters record one disposition per request
                # that reaches a slot (a paged pool deferral marks the
                # match done rather than re-counting; a cancel landing
                # in the deferral window releases the pins below).
                # It runs BEFORE the page reservation — the hit
                # decides how many pages alias vs allocate. The lookup
                # is UNCOUNTED here (count=False): a deferred request
                # can still be cancelled, and prometheus counters can't
                # take a phantom hit back — the disposition commits at
                # slot assignment below.
                req.matched = True
                t_match = (
                    time.perf_counter() if req.timeline is not None else 0.0
                )
                hit = self.prefix_cache.match(
                    req.prompt, req.adapter, count=False
                )
                if req.timeline is not None:
                    req.timeline.prefix_match_s += (
                        time.perf_counter() - t_match
                    )
                if hit is not None:
                    req.prefix, matched = hit
                    req._match_depth = matched
                    req.cached_tokens = self.prefix_cache.effective_reuse(
                        matched, len(req.prompt)
                    )
                    if isinstance(req.prefix, PagedPrefixState):
                        # pin the entry's pages NOW: an LRU eviction
                        # while this request waits for pool pressure
                        # must not free rows it is about to alias
                        pin = list(req.prefix.page_ids)
                        self.pool.incref(pin)
                        req._pinned_pages = pin
            if not self._admit_adapter(req):
                # head-of-line wait, the pool-pressure twin: the compact
                # stacks gain a slot as adapters retire, or the miss's
                # background upload lands — either way the next admission
                # pass re-polls. Runs BEFORE the page reservation so a
                # deferred request holds no fresh pages (match-time pins
                # stay; cancel releases them).
                break
            if self.pool is not None:
                t_pages = (
                    time.perf_counter() if req.timeline is not None else 0.0
                )
                reserved = self._reserve_pages(req)
                if req.timeline is not None:
                    req.timeline.page_alloc_s += (
                        time.perf_counter() - t_pages
                    )
                if not reserved:
                    break  # head-of-line wait: pages free as slots retire
            self.pending.pop(0)
            slot = free.pop(0)
            req.slot = slot
            if self.scheduler is not None:
                # commit point: the request has a slot — queue-wait and
                # WFQ virtual time charge land here, past every
                # cancellable wait (the record_match discipline)
                self.scheduler.on_admitted(req, self, time.perf_counter())
            if req.timeline is not None:
                # the attribution cursor leaves queue_wait exactly where
                # the admit span ends: slot assignment
                req.timeline.advance("prefill", time.perf_counter())
            if req.matched:
                # the request is past every cancellable wait: commit its
                # hit/miss disposition (one per request that reaches a
                # slot, the PR-3 contract)
                self.prefix_cache.record_match(
                    req._match_depth, len(req.prompt), req.adapter
                )
            if req.span is not None:
                # the admit span COVERS the queue wait: backdated to
                # submit time, ended at slot assignment
                self.tracer.span(
                    "admit", component="serving", parent=req.span,
                    t0=req.t_submit, slot=slot,
                ).end()
            if self.pool is not None:
                t_inst = (
                    time.perf_counter() if req.timeline is not None else 0.0
                )
                self._install_pages(req, slot)
                if req.timeline is not None:
                    req.timeline.page_alloc_s += (
                        time.perf_counter() - t_inst
                    )
            if self.chunk:
                start = 0
                if req._kv_wire is not None:
                    # disaggregated transfer: scatter the shipped pages
                    # into the fresh allocation and jump the chunk
                    # scheduler to the finish chunk — the only prefill
                    # dispatch this admission makes
                    t_inst = (
                        time.perf_counter()
                        if req.timeline is not None else 0.0
                    )
                    start = self.install_kv_pages(req, slot)
                    if req.timeline is not None:
                        req.timeline.page_alloc_s += (
                            time.perf_counter() - t_inst
                        )
                elif req.prefix is not None:
                    if self.pool is None:
                        # copy the shared rows + presence; suffix chunks
                        # continue from the prefix boundary (the paged
                        # twin already aliased in _install_pages — zero
                        # row copies)
                        self.state = _insert_prefix(
                            self.state, req.prefix.rows,
                            req.prefix.presence, jnp.int32(slot),
                        )
                        _KV_COPIES["rows"] += len(req.prefix.tokens)
                    start = len(req.prefix.tokens)
                    # cached_tokens is already the effective reuse, on
                    # both the manual and auto paths
                    self._count_prefill_tokens(
                        req.cached_tokens, "prefix_reused"
                    )
                self.prefilling[slot] = req
                self._prefill_pos[slot] = start
                self._on_prefill_scheduled(req, slot, start)
                continue
            bucket = _bucket(len(req.prompt), self.buckets)
            padded = jnp.asarray(
                req.prompt + [0] * (bucket - len(req.prompt)), jnp.int32
            )
            prefill_span = None
            if req.span is not None:
                prefill_span = self.tracer.span(
                    "prefill", component="serving", parent=req.span,
                    bucket=bucket, prompt_len=len(req.prompt),
                )
            try:
                # sel BEFORE params: the gathered-LoRA sel build may swap
                # fresh compact stacks under self.params, and Python
                # evaluates call arguments left to right — reading params
                # first would dispatch against the pre-gather tree
                sel = self._req_sel(req)
                self.state, tok, logp = prefill_insert(
                    self.params, self.state, padded,
                    jnp.int32(len(req.prompt)), jnp.int32(slot),
                    self.cfg, self._req_knobs(req),
                    jnp.int32(req.max_new), sel=sel,
                    bias=self._req_bias(req), seed=self._req_seed(req),
                )
                req.out.append(int(tok))  # device sync: prefill really done
                req.out_logp.append(float(logp))
            finally:  # a raised dispatch must not pin the trace open
                if prefill_span is not None:
                    prefill_span.end()
            self._count_prefill_tokens(len(req.prompt), "computed", req)
            self._on_first_token(req)
            self.running[slot] = req
            self._invalidate_slot_caches()
            self._finish_if_done(req)

    # --- paged-KV admission plumbing (no-ops on the dense layout) ---

    def _kv_need_tokens(self, prompt_len: int, max_new: int) -> int:
        """Worst-case cache rows one admission must cover — the paged
        reservation's denominator, shared by ``validate`` and
        ``_reserve_pages`` so submit-time refusal and admission-time
        deferral can never disagree. The speculative subclass adds its
        ``gamma`` verify window (each round may write that far past the
        accepted length)."""
        return prompt_len + max_new

    def _windowed_peak_tokens(self, max_new: int) -> int:
        """Upper bound on the token rows one windowed row has LIVE at
        any moment under incremental reservation: the trailing window,
        the admission tranche plus one in-flight chunk (recycling lags
        the cursor by the finish chunk's back-scheduled overlap), the
        larger of one chunk and the decode span (grown at the finish
        chunk, recycled down during decode), and two pages of boundary
        rounding. ``validate`` admits against this bound, so a deferred
        growth can always eventually succeed — the pool is provably big
        enough for the peak."""
        return (
            self.window
            + (self.reserve_chunks + 1) * self.chunk
            + max(self.chunk, max_new)
            + 2 * self.pool.page_size
        )

    def _initial_reserve_tokens(self, req: _Request) -> int:
        """The admission tranche for a windowed request: rows through
        the first ``reserve_chunks`` prefill chunks past the prefix
        match (the growth path backs the rest chunk by chunk). Short
        requests are covered whole — identical to the full reservation."""
        total = self._kv_need_tokens(
            len(req.prompt), req.max_new - req.prefilled_out
        )
        start = len(req.prefix.tokens) if req.prefix is not None else 0
        return min(total, start + self.reserve_chunks * self.chunk)

    def _outstanding_growth_pages(self) -> int:
        """Pages the in-flight windowed prefills may still draw before
        they peak — virtual headroom new admissions must not eat. Two
        long prompts admitted into one window's worth of free pages
        would starve each other forever (only the oldest mid-prefill
        slot advances, so neither could grow and nothing would retire);
        keeping the in-flight peaks admissible makes growth deferral
        transient by construction."""
        if not self._incremental_reserve:
            return 0
        out = 0
        for slot, req in self.prefilling.items():
            rem = req.max_new - req.prefilled_out
            peak = self.pool.pages_for_tokens(min(
                self._kv_need_tokens(len(req.prompt), rem),
                self._windowed_peak_tokens(rem),
            ))
            backed = sum(
                1 for p in (self._slot_pages.get(slot) or []) if p
            )
            out += max(0, peak - backed)
        return out

    def _reserve_pages(self, req: _Request) -> bool:
        """Pool-pressure check + reservation for one admission: aliased
        prefix pages are already pinned (match time), so only the COW
        tail and the fresh pages draw on the free list. False = defer
        (the request keeps its queue head; pages free as slots retire)."""
        if self._flt_pool_alloc is not None:
            try:
                self._flt_pool_alloc.fire()
            except self._fault_error:
                # injected TRANSIENT pool pressure: defer head-of-line
                # exactly like a real exhausted free list — the request
                # retries next step and admits when the schedule relents
                if not req.defer_counted:
                    req.defer_counted = True
                    self._count_kv_rejection("pool_pressure")
                return False
        ps = self.pool.page_size
        # a resumed request's prompt already CONTAINS its pre-preemption
        # output (prefilled_out tokens), so only the remaining budget
        # adds rows — the reservation is exactly the original worst case
        total = self.pool.pages_for_tokens(
            self._kv_need_tokens(
                len(req.prompt), req.max_new - req.prefilled_out
            )
        )
        if self._incremental_reserve and req._kv_wire is None:
            # windowed streaming prefill: reserve only the admission
            # tranche — _prefill_one_chunk grows the rest as the cursor
            # advances and recycling keeps the live span O(window).
            # (A KV-transfer install keeps the full reservation: its
            # rows arrive materialized, there is nothing to stream.)
            total = min(total, self.pool.pages_for_tokens(
                self._initial_reserve_tokens(req)
            ))
        # virtual headroom for in-flight windowed growth: counted
        # against the free list in every pressure check below, never
        # allocated here
        growth = self._outstanding_growth_pages()
        aliased = 0
        if isinstance(req.prefix, PagedPrefixState):
            # full shared pages alias; a partial tail still needs a
            # fresh page (the COW destination), so it stays in ``need``
            aliased = len(req.prefix.tokens) // ps
        need = total - aliased
        if need + growth > self.pool.free_pages and self.prefix_cache is not None:
            # Pool pressure: promoted prefixes are reclaimable capacity.
            # Evict LRU entries until the reservation fits or the cache
            # runs dry — otherwise entries pinning the last free pages
            # would defer this admission forever with every slot idle
            # (the dense layout would have admitted it). Pages an entry
            # shares with running slots or already-matched requests stay
            # allocated through their own refs; evicting those entries
            # may free nothing, so the loop walks deeper into the LRU —
            # but only when full reclamation COULD close the gap: pages
            # held by slots or queued requests' pins won't free no
            # matter how much cache is destroyed, and evicting every
            # prefix just to defer anyway would trade a working cache
            # for nothing (the request admits when a slot retires).
            held = set()
            for ids in self._slot_pages.values():
                held.update(ids)
            for r in self.pending:
                if r._pinned_pages:
                    held.update(r._pinned_pages)
            reclaimable = self.pool.in_use - len(held)
            evict_one = getattr(self.prefix_cache, "evict_one", None)
            if (evict_one is not None
                    and self.pool.free_pages + reclaimable >= need + growth):
                while need + growth > self.pool.free_pages and evict_one():
                    pass
        if (need > self.pool.free_pages and not self.running
                and not self.prefilling):
            # Futile-deferral escape: the server is IDLE, so no
            # retirement will ever grow the free list — waiting would
            # spin forever. What the valve above could not reclaim is
            # pinned by this very request (a matched prefix whose
            # partial tail page is pinned for the COW read while the
            # reservation also needs capacity the pin occupies — the
            # tight-pool corner the dense layout never hits). Fall back
            # to a COLD admission: drop the hit, release the pins (the
            # entry becomes evictable), and reclaim outright —
            # ``validate`` guaranteed the cold reservation fits the
            # pool, so this always terminates in an allocation.
            self._release_pinned(req)
            if isinstance(req.prefix, PagedPrefixState):
                req.prefix = None
                req._match_depth = None
                req.cached_tokens = 0
                need = total
            if self.prefix_cache is not None:
                evict_one = getattr(self.prefix_cache, "evict_one", None)
                if evict_one is not None:
                    while need > self.pool.free_pages and evict_one():
                        pass
        if need + growth > self.pool.free_pages:
            if not req.defer_counted:
                req.defer_counted = True
                self._count_kv_rejection("pool_pressure")
                if req.span is not None:
                    with attach(req.span):
                        get_logger().debug(
                            "admission deferred: KV pool pressure",
                            extra={"fields": {
                                "rid": req.rid, "need_pages": need,
                                "free_pages": self.pool.free_pages,
                            }},
                        )
            return False
        req.defer_counted = False
        req._new_pages = self.pool.alloc(need)
        return True

    def _install_pages(self, req: _Request, slot: int) -> None:
        """Upload the slot's page-table row (aliased + COW + fresh) and
        perform the prefix insert for the paged layout: an automatic hit
        is pure table aliasing (plus at most ONE tail-page copy-on-write
        when the boundary is not page-aligned); a manual dense prefix
        scatters its rows into the fresh pages."""
        assert slot not in self._slot_pages, "slot pages leaked"
        ps = self.pool.page_size
        new = req._new_pages or []
        req._new_pages = None
        shared: list[int] = []
        cow_pair = None
        if isinstance(req.prefix, PagedPrefixState):
            m = len(req.prefix.tokens)
            full = m // ps
            # the match site pinned these pages (and submit refuses a
            # manual PagedPrefixState), so they cannot have been evicted
            # and reallocated by _reserve_pages' pressure relief
            pinned = req._pinned_pages
            assert pinned is not None, "paged prefix reached install unpinned"
            req._pinned_pages = None
            shared = pinned[:full]  # the match-time pins transfer here
            if m % ps:
                cow_pair = (pinned[full], new[0])
        row_ids = shared + new
        row = np.zeros((self.state.pages.shape[1],), np.int32)
        row[: len(row_ids)] = row_ids
        self._slot_pages[slot] = row_ids
        if isinstance(req.prefix, PagedPrefixState):
            self.state = _alias_slot_pages(
                self.state, jnp.asarray(row), req.prefix.presence,
                jnp.int32(slot),
            )
            if cow_pair is not None:
                src, dst = cow_pair
                self.state = _copy_page(
                    self.state, jnp.int32(src), jnp.int32(dst)
                )
                _KV_COPIES["cow_pages"] += 1
                # the tail pin served only the COW read; the slot owns
                # its private copy now
                self.pool.decref([src])
            if self.tracer.enabled and req.span is not None:
                self.tracer.span(
                    "prefix_alias", component="serving", parent=req.span,
                    pages=len(shared), cow=int(cow_pair is not None),
                    matched=len(req.prefix.tokens),
                ).end()
        elif req.prefix is not None:
            # manual (dense-rows) prefix into a paged slot: a real row
            # copy, counted as such — only the automatic cache aliases
            self.state = _set_slot_pages(
                self.state, jnp.asarray(row), jnp.int32(slot)
            )
            self.state = _insert_prefix_rows_paged(
                self.state, req.prefix.rows, req.prefix.presence,
                jnp.int32(slot),
            )
            _KV_COPIES["rows"] += len(req.prefix.tokens)
        else:
            self.state = _set_slot_pages(
                self.state, jnp.asarray(row), jnp.int32(slot)
            )
        if self.tracer.enabled and req.span is not None:
            self.tracer.span(
                "page_alloc", component="serving", parent=req.span,
                pages=len(new), aliased=len(shared),
                free=self.pool.free_pages,
            ).end()
            with attach(req.span):
                get_logger().debug(
                    "kv pages allocated",
                    extra={"fields": {
                        "rid": req.rid, "slot": slot, "pages": len(new),
                        "aliased": len(shared),
                        "free_pages": self.pool.free_pages,
                    }},
                )
        self._report_kv_gauges()

    # --- incremental reservation + out-of-window recycling (windowed) ---

    def _grow_slot_pages(self, slot: int, req: _Request,
                         upto_tokens: int) -> bool:
        """Extend ``slot``'s page-table row so positions
        ``[0, upto_tokens)`` are backed by real pages. The growth half
        of incremental reservation: host free-list math plus ONE
        admission-style row upload per chunk — never called from the
        decode hot path. Returns False on pool pressure (nothing
        allocated; the caller defers the CHUNK and retries next step —
        the request keeps its slot, its cursor, and every page grown so
        far)."""
        ids = self._slot_pages[slot]
        grow = self.pool.pages_for_tokens(upto_tokens) - len(ids)
        if grow <= 0:
            return True
        if self._flt_pool_alloc is not None:
            try:
                self._flt_pool_alloc.fire()
            except self._fault_error:
                # injected TRANSIENT pool pressure mid-prompt: defer the
                # next chunk exactly like a real exhausted free list
                self._count_chunk_deferral(req)
                return False
        if grow > self.pool.free_pages and self.prefix_cache is not None:
            # the admission-time pressure valve, mid-prompt: promoted
            # prefixes are reclaimable capacity
            evict_one = getattr(self.prefix_cache, "evict_one", None)
            if evict_one is not None:
                while grow > self.pool.free_pages and evict_one():
                    pass
        if grow > self.pool.free_pages:
            self._count_chunk_deferral(req)
            if req.span is not None:
                with attach(req.span):
                    get_logger().debug(
                        "prefill chunk deferred: KV pool pressure",
                        extra={"fields": {
                            "rid": req.rid, "need_pages": grow,
                            "free_pages": self.pool.free_pages,
                        }},
                    )
            return False
        new = self.pool.alloc(grow)
        self._slot_pages[slot] = ids = ids + new
        row = np.zeros((self.state.pages.shape[1],), np.int32)
        row[: len(ids)] = ids  # recycled entries stay 0 (the trap page)
        self.state = _set_slot_pages(
            self.state, jnp.asarray(row), jnp.int32(slot)
        )
        if self.tracer.enabled and req.span is not None:
            self.tracer.span(
                "page_grow", component="serving", parent=req.span,
                pages=grow, free=self.pool.free_pages,
            ).end()
        self._report_kv_gauges()
        return True

    def _recycle_slot_pages(self, slot: int, pos: int) -> None:
        """Free pages no FUTURE query of this row can attend: queries at
        positions >= ``pos`` reach keys in ``(q - window, q]``, so a
        page whose last position is <= ``pos - window`` is dead. Pure
        host free-list math — no device work: the windowed kernel's DMA
        lo-clamp never fetches blocks below the window, the XLA gather
        masks those rows to exact-zero softmax weight, and no write ever
        targets a passed position, so the stale table entries are
        unreachable by construction (a freed page reallocated to another
        slot can never be scribbled on or observed through this row)."""
        if not self._incremental_reserve:
            return
        ids = self._slot_pages.get(slot)
        if not ids:
            return
        ps = self.pool.page_size
        # page k spans [k*ps, (k+1)*ps); dead iff (k+1)*ps <= pos-W+1
        dead = min(max(0, (pos - self.window + 1) // ps), len(ids))
        lo = self._recycle_lo.get(slot, 0)
        if dead <= lo:
            return
        batch = []
        for k in range(lo, dead):
            p = ids[k]
            if p:
                ids[k] = 0
                batch.append(p)
        self._recycle_lo[slot] = dead
        if batch:
            # pool.recycle reports pages actually FREED — a
            # prefix-shared page only drops this row's reference and
            # stays live for its other holders
            freed = self.pool.recycle(batch)
            self._pages_recycled += freed
            if freed and self.metrics is not None:
                count = getattr(self.metrics, "on_kv_pages_recycled", None)
                if count is not None:
                    count(freed)
            self._report_kv_gauges()

    def _count_chunk_deferral(self, req: _Request) -> None:
        self._chunks_deferred += 1
        if self.metrics is not None:
            count = getattr(self.metrics, "on_prefill_chunk_deferred", None)
            if count is not None:
                count("pool_pressure")

    # --- KV page transfer (disaggregated prefill/decode) ---

    def export_kv_pages(self, rid: int) -> "tuple[dict, list, list]":
        """Export a decoding request's materialized cache pages as a
        self-describing wire blob (the prefill replica's half of a
        disaggregated transfer, serving/router.py). Returns
        ``(blob, out, out_logp)``: the blob plus a CONSISTENT snapshot
        of the tokens emitted so far — exactly the ``resume_out`` /
        ``resume_logprobs`` the resubmission needs. The request keeps
        decoding here until the caller cancels it (the serving engine's
        export op does snapshot + cancel back-to-back on the engine
        thread, so nothing can interleave).

        Only pages holding VALID rows ship: ``lengths[slot]`` rows =
        folded prompt + emitted - 1. The newest emitted token's K/V row
        does not exist yet (the next decode step would write it) — it
        rides ``out`` instead, becoming the last resumed token, whose
        row the importer's finish chunk writes. Raises KeyError for an
        unknown/finished rid, ValueError for the dense layout or a
        request still prefilling."""
        if self.pool is None:
            raise ValueError(
                "KV page export requires the paged layout "
                "(kv_layout='paged' / --kvLayout paged); this replica "
                "serves dense KV — resume with re-prefill instead"
            )
        if self._inflight is not None:
            # the snapshot must include every dispatched emission, or
            # the blob's row count and ``out`` would disagree
            self._flush_inflight()
        req = None
        for slot, r in self.running.items():
            if r.rid == rid:
                req = r
                break
        if req is None:
            waiting = [r.rid for r in self.pending] + [
                r.rid for r in self.prefilling.values()
            ]
            if rid in waiting:
                raise ValueError(
                    f"request {rid} has not finished prefill; KV pages "
                    "export only after the first emitted token"
                )
            raise KeyError(f"unknown or finished request {rid}")
        valid = len(req.prompt) + len(req.out) - req.prefilled_out - 1
        n = self.pool.pages_for_tokens(valid)
        ids_host = self._slot_pages[slot][:n]
        if len(ids_host) < n or any(p == 0 for p in ids_host):
            # windowed rows recycle out-of-window pages mid-flight: the
            # early rows no longer exist anywhere, so a full-row export
            # cannot be assembled — the caller degrades to re-prefill
            # (the standing hop-failure fallback)
            raise ValueError(
                f"request {rid}'s early KV pages were recycled "
                "(attn_window serving): export cannot ship the full "
                "row — resume with re-prefill instead"
            )
        ids = jnp.asarray(np.asarray(ids_host, np.int32))
        planes = {}
        with self._dispatch_scope():
            for name in ("k", "v", "k_scale", "v_scale"):
                leaf = getattr(self.state.cache, name)
                if leaf is not None:
                    planes[name] = np.asarray(jax.device_get(leaf[:, ids]))
        blob = pack_kv_wire(
            planes, page_size=self.pool.page_size,
            cache_quant=self.cfg.cache_quant, tokens=valid,
        )
        if self.tracer.enabled and req.span is not None:
            self.tracer.span(
                "kv_export", component="serving", parent=req.span,
                pages=n, tokens=valid,
            ).end()
        return blob, list(req.out), list(req.out_logp)

    def install_kv_pages(self, req: _Request, slot: int) -> int:
        """Install a transferred wire blob into ``slot``'s freshly
        allocated pages (the decode replica's half of a disaggregated
        transfer; ``_admit`` calls this right after ``_install_pages``).
        The pages are brand-new allocations at refcount 1 — scattering
        rows into them can touch neither shared pages nor the trap
        page, so refcount/COW/trap semantics are exactly the cold
        admission's. Presence is seeded host-side from the folded
        prompt's token ids (a pure function of them — identical to what
        the skipped chunks would have accumulated). Returns the chunk
        scheduler's start position: the finish chunk becomes the ONLY
        prefill dispatch — it rewrites its overlap window (identical
        K/V, the standing chunk-overlap argument), writes the one row
        the export could not carry, and samples emission number
        ``prefilled_out`` exactly like a PR-14 re-prefill resume, so
        greedy and seeded streams stay bit-identical to single-replica
        serving."""
        meta, wire_planes = req._kv_wire
        req._kv_wire = None
        n = int(meta["n_pages"])
        ids = jnp.asarray(np.asarray(self._slot_pages[slot][:n], np.int32))
        wire = KVCache(
            k=wire_planes["k"], v=wire_planes["v"],
            k_scale=wire_planes.get("k_scale"),
            v_scale=wire_planes.get("v_scale"),
        )
        seen = np.zeros((self.state.presence.shape[1],), bool)
        seen[list(set(req.prompt))] = True
        self.state = _install_wire_pages(
            self.state, wire, ids, jnp.asarray(seen), jnp.int32(slot)
        )
        plen = len(req.prompt)
        start = max(0, plen - self.chunk)
        # rows the transfer served in place of prefill compute: a new
        # provenance label beside computed/prefix_reused (the finish
        # chunk's window still counts as computed — it really runs)
        self._count_prefill_tokens(start, "kv_installed")
        if self.tracer.enabled and req.span is not None:
            self.tracer.span(
                "kv_install", component="serving", parent=req.span,
                pages=n, tokens=int(meta["tokens"]), start=start,
            ).end()
        return start

    def _release_slot_pages(self, slot: int, req: "_Request | None" = None
                            ) -> None:
        """Drop the slot's page references at retirement; pages shared
        with the prefix cache (or other slots) survive until their last
        holder lets go."""
        if self.pool is None:
            return
        self._recycle_lo.pop(slot, None)
        ids = self._slot_pages.pop(slot, None)
        if not ids:
            return
        # recycled entries are 0 (already freed mid-flight): exactly the
        # grown-minus-recycled remainder returns here, the PR-6 leak pin
        freed = self.pool.decref([p for p in ids if p])
        if self.tracer.enabled:
            span = req.span if req is not None else None
            self.tracer.span(
                "page_free", component="serving", parent=span,
                pages=len(ids), freed=len(freed),
                free=self.pool.free_pages,
            ).end()
            if span is not None:
                with attach(span):
                    get_logger().debug(
                        "kv pages released",
                        extra={"fields": {
                            "slot": slot, "pages": len(ids),
                            "freed": len(freed),
                        }},
                    )
        self._report_kv_gauges()

    def _release_pinned(self, req: _Request) -> None:
        """A request cancelled while still pending may hold match-time
        page pins; give them back."""
        if self.pool is not None and req._pinned_pages:
            self.pool.decref(req._pinned_pages)
            req._pinned_pages = None

    def _count_kv_rejection(self, reason: str) -> None:
        if self.metrics is not None:
            count = getattr(self.metrics, "on_kv_admission_rejected", None)
            if count is not None:
                count(reason)

    def _report_kv_gauges(self) -> None:
        if self.metrics is None:
            return
        self._report_kv_shard_gauges()
        if self.pool is None:
            return
        set_pages = getattr(self.metrics, "set_kv_pages", None)
        if set_pages is not None:
            s = self.kv_stats()
            set_pages(s["pages_total"], s["pages_in_use"],
                      s["fragmentation_pct"])

    def _report_kv_shard_gauges(self) -> None:
        """Per-shard KV gauges (tp>1 only — the tp=1 gauge surface is
        byte-identical to the pre-tp server, for comparability)."""
        if self.metrics is None or self.cfg.tp <= 1:
            return
        set_shards = getattr(self.metrics, "set_kv_shards", None)
        if set_shards is not None:
            set_shards(self.kv_stats().get("shards", []))

    def _kv_shard_view(self, out: dict) -> dict:
        """Append the per-shard view to a kv_stats dict under tp>1: one
        entry per tensor-parallel shard, each holding its slice of every
        page/row (page COUNTS are identical across shards by design —
        one replicated host-side table — while the BYTES behind them
        split by tp). tp=1 returns ``out`` untouched: the health surface
        stays byte-comparable with the single-chip server."""
        if self.cfg.tp <= 1:
            return out
        per = kv_shard_token_bytes(self.cfg)
        shards = []
        for i in range(self.cfg.tp):
            s: dict = {"shard": i}
            if self.devices is not None:
                # shard -> physical chip (device/allocation.py): names
                # the silicon behind each tp slice on /v1/health and the
                # kv_shard chip-mapping gauge
                chip = self.devices.shard_chip(i)
                if chip is not None:
                    s["chip"] = chip
            if self.pool is None:
                s["reserved_bytes"] = self.n_slots * self.max_len * per
            else:
                s["reserved_bytes"] = (
                    self.pool.n_pages * self.pool.page_size * per
                )
                s["in_use_bytes"] = (
                    self.pool.in_use * self.pool.page_size * per
                )
                s["pages_total"] = self.pool.capacity
                s["pages_in_use"] = self.pool.in_use
                s["pages_free"] = self.pool.free_pages
            shards.append(s)
        out["tp"] = self.cfg.tp
        out["shards"] = shards
        return out

    def kv_stats(self) -> dict:
        """KV residency for /v1/health and the gauges — both layouts
        report ``reserved_bytes`` (the static HBM the cache arrays hold)
        so dense and paged are directly comparable; paged adds the pool
        occupancy and internal fragmentation (allocated page capacity
        not covered by live tokens — tail-page waste plus pages pinned
        by promoted prefixes). Under tensor-parallel serving (tp>1) a
        ``shards`` list reports each shard's slice alongside the
        aggregates; at tp=1 the dict is exactly the pre-tp one. Always a
        SNAPSHOT built from engine-owned state (the thread-ownership
        contract: /v1/health reads this cross-thread)."""
        tb = kv_token_bytes(self.cfg)
        # attn_window only when windowed: at window=0 the surface stays
        # BYTE-identical to the pre-feature server (the comparability
        # pin in test_tp_serving — same rule as the tp/shards keys)
        windowed = {"attn_window": self.window} if self.window else {}
        if self.pool is None:
            return self._kv_shard_view({
                "layout": "dense",
                **windowed,
                "reserved_bytes": self.n_slots * self.max_len * tb,
            })
        # list() snapshots before iterating: /v1/health calls this from
        # the HTTP thread while the engine thread admits/retires, and a
        # mid-generator dict mutation raises RuntimeError (the same
        # approximate-read contract as stats()'s atomic len() calls)
        live = sum(
            # resumed requests: prompt already holds prefilled_out of
            # the out tokens — don't count those rows twice
            len(r.prompt) + len(r.out) - r.prefilled_out
            for r in list(self.running.values())
        ) + sum(self._prefill_pos.get(s, 0) for s in list(self.prefilling))
        cap_tokens = self.pool.in_use * self.pool.page_size
        return self._kv_shard_view({
            "layout": "paged",
            **windowed,
            "page_size": self.pool.page_size,
            "pages_total": self.pool.capacity,
            "pages_in_use": self.pool.in_use,
            "pages_free": self.pool.free_pages,
            "pages_recycled_total": self._pages_recycled,
            "fragmentation_pct": (
                100.0 * (1.0 - min(live, cap_tokens) / cap_tokens)
                if cap_tokens else 0.0
            ),
            "reserved_bytes": self.pool.n_pages * self.pool.page_size * tb,
            "in_use_bytes": cap_tokens * tb,
        })

    def attn_backend_stats(self) -> dict:
        """The static attention-backend plan for /v1/health — which of
        decode / verify / prefill route to the Pallas kernel vs the XLA
        gather, with the dispatch gate that decided it. A copy of the
        startup plan (the plan itself never mutates; health serializers
        may), so this is trivially safe cross-thread."""
        return {m: dict(d) for m, d in self.attn_plan.items()}

    # --- adapter registry (gathered multi-LoRA; engine thread) -----------

    def register_adapter(self, name: str, lora_params, lora_cfg) -> int:
        """Dynamically add an adapter to the store (engine thread — the
        serving engine routes control-plane calls through its admission
        queue). Returns the new registry index. Residency follows the
        store's budget policy: room (or no budget) uploads now,
        otherwise first use pays one deferred admission."""
        if self.adapter_store is None:
            raise ValueError(
                "this batcher serves a static AdapterSet (or none); "
                "dynamic registration needs gathered mode (an "
                "AdapterStore, lora_slots > 0)"
            )
        return self.adapter_store.register(name, lora_params, lora_cfg)

    def unregister_adapter(self, name: str) -> int:
        """Remove ``name`` from the registry AND evict its prefix-cache
        root: an unregistered adapter can never match again, so its
        cached K/V (pages under the paged layout) is dead weight that
        would otherwise linger until LRU pressure. Refuses while
        requests for it are live (queued, prefilling, or decoding) —
        the compact stacks a dispatch is using must stay truthful."""
        if self.adapter_store is None:
            raise ValueError(
                "this batcher serves a static AdapterSet (or none); "
                "unregistration needs gathered mode"
            )
        idx = self.adapter_store.index_of(name)
        live = idx in self._active_adapters() or any(
            r.adapter == idx for r in self.pending
        )
        if live:
            raise ValueError(
                f"adapter {name!r} has live requests; drain them first"
            )
        # refresh the gather (and the store's protected set) to the true
        # active set — it may be stale if every holder retired and no
        # dispatch has rebuilt sel since
        self._ensure_gathered()
        self.adapter_store.unregister(name)
        if self.prefix_cache is not None:
            evict = getattr(self.prefix_cache, "evict_adapter", None)
            if evict is not None:
                evict(idx)
        return idx

    def adapter_stats(self) -> "dict | None":
        """Adapter-serving snapshot for /v1/health and the serve row
        (None when this batcher serves no adapters). Cross-thread safe:
        the store snapshots under its lock; the gather counters ride
        the kv_stats approximate-read contract."""
        if self.adapter_store is None:
            if not self.n_adapters:
                return None
            return {
                "mode": "dense",
                "registered": self.n_adapters,
                "resident": self.n_adapters,
            }
        out = self.adapter_store.stats()
        out.update(
            mode="gathered",
            lora_slots=self.lora_slots,
            active=len(self._lora_active),
            gathers=self._gather_count,
            gather_ms_total=round(self._gather_s * 1e3, 3),
            deferrals=dict(self._adapter_deferrals),
        )
        return out

    def precompute_shared_prefix(self, tokens, adapter: int = -1):
        """:func:`precompute_prefix` against THIS batcher's params — the
        only safe entry under gathered serving, where the module
        function's ``adapter`` (a registry id) is not the position
        inside the compact stacks: this method makes the adapter
        resident (a SYNC upload — control-plane work, not the admission
        path), gathers it in, and passes the remapped ``sel_index``.
        Dense/static batchers just forward."""
        if adapter >= 0:
            self.validate_adapter(adapter)
        buckets = self.buckets or DEFAULT_PROMPT_BUCKETS
        if self.adapter_store is None:
            return precompute_prefix(
                self.params, tokens, self.cfg, adapter=adapter,
                n_adapters=self.n_adapters, prompt_buckets=buckets,
            )
        pos = None
        if adapter >= 0:
            self.adapter_store.make_resident(adapter)
            self._ensure_gathered(extra=adapter)
            pos = self._lora_active.index(adapter)
        return precompute_prefix(
            self.params, tokens, self.cfg, adapter=adapter,
            n_adapters=self.lora_slots, prompt_buckets=buckets,
            sel_index=pos,
        )

    def _prefill_one_chunk(self) -> None:
        """Advance the oldest mid-prefill request by one chunk; on its
        final chunk, sample the first token and move it to running."""
        if not self.prefilling:
            return
        if self._flt_prefill is not None:
            self._flt_prefill.fire()  # induced prefill-dispatch crash
        slot = next(iter(self.prefilling))
        req = self.prefilling[slot]
        start = self._prefill_pos[slot]
        c = self.chunk
        plen = len(req.prompt)
        if self.pool is not None:
            # incremental reservation (windowed rows): back the pages
            # this chunk writes — plus the decode span on the finish
            # chunk — BEFORE dispatching. Pool pressure defers the
            # CHUNK, never the request: it keeps its slot, its cursor,
            # and every page grown so far, and retries next step (pages
            # free as slots retire and as recycling runs). Fully
            # reserved rows (window off, short prompts, KV installs)
            # are already backed, so this is a no-op compare for them.
            upto = (start + c if start + c < plen
                    else plen + req.max_new - req.prefilled_out)
            if not self._grow_slot_pages(slot, req, upto):
                return
        if start + c < plen:  # intermediate chunk, all real tokens
            chunk = jnp.asarray(req.prompt[start:start + c], jnp.int32)
            chunk_span = None
            if req.span is not None:
                chunk_span = self.tracer.span(
                    "prefill_chunk", component="serving", parent=req.span,
                    start=start, tokens=c,
                )
            t_chunk = (
                time.perf_counter() if req.timeline is not None else 0.0
            )
            try:
                self._apply_prefill_chunk(chunk, start, slot)
            finally:
                if chunk_span is not None:
                    chunk_span.end()
            if req.timeline is not None:
                now = time.perf_counter()
                req.timeline.add_chunk(now, now - t_chunk)
            self._prefill_pos[slot] = start + c
            # recycle behind the cursor — floored at the finish chunk's
            # back-scheduled start (plen - c): its overlap window
            # REWRITES those rows and its queries attend them, so pages
            # under it must stay live until the finish chunk runs
            self._recycle_slot_pages(
                slot, min(start + c, max(plen - c, 0))
            )
            self._count_prefill_tokens(c, "computed", req)
            if self.metrics:
                self.metrics.on_prefill_chunk()
            return
        # finish chunk: scheduled at plen - C (all real tokens; the
        # overlap with the last intermediate chunk rewrites identical
        # K/V) so its write window always fits max_len — forward padding
        # could straddle it and dynamic_update_slice would silently
        # clamp-shift the rows. Prompts < C pad at the tail instead.
        fstart = max(0, plen - c)
        rest = req.prompt[fstart:]
        chunk = jnp.asarray(rest + [0] * (c - len(rest)), jnp.int32)
        finish_span = None
        if req.span is not None:
            finish_span = self.tracer.span(
                "prefill_chunk", component="serving", parent=req.span,
                start=fstart, tokens=c, final=True,
            )
        t_chunk = time.perf_counter() if req.timeline is not None else 0.0
        try:
            tok, logp = self._apply_prefill_finish(chunk, fstart, plen, slot)
        finally:
            if finish_span is not None:
                finish_span.end()
        if req.timeline is not None:
            now = time.perf_counter()
            req.timeline.add_chunk(now, now - t_chunk)
        del self.prefilling[slot], self._prefill_pos[slot]
        self._count_prefill_tokens(plen - fstart, "computed", req)
        req.out.append(int(tok))
        req.out_logp.append(float(logp))
        self._on_first_token(req)
        self.running[slot] = req
        self._invalidate_slot_caches()
        # decode queries start at plen: everything below plen - window
        # is now dead (promotion below sees the recycled row and skips —
        # the early rows a boundary would cache no longer exist)
        self._recycle_slot_pages(slot, plen)
        self._maybe_promote_prefix(req)
        self._finish_if_done(req)

    def _count_prefill_tokens(self, n: int, source: str,
                              req: "_Request | None" = None) -> None:
        """Prefill work accounting by provenance: ``computed`` tokens ran
        through the model (chunk overlap recompute included — it is real
        compute), ``prefix_reused`` tokens were copied from prefilled
        prefix rows. Duck-typed like the other optional metric hooks.
        ``req`` attributes computed tokens to the request so the MFU
        layer's retirement charge matches what actually ran."""
        if self.metrics is not None and n > 0:
            count = getattr(self.metrics, "on_prefill_tokens", None)
            if count is not None:
                count(n, source)
        if n > 0 and source == "computed":
            if req is not None:
                req.prefill_computed += n
            if self.mfu is not None:
                # only COMPUTED tokens moved FLOPs; prefix-reused rows
                # cost nothing (that is the cache's point)
                self.mfu.on_prefill_tokens(n)

    def _maybe_promote_prefix(self, req: _Request) -> None:
        """The promotion hook: a completed chunked prefill offers its
        full prompt back to the prefix cache, which decides which
        ``prompt_buckets`` boundaries to materialize (hit-count policy,
        HBM byte budget) and pulls each boundary's rows straight out of
        the slot via :func:`extract_prefix_rows` — the slot holds the
        whole prompt's K/V at this moment regardless of how much of it
        came from a matched prefix."""
        if self.prefix_cache is None:
            return
        if self._flt_promote is not None:
            self._flt_promote.fire()  # induced promotion crash
        if self.pool is not None:
            # ZERO-COPY promotion: the boundary's rows already live in
            # the slot's pages — take a reference on each page the
            # boundary spans and hand the ids to the cache (the bound
            # entry_factory wraps them in a PagedPrefixState). No device
            # work at all, vs one row-slice compile per boundary dense.
            slot_pages = self._slot_pages[req.slot]
            if any(p == 0 for p in slot_pages):
                # windowed prefill recycled out-of-window pages: the
                # prompt's early rows are gone, so no boundary below the
                # window is materializable — nothing cacheable here
                return

            def extract(p: int):
                # nothing between the incref and the return: a call in
                # that window (the gauge push used to sit here) could
                # raise before the cache records the page refs, leaking
                # them — graftlint's refcount-pairing rule
                ids = tuple(slot_pages[: self.pool.pages_for_tokens(p)])
                self.pool.incref(ids)
                return ids

            self.prefix_cache.on_prefill_done(
                req.prompt, req.adapter, extract
            )
            # gauges once per promotion pass (not per boundary), after
            # every extracted boundary's refs are owned by cache entries
            self._report_kv_gauges()
            return
        slot = jnp.int32(req.slot)

        def extract_dense(p: int):
            _KV_COPIES["rows"] += p
            return extract_prefix_rows(self.state, slot, p)

        self.prefix_cache.on_prefill_done(
            req.prompt, req.adapter, extract_dense
        )

    def _on_first_token(self, req: _Request) -> None:
        """First generated token (sampled at prefill time): TTFT metric +
        the request's decode-phase span opens. A RESUMED request's
        finish-chunk token is a real emission (counted) but not a first
        token — its TTFT was observed at the original admission."""
        now = time.perf_counter()
        req.t_last_tok = now
        if self.metrics:
            self.metrics.on_first_token()
            if req.preemptions == 0:
                observe = getattr(self.metrics, "observe_ttft", None)
                if observe is not None:  # duck-typed: fakes may lack it
                    if req.timeline is not None and getattr(
                        self.metrics, "supports_exemplars", False
                    ):
                        # the TTFT bucket carries a trace-id exemplar so
                        # a histogram spike pivots to a concrete request
                        observe(now - req.t_submit, req.timeline.xid)
                    else:
                        observe(now - req.t_submit)
        if req.preemptions == 0:
            req.t_first_tok = now
        if req.timeline is not None:
            # TTFT ends here: the prefill segment closes, decode opens
            req.timeline.advance("decode", now)
        if req.span is not None:
            req.decode_span = self.tracer.span(
                "decode", component="serving", parent=req.span,
            )

    def _close_request_spans(self, req: _Request, reason: str) -> None:
        """Retire the request's span tree: decode phase ends, a retire
        marker lands, the root closes (completing the trace)."""
        if req.span is None:
            return
        if req.decode_span is not None:
            req.decode_span.set(tokens=len(req.out)).end()
            req.decode_span = None
        self.tracer.span(
            "retire", component="serving", parent=req.span, reason=reason,
        ).end()
        with attach(req.span):  # the log line carries the trace ids
            get_logger().debug(
                "request retired",
                extra={"fields": {"rid": req.rid, "reason": reason,
                                  "tokens": len(req.out)}},
            )
        req.span.set(reason=reason, tokens=len(req.out)).end()
        req.span = None

    # overridable seams (the speculative batcher mirrors these onto a
    # second, draft-model state)

    def _on_prefill_scheduled(self, req: _Request, slot: int,
                              start: int) -> None:
        """A chunked prefill was just scheduled for ``slot``, continuing
        from ``start`` (> 0 iff a prefix served rows [0, start)). Base:
        nothing to do. The speculative batcher backfills its draft cache
        here — the prefix rows the target aliased were never run through
        the draft model, so the draft cheaply re-prefills them."""

    def _apply_prefill_chunk(self, chunk, start: int, slot: int) -> None:
        # sel before params: the gathered-LoRA sel build may swap the
        # compact stacks under self.params (argument-evaluation order)
        sel = self._req_sel(self.prefilling[slot])
        self.state = prefill_chunk(
            self.params, self.state, chunk,
            jnp.int32(start), jnp.int32(slot), self.cfg,
            sel=sel,
        )

    def _apply_prefill_finish(self, chunk, fstart: int, plen: int,
                              slot: int) -> tuple[int, float]:
        req = self.prefilling[slot]
        # a resumed request (preempted mid-decode) already emitted
        # prefilled_out tokens — they sit in the prompt now, so the
        # finish chunk samples emission number prefilled_out (the same
        # seeded draw index the dropped decode would have used) against
        # the REMAINING budget; prefilled_out == 0 keeps today's trace
        # sel before params: the gathered-LoRA sel build may swap the
        # compact stacks under self.params (argument-evaluation order)
        sel = self._req_sel(req)
        self.state, tok, logp = prefill_finish(
            self.params, self.state, chunk, jnp.int32(fstart),
            jnp.int32(plen), jnp.int32(slot),
            self.cfg, self._req_knobs(req),
            jnp.int32(req.max_new - req.prefilled_out),
            sel=sel,
            bias=self._req_bias(req),
            seed=self._req_seed(req),
            draw0=(
                jnp.int32(req.prefilled_out) if req.prefilled_out else None
            ),
        )
        return int(tok), float(logp)

    def _attr_retired(self, req: _Request, reason: str) -> None:
        """Attribution + MFU wrap-up for one retired request — all three
        retirement paths (finish, cancel, reject) funnel here after
        ``t_done`` is set. The deadline disposition mirrors the
        scheduler's goodput rule so tokens-per-TFLOP is a goodput
        ratio, not a raw-throughput one."""
        if self.attribution is None and self.mfu is None:
            return
        missed = req.deadline is not None and req.t_done > req.deadline
        if self.mfu is not None:
            goodput = (
                0 if (missed or reason in ("cancelled", "rejected"))
                else len(req.out)
            )
            self.mfu.on_retired(req, goodput)
        if self.attribution is not None and req.timeline is not None:
            self.attribution.on_retired(
                req, reason, req.t_done, deadline_missed=missed
            )

    def attribution_stats(self) -> "dict | None":
        """Cross-thread snapshot of the attribution layer's COUNTERS
        (None when disabled) — the kv_stats()/sched_stats() contract.
        The timeline payloads stay behind the /debug endpoints'
        request_stats()/slow_stats(): health polls must not pay for
        copying 256 timeline dicts they discard."""
        if self.attribution is None:
            return None
        return self.attribution.count_stats()

    def mfu_stats(self) -> "dict | None":
        """Cross-thread snapshot of the live MFU/roofline accounting
        (None when disabled)."""
        if self.mfu is None:
            return None
        return self.mfu.mfu_stats()

    def cancel(self, rid: int) -> bool:
        """Retire ``rid`` wherever it lives — pending, mid-prefill, or
        decoding — freeing its slot for the next admission; tokens
        generated so far are recorded under ``done``. Returns False for
        unknown or already-finished rids (idempotent): a serving client
        that disconnects must not keep its slot decoding to the token
        budget, and a double-cancel must be harmless."""
        for i, req in enumerate(self.pending):
            if req.rid == rid:
                self.pending.pop(i)
                self._release_pinned(req)  # paged: match-time page pins
                self._retire_cancelled(req)
                return True
        for mapping in (self.prefilling, self.running):
            for slot, req in list(mapping.items()):
                if req.rid == rid:
                    del mapping[slot]
                    self._prefill_pos.pop(slot, None)
                    self._invalidate_slot_caches()
                    self._release_slot_pages(slot, req)
                    self._retire_cancelled(req)
                    return True
        return False

    def _retire_cancelled(self, req: _Request) -> None:
        # device state needs no touch: the decode mask is built from
        # `running` each step, and admission overwrites the slot's rows
        self.done[req.rid] = req.out
        self.done_requests[req.rid] = req
        req.t_done = time.perf_counter()
        if self.scheduler is not None:
            # cancel-while-queued refunds the quota charge here
            self.scheduler.on_retired(req, self, "cancelled", req.t_done)
        if self.metrics:
            self.metrics.on_finish("cancelled")
        self._attr_retired(req, "cancelled")
        self._close_request_spans(req, "cancelled")

    def _retire_rejected(self, req: _Request, now: float) -> None:
        """The scheduler expired this queued request (its pool-pressure
        deferral outlived the budget): retire it with whatever it has
        (nothing — it never took a slot) so its stream closes and the
        HTTP plane can answer 429 off ``reject_reason``."""
        self.done[req.rid] = req.out
        self.done_requests[req.rid] = req
        req.t_done = now
        if self.scheduler is not None:
            self.scheduler.on_retired(req, self, "rejected", now)
        if self.metrics:
            self.metrics.on_finish("rejected")
        self._attr_retired(req, "rejected")
        self._close_request_spans(req, "rejected")

    def _preempt_slot(self, slot: int) -> None:
        """Evict the decoding request in ``slot`` and requeue it for a
        later resume (the slo scheduler's pressure valve). The emitted
        tokens fold back into the prompt, so the resumed admission
        chunk-prefills them like any other prompt — and the prefix
        cache serves whatever boundaries the ORIGINAL prefill promoted,
        so only the uncached tail recomputes. The finish chunk then
        samples emission (and seeded draw) number ``prefilled_out``,
        making the resumed stream bit-identical to an uninterrupted
        run for greedy and seeded requests (pinned)."""
        req = self.running.pop(slot)
        self._invalidate_slot_caches()
        self._release_slot_pages(slot, req)
        req.prompt = list(req.prompt) + [
            int(t) for t in req.out[req.prefilled_out:]
        ]
        req.prefilled_out = len(req.out)
        req.preemptions += 1
        req.slot = -1
        req.defer_counted = False
        # re-match at re-admission: the longer prompt may hit a deeper
        # promoted boundary than the original did
        req.matched = False
        req.prefix = None
        req._match_depth = None
        if req.timeline is not None:
            # the decode segment closes at eviction; a fresh queue_wait
            # opens (the resumed admission closes it again), so the
            # phase sums stay exact across preemption cycles
            req.timeline.advance("queue_wait", time.perf_counter())
        if req.decode_span is not None:
            req.decode_span.set(tokens=len(req.out)).end()
            req.decode_span = None
        if self.tracer.enabled and req.span is not None:
            self.tracer.span(
                "preempt", component="serving", parent=req.span,
                slot=slot, emitted=len(req.out),
            ).end()
            with attach(req.span):
                get_logger().debug(
                    "request preempted",
                    extra={"fields": {"rid": req.rid, "slot": slot,
                                      "tokens": len(req.out)}},
                )
        if self.scheduler is not None:
            self.scheduler.on_preempted(req, self, time.perf_counter())
        # requeue at the head; the next plan() pass re-sorts by policy
        self.pending.insert(0, req)
        if self.tracer.enabled and req.span is not None:
            self.tracer.span(
                "requeue", component="serving", parent=req.span,
                queued=len(self.pending),
            ).end()

    def _finish_if_done(self, req: _Request) -> None:
        """EOS, a stop sequence, or budget exhaustion retires the request
        and frees its slot. Stop sequences are host-side suffix matches
        (device shapes unchanged); matched tokens stay in the output."""
        hit_eos = self.eos_id >= 0 and req.out and req.out[-1] == self.eos_id
        hit_stop = any(
            len(req.out) >= len(st) and tuple(req.out[-len(st):]) == st
            for st in req.stop
        )
        if hit_eos or hit_stop or len(req.out) >= req.max_new:
            reason = "eos" if hit_eos else ("stop" if hit_stop else "budget")
            self.done[req.rid] = req.out
            self.done_requests[req.rid] = req
            req.t_done = time.perf_counter()
            if req.slot in self.running:
                del self.running[req.slot]
                self._invalidate_slot_caches()
                self._release_slot_pages(req.slot, req)
            if self.scheduler is not None:
                # deadline disposition (met -> goodput, missed -> miss
                # counter + overrun histogram) commits at retirement
                self.scheduler.on_retired(req, self, reason, req.t_done)
            if self.metrics:
                self.metrics.on_finish(reason)
            self._attr_retired(req, reason)
            self._close_request_spans(req, reason)

    def step(self) -> None:
        """Admit what fits, advance at most one prefill chunk, then one
        decode step for the whole batch.

        With ``pipeline_depth=1`` the decode is double-buffered: this
        call dispatches step t+1 and only then reads step t back, so the
        host-side per-token work (stop matching, budget retirement,
        metrics, stream publishing) runs while the device computes the
        next step. The flush-first rule: drain the in-flight step before
        this step can change slot occupancy (pending admissions, prefill
        progress, or an emptied batch) IF any of the step's live slots
        has since been freed by retire/cancel — otherwise that slot's
        stale token could be attributed to its next occupant once the
        occupant reaches ``running`` (bucketed admits land there in the
        same call; chunked ones at their finish chunk, which can also be
        the same call for short prompts). When every in-flight slot is
        still running — the saturated queue, and steady chunked
        admission — there is no hazard and no flush: the pipeline keeps
        streaming through admissions.

        Every device dispatch a step makes — admission prefills, page-
        table installs, prefix promotion slices, the decode dispatch —
        runs inside :meth:`_dispatch_scope`, so under tp>1 every trace
        binds the tensor-parallel sharding constraints (tp=1 is a
        nullcontext: today's graphs exactly).
        """
        with self._dispatch_scope():
            self._step_inner()

    def _step_inner(self) -> None:  # graftlint: hot-path
        # the per-step driver is REGISTERED hot: everything it runs —
        # sharded or not — must keep the zero-per-step-H2D contract (a
        # per-step device_put of, say, the page table would silently
        # re-upload the whole table every token)
        n_emitted = 0
        if self._inflight is not None and (
            self.pending or self.prefilling or not self.running
        ) and any(s not in self.running for s in self._inflight[2]):
            n_emitted += self._flush_inflight()
        self._admit()
        self._prefill_one_chunk()
        if self.running:
            allowed = self._batch_allowed()
            if self.pipeline_depth:
                prev, self._inflight = self._inflight, None
                if prev is not None and self._inflight_covers_rest(prev):
                    # budgets prove the in-flight step retires EVERY
                    # running request: a dispatch-ahead would compute a
                    # whole batch of -1 sentinels (the device budget
                    # gate). Read it instead — the drain's last step
                    # costs zero wasted compute.
                    n_emitted += self._read_step(prev)
                    if self.running:  # never on budget; belt for EOS/stop
                        self._dispatch_decode(self._batch_allowed())
                else:
                    self._dispatch_decode(allowed)
                    n_emitted += self._read_step(prev)
            else:
                n_emitted += self._decode_once(allowed)
        elif not n_emitted:
            return
        if self.metrics:
            self.metrics.on_step(
                n_emitted, len(self.pending), len(self.running),
                len(self.prefilling),
            )
        if self.mfu is not None:
            # live context rows the step's attention read (host ints
            # over <= n_slots requests — no device work, keeps the
            # zero-per-step-H2D contract this driver is registered for)
            live = sum(
                len(r.prompt) + len(r.out) - r.prefilled_out
                for r in self.running.values()
            )
            self.mfu.on_step(n_emitted, len(self.running), live)

    def _decode_dispatch(self, allowed):  # graftlint: hot-path
        """Enqueue ONE device decode dispatch and return the result
        arrays a later :meth:`_apply_decode_result` consumes. The
        overridable device half of a decode step: the speculative
        batcher dispatches a whole draft+verify round here instead (its
        result tuple carries per-slot acceptance counts too). Both
        halves must stay purely functional over ``self.state`` so the
        pipelined loop can hold one dispatch in flight."""
        # sel before params: the gathered-LoRA sel rebuild may swap the
        # compact stacks under self.params (argument-evaluation order)
        sel = self._batch_sel()
        self.state, emitted, logps = decode_step(
            self.params, self.state, allowed, self._eos_dev,
            self.cfg, self._batch_knobs(), sel=sel,
            bias=self._batch_bias(), seeds=self._batch_seeds(),
        )
        return (emitted, logps)

    def _apply_decode_result(self, arrs) -> int:  # graftlint: hot-path
        """The host half: sync ``arrs`` (one host sync) and run the
        per-token work. Returns tokens emitted."""
        if self._flt_decode is not None:
            # BEFORE the readback: an induced mid-decode crash loses
            # only device work that never reached ``req.out``, so the
            # supervisor's resume can never double-emit
            self._flt_decode.fire()
        emitted, logps = jax.device_get(arrs)
        return self._apply_emitted(emitted, logps)

    def _decode_once(self, allowed) -> int:
        """One SYNCHRONOUS decode dispatch + readback for the whole
        batch (the whole decode path at pipeline_depth=0)."""
        return self._apply_decode_result(self._decode_dispatch(allowed))

    def _dispatch_decode(self, allowed) -> None:  # graftlint: hot-path
        """Enqueue one decode step WITHOUT waiting for its results: the
        result device arrays are parked in ``_inflight`` (their D2H
        copies started immediately) and read by a later ``_read_step``.
        In steady state every argument here is a cached device array —
        zero host->device transfers per token."""
        span = None
        if self.trace_steps and self.tracer.enabled:
            span = self.tracer.span(
                "decode_dispatch", component="serving_engine",
                step=self._step_no,
            )
        t0 = time.perf_counter()
        arrs = self._decode_dispatch(allowed)
        for arr in arrs:
            # start the D2H copy the moment the step completes, so the
            # later device_get finds the bytes already on the host
            start = getattr(arr, "copy_to_host_async", None)
            if start is not None:
                start()
        if span is not None:
            span.end()
        if self.metrics:
            observe = getattr(self.metrics, "observe_dispatch", None)
            if observe is not None:
                observe(time.perf_counter() - t0)
        # the slots this dispatch counted as live (the allowed mask's
        # true set): step() flushes before re-admitting any of them
        self._inflight = (self._step_no, arrs, tuple(self.running))
        self._step_no += 1

    def _read_step(self, inflight) -> int:  # graftlint: hot-path
        """Read one previously dispatched step back and run the host
        per-token work for it. ``inflight`` is a ``_dispatch_decode``
        record or None (the pipeline's first step has nothing to read)."""
        if inflight is None:
            return 0
        step_no, arrs, _slots = inflight
        span = None
        if self.trace_steps and self.tracer.enabled:
            span = self.tracer.span(
                "decode_readback", component="serving_engine", step=step_no,
            )
        t0 = time.perf_counter()
        n = self._apply_decode_result(arrs)
        if span is not None:
            span.set(emitted=n).end()
        if self.metrics:
            observe = getattr(self.metrics, "observe_readback", None)
            if observe is not None:
                observe(time.perf_counter() - t0)
        return n

    def _inflight_covers_rest(self, inflight) -> bool:
        """True when the in-flight step's pending tokens will retire
        every running request on budget (len(out) plus the in-flight
        emission reaches max_new for each). Sound because the device
        budget counter can't disagree with the host count; conservative
        because EOS/stop retirements aren't predictable host-side."""
        slots = inflight[2]
        return all(
            len(req.out) + (1 if slot in slots else 0) >= req.max_new
            for slot, req in self.running.items()
        )

    def _flush_inflight(self) -> int:
        """Drain the in-flight step before an admission that could reuse
        one of its live slots: its tokens are applied against the
        CURRENT running map, so the freed slot's lagging token is
        dropped here rather than leaking into the slot's next occupant.
        (cancel() itself does NOT flush — it only shrinks ``running``,
        which the readback's membership check already handles; the flush
        happens in the step() that re-admits the slot.)"""
        prev, self._inflight = self._inflight, None
        if prev is None:
            return 0
        if self.metrics:
            on_flush = getattr(self.metrics, "on_pipeline_flush", None)
            if on_flush is not None:
                on_flush()
        return self._read_step(prev)

    def _apply_emitted(self, emitted, logps) -> int:  # graftlint: hot-path
        """Host per-token work for one read-back step: append tokens and
        logprobs, match stop sequences, retire finished requests, feed
        the inter-token histogram. Slots not in ``running`` (retired or
        cancelled since dispatch) and -1 sentinels are skipped — the
        lag-by-one drop that makes the pipeline exact."""
        n_emitted = 0
        observe_it, track, exemplars, now = self._token_tracking()
        for slot, req in list(self.running.items()):
            tok = int(emitted[slot])
            if tok >= 0:
                n_emitted += 1
                req.out.append(tok)
                req.out_logp.append(float(logps[slot]))
                if track:
                    self._mark_emitted_token(req, now, observe_it,
                                             exemplars)
                self._finish_if_done(req)
                if self._incremental_reserve:
                    # sliding-window decode: pages falling out of the
                    # window free as the row advances, so a windowed
                    # row's steady-state footprint is O(window) not
                    # O(length). Host free-list math only (one decref
                    # per page_size tokens) — the hot path's zero-H2D
                    # contract holds; a just-retired slot is a no-op
                    # (its ledger entry is already gone).
                    self._recycle_slot_pages(
                        slot,
                        len(req.prompt) + len(req.out)
                        - req.prefilled_out - 1,
                    )
        return n_emitted

    def _token_tracking(self):
        """Per-readback setup for inter-token tracking: returns
        (observe_it, track, exemplars, now) — shared by the plain and
        speculative readback loops so the ITL/exemplar/timeline
        semantics have ONE definition. ``track`` is False (and ``now``
        unread) when neither metrics nor attribution want per-token
        facts — the hot path's whole cost is this tuple build."""
        observe_it = (
            getattr(self.metrics, "observe_inter_token", None)
            if self.metrics else None
        )
        track = observe_it is not None or self.attribution is not None
        exemplars = observe_it is not None and getattr(
            self.metrics, "supports_exemplars", False
        )
        return (observe_it, track, exemplars,
                time.perf_counter() if track else 0.0)

    def _mark_emitted_token(self, req: _Request, now: float, observe_it,
                            exemplars: bool) -> None:
        """One emitted token's inter-token bookkeeping: the gap since
        the request's previous token feeds the ITL histogram (exemplar-
        tagged with the request's trace id when supported) and the
        attribution timeline; ``t_last_tok`` advances either way."""
        if req.t_last_tok:
            gap = now - req.t_last_tok
            if observe_it is not None:
                if exemplars and req.timeline is not None:
                    observe_it(gap, req.timeline.xid)
                else:
                    observe_it(gap)
            if req.timeline is not None:
                req.timeline.add_itl(now, gap)
        req.t_last_tok = now

    def run(self, max_steps: int | None = None) -> dict[int, list[int]]:
        """Drive until every submitted request finished (or max_steps)."""
        steps = 0
        while self.pending or self.running or self.prefilling:
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                break
        return dict(self.done)


# ---------------- chunked prefill ----------------
#
# A long admission prefill stalls every running slot for its full
# duration (one big dispatch). Chunked prefill (the Sarathi-style
# schedule) splits the prompt into fixed C-token chunks and interleaves
# them with decode steps: per-token decode latency for running requests
# is bounded by ONE chunk's compute instead of the whole prompt. Fixed C
# also means exactly two prefill compiles total (chunk, finish) — no
# bucket ladder.


def _slot_cache(cache: KVCache, slot) -> KVCache:
    f = lambda c: (  # noqa: E731
        None if c is None else jax.lax.dynamic_slice_in_dim(c, slot, 1, axis=1)
    )
    return KVCache(k=f(cache.k), v=f(cache.v),
                   k_scale=f(cache.k_scale), v_scale=f(cache.v_scale))


def _merge_slot(cache: KVCache, part: KVCache, slot) -> KVCache:
    g = lambda full, p: (  # noqa: E731
        None if full is None
        else jax.lax.dynamic_update_slice_in_dim(full, p, slot, axis=1)
    )
    return KVCache(k=g(cache.k, part.k), v=g(cache.v, part.v),
                   k_scale=g(cache.k_scale, part.k_scale),
                   v_scale=g(cache.v_scale, part.v_scale))


@partial(jax.jit, static_argnames=("p",))
def extract_prefix_rows(state: BatchState, slot, p: int) -> KVCache:
    """First ``p`` KV rows of ``slot`` as a (L, 1, p, Hkv, hd) KVCache —
    the prefix-cache promotion slice. ``p`` is static and always a
    ``prompt_buckets`` boundary, so this compiles once per boundary (and
    ``_insert_prefix``, which consumes the result, does too). The state
    is NOT donated: the batch keeps decoding from it."""
    sl = _slot_cache(state.cache, slot)
    f = lambda c: (  # noqa: E731
        None if c is None else jax.lax.slice_in_dim(c, 0, p, axis=2)
    )
    return KVCache(k=f(sl.k), v=f(sl.v),
                   k_scale=f(sl.k_scale), v_scale=f(sl.v_scale))


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(1,))
def prefill_chunk(
    params,
    state: BatchState,
    chunk: jax.Array,        # (C,) int32 — all real tokens
    chunk_start: jax.Array,  # scalar int32: absolute position of chunk[0]
    slot: jax.Array,
    cfg: LlamaConfig,
    sel: jax.Array | None = None,  # (1, N) adapter one-hot for THIS request
) -> BatchState:
    """One intermediate prefill chunk into ``slot`` (no sampling; the
    slot stays inactive until the finish chunk). Runs against the slot's
    OWN cache rows, so the chunk attends everything the slot prefilled
    so far and nothing of its neighbors (paged: the slot's page-table
    row scopes both the scatter-writes and the gather-reads)."""
    if cfg.kv_layout == "paged":
        _, cache = _forward_cached(
            params, chunk[None, :], state.cache, chunk_start, cfg,
            select_pos=jnp.int32(0), lora_sel=sel,
            pages=state.pages[slot][None],
        )
    else:
        sl = _slot_cache(state.cache, slot)
        _, sl = _forward_cached(
            params, chunk[None, :], sl, chunk_start, cfg,
            select_pos=jnp.int32(0),  # logits unused; lm_head at 1 row
            lora_sel=sel,
        )
        cache = _merge_slot(state.cache, sl, slot)
    # chunk_start == 0 is the request's FIRST chunk: start the presence
    # row from zeros, or a reused slot leaks its previous occupant's
    # seen-token set into this request's repetition penalty
    base = jnp.where(chunk_start == 0, False, state.presence[slot])
    presence = state.presence.at[slot].set(
        base.at[chunk].set(True)
    )
    return BatchState(
        cache=cache,
        lengths=state.lengths, last_token=state.last_token,
        active=state.active, presence=presence, key=state.key,
        budget=state.budget, draws=state.draws, pages=state.pages,
    )


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(1,))
def prefill_finish(
    params,
    state: BatchState,
    chunk: jax.Array,        # (C,) int32, padded past the real tail
    chunk_start: jax.Array,
    prompt_len: jax.Array,   # absolute total prompt length
    slot: jax.Array,
    cfg: LlamaConfig,
    knobs: jax.Array,        # (4,) f32 sampler knobs for THIS request
    max_new: jax.Array,      # scalar int32: the request's token budget
    sel: jax.Array | None = None,  # (1, N) adapter one-hot for THIS request
    bias: jax.Array | None = None,  # (1, V) logit bias for THIS request
    seed: jax.Array | None = None,  # (1,) i32 per-request seed (draw 0)
    draw0: jax.Array | None = None,  # scalar i32: first seeded-draw index
    #   (None = 0, the fresh-request trace; a preempted request resumes
    #   at draw prefilled_out so its seeded stream continues exactly)
) -> tuple[BatchState, jax.Array, jax.Array]:
    """Final chunk: run it, sample the first generated token (returned
    with its logprob), activate the slot.

    For prompts >= C the host schedules this chunk at ``prompt_len - C``
    — all real tokens, possibly overlapping rows earlier chunks already
    wrote (the overlap recomputes IDENTICAL K/V at identical positions,
    so the rewrite is a no-op; and the window always fits inside max_len,
    where a forward-padded chunk could straddle it and silently clamp).
    Only prompts < C pad, and their padded rows land at positions >=
    prompt_len, never attended (decode masks to ``lengths`` and the first
    decode token overwrites row ``prompt_len`` before attending it)."""
    c = chunk.shape[0]
    if cfg.kv_layout == "paged":
        logits, cache = _forward_cached(
            params, chunk[None, :], state.cache, chunk_start, cfg,
            select_pos=prompt_len - 1 - chunk_start, lora_sel=sel,
            pages=state.pages[slot][None],
        )
    else:
        sl = _slot_cache(state.cache, slot)
        logits, sl = _forward_cached(
            params, chunk[None, :], sl, chunk_start, cfg,
            select_pos=prompt_len - 1 - chunk_start, lora_sel=sel,
        )
        cache = _merge_slot(state.cache, sl, slot)
    base = jnp.where(chunk_start == 0, False, state.presence[slot])
    seen = base.at[chunk].max(
        chunk_start + jnp.arange(c) < prompt_len
    )
    key, sub = jax.random.split(state.key)
    tok, seen = sample_and_mark_dyn(
        logits[:, 0], sub, knobs[None, :], seen[None, :], bias,
        seed,  # draw index defaults to 0 (the first draw) in the sampler
        None if draw0 is None else draw0[None],
    )
    logp = token_logprob(logits[:, 0], tok)[0]
    tok = tok[0]
    write = jnp.int32(slot)
    return BatchState(
        cache=cache,
        lengths=state.lengths.at[write].set(prompt_len),
        last_token=state.last_token.at[write].set(tok),
        active=state.active.at[write].set(True),
        presence=state.presence.at[write].set(seen[0]),
        key=key,
        budget=state.budget.at[write].set(max_new - 1),
        draws=state.draws.at[write].set(
            1 if draw0 is None else draw0 + 1
        ),
        pages=state.pages,
    ), tok, logp


# ---------------- shared-prefix admission ----------------
#
# The serving killer-feature of prefix caching (generate.py's
# prefill_prompt/generate_from) at request granularity: a system prompt
# is prefilled ONCE into a PrefixState; every admission that names it
# starts by copying those rows into its slot and chunk-prefills only its
# own suffix. N requests sharing a P-token system prompt cost one
# P-token prefill total instead of N.
#
# serving/prefix_cache.py builds the AUTOMATIC tier on top: a radix
# index of promoted PrefixStates that _admit matches every prompt
# against, populated by the completed-prefill hook above
# (_maybe_promote_prefix + extract_prefix_rows) — no caller ever names
# a prefix, multi-turn chats and shared system prompts just stop paying
# for re-prefill.


@dataclass(frozen=True)
class PrefixState:
    """Immutable prefilled prefix: cache rows + presence + the tokens
    (the tokens ride along so finish-chunk overlap can recompute across
    the prefix boundary). Deliberately NOT a pytree: only its arrays
    enter jit (as plain args), so the insert compiles per prefix SHAPE —
    registering the token tuple as treedef metadata would recompile per
    distinct system prompt."""

    rows: KVCache          # (L, 1, P_pad, Hkv, hd)
    tokens: tuple          # the real prefix token ids (length P)
    presence: jax.Array    # (V,) bool over the real tokens
    # adapter these rows were prefilled under (-1 = base): the K/V depend
    # on the weights, so submit() only accepts a matching request
    adapter: int = -1


@partial(jax.jit, static_argnames=("cfg",))
def _precompute_prefix(params, prefix: jax.Array, prefix_len: jax.Array,
                       cfg: LlamaConfig, sel: jax.Array | None = None):
    """Traces at the PADDED bucket length ``prefix.shape[0]``: the real
    length rides as a traced scalar and only gates the presence writes
    (causal attention already keeps the padding out of the real rows'
    K/V), so every prefix in the same bucket shares one compile instead
    of one compile per exact length."""
    p = prefix.shape[0]
    scratch = KVCache.init(cfg, 1, p)
    _, scratch = _forward_cached(
        params, prefix[None, :], scratch, jnp.int32(0), cfg,
        select_pos=jnp.int32(0),  # logits unused
        lora_sel=sel,
    )
    # masked presence write over the real tokens only (.max = scatter-OR,
    # the prefill_insert idiom: a token in both prefix and padding stays
    # True)
    seen = jnp.zeros((cfg.vocab_size,), bool).at[prefix].max(
        jnp.arange(p) < prefix_len
    )
    return scratch, seen


def precompute_prefix(
    params, tokens: list[int], cfg: LlamaConfig,
    adapter: int = -1, n_adapters: int = 0,
    prompt_buckets: tuple[int, ...] = DEFAULT_PROMPT_BUCKETS,
    sel_index: "int | None" = None,
) -> PrefixState:
    """Prefill a shared prefix once. The forward pads to the next
    ``prompt_buckets`` boundary so similar-length prefixes share a
    compile (one trace per bucket, not per length); the returned rows
    are sliced back to the exact token count, so ``PrefixState`` and
    ``_insert_prefix`` semantics are unchanged. ``params`` must already
    carry stacked adapters when ``adapter`` >= 0 — pass the batcher's
    own ``.params``. Under GATHERED serving the stack position differs
    from the registry index: ``sel_index`` is the position inside the
    compact stacks (``n_adapters`` is then K) while ``adapter`` stays
    the registry id the PrefixState is labeled with — callers should
    use ``ContinuousBatcher.precompute_shared_prefix``, which derives
    both."""
    n = len(tokens)
    pad = next((b for b in sorted(prompt_buckets) if b >= n), n)
    arr = jnp.asarray(list(tokens) + [0] * (pad - n), jnp.int32)
    sel = None
    if adapter >= 0 and not n_adapters:
        # silently prefilling BASE rows while labeling them with the
        # adapter would defeat submit()'s exact-match check
        raise ValueError(
            f"precompute_prefix(adapter={adapter}) needs n_adapters > 0 "
            "(pass the batcher's adapter count and its .params)"
        )
    if n_adapters:
        from k8s_gpu_device_plugin_tpu.models.lora_serving import one_hot_sel

        if adapter >= 0 and not any(
            k.startswith("lora_") for k in params["layers"]
        ):
            # a sel over params WITHOUT stacked leaves would prefill BASE
            # rows and tag them with the adapter — the same silent-wrong-
            # K/V case as above, via the other argument
            raise ValueError(
                "params carry no stacked LoRA leaves; pass the batcher's "
                "own .params (attach_adapters output), not the base tree"
            )
        sel = jnp.asarray(one_hot_sel(
            adapter if sel_index is None else sel_index, n_adapters
        ))[None, :]
    scope = nullcontext()
    if cfg.tp > 1:
        # trace under the serving mesh so the tp constraints bind (the
        # caller passes the batcher's SHARDED params; an unconstrained
        # trace would leave the partitioner free to psum, breaking the
        # bit-identity the inserted rows must preserve)
        from k8s_gpu_device_plugin_tpu.parallel.tp_serving import (
            serving_mesh,
        )

        scope = serving_mesh(cfg.tp, cfg.n_kv_heads)
    with scope:
        rows, seen = _precompute_prefix(params, arr, jnp.int32(n), cfg, sel)
    if pad != n:
        # slice back to the exact length: the padded tail rows are
        # causal-masked garbage and must not enter _insert_prefix (they
        # would be copied over the suffix's positions in the slot)
        cut = lambda c: None if c is None else c[:, :, :n]  # noqa: E731
        rows = KVCache(k=cut(rows.k), v=cut(rows.v),
                       k_scale=cut(rows.k_scale), v_scale=cut(rows.v_scale))
    return PrefixState(rows=rows, tokens=tuple(tokens), presence=seen,
                       adapter=adapter)


@partial(jax.jit, donate_argnums=(0,))
def _insert_prefix(
    state: BatchState, rows: KVCache, presence: jax.Array, slot
) -> BatchState:
    """Copy prefilled prefix rows + presence into ``slot`` (suffix chunks
    and activation follow via the normal chunked-prefill path)."""
    def ins(full, part):
        if full is None:
            return None
        return jax.lax.dynamic_update_slice(
            full, part, (0, slot, 0, 0, 0)
        )

    cache = jax.tree.map(
        ins, state.cache, rows, is_leaf=lambda x: x is None
    )
    return BatchState(
        cache=cache,
        lengths=state.lengths,
        last_token=state.last_token,
        active=state.active,
        presence=state.presence.at[jnp.int32(slot)].set(presence),
        key=state.key,
        budget=state.budget,
        draws=state.draws,
        pages=state.pages,
    )


# ---------------- paged KV layout ----------------
#
# kv_layout="paged" (opt-in; LlamaConfig.kv_layout) replaces the dense
# (n_slots, max_len) per-slot row reservation with a shared page pool
# (models/paging.py owns the free list and refcounts; KVCache.init_paged
# holds the device arrays) and per-slot int32 page tables in
# ``BatchState.pages``. The jitted steps above all branch on the static
# cfg; the helpers below are the admission-time table/pool manipulations
# — tiny donated jits, so a table write never copies the pool.
#
# Zero-copy prefix sharing: promotion takes REFERENCES on the pages a
# completed prefill spans (PagedPrefixState), and a cache hit aliases
# them into the new slot's table — no KV rows move. The only copy left
# is copy-on-write of a PARTIALLY-filled tail page (a promotion boundary
# that isn't page-aligned): the aliasing slot will append its suffix
# into that page, so it gets a private copy of the one page while the
# full pages stay shared. ``kv_copy_counts()`` exposes both counters so
# tests can assert the zero-copy claim directly.

_KV_COPIES = {"rows": 0, "cow_pages": 0}


def kv_copy_counts() -> dict:
    """Live counters of KV data movement on the prefix paths: ``rows``
    counts dense row copies (extract_prefix_rows + _insert_prefix row
    counts), ``cow_pages`` counts paged tail-page copy-on-writes. The
    paged layout's zero-copy claim is ``rows == 0`` across any number of
    hits/promotions — test-asserted, not just documented."""
    return dict(_KV_COPIES)


def reset_kv_copy_counts() -> None:
    _KV_COPIES["rows"] = 0
    _KV_COPIES["cow_pages"] = 0


def _paged_release_hook(cb: "ContinuousBatcher"):
    """Build ``PrefixCache.release_entry`` for a paged batcher, closed
    over a WEAKREF only. A cache that outlives its batcher (the attach
    guard refuses to REUSE its paged entries, but nothing stops a caller
    keeping the object) must not retain the dead batcher — and through
    it the device page pool in ``BatchState`` — just to return page refs
    to a free list nobody allocates from anymore; once the batcher is
    collected, its pool died with it and the release is a no-op. Every
    attribute resolves at CALL time: the hook is bound before __init__
    builds the pool."""
    wref = weakref.ref(cb)

    def release(entry) -> None:
        live = wref()
        if live is None:
            return
        freed = live.pool.decref(entry.page_ids)
        if live.tracer.enabled:
            live.tracer.span(
                "page_free", component="serving",
                pages=len(entry.page_ids), freed=len(freed),
                free=live.pool.free_pages,
            ).end()
        live._report_kv_gauges()

    return release


@dataclass(frozen=True)
class PagedPrefixState:
    """A promoted prefix under the paged layout: physical page ids (each
    holding a pool reference taken at promotion) instead of copied rows.
    Same duck-typed surface as PrefixState where the batcher needs it
    (``tokens``/``presence``/``adapter``); ``page_ids`` spans
    ceil(len(tokens) / page_size) pages, the last one possibly partial
    (the COW case on alias)."""

    page_ids: tuple
    tokens: tuple
    presence: jax.Array
    adapter: int = -1


@partial(jax.jit, donate_argnums=(0,))
def _set_slot_pages(state: BatchState, row: jax.Array, slot) -> BatchState:
    """Upload one slot's page-table row (admission: the pages the host
    allocator just reserved). Donated so the pool is never copied."""
    return BatchState(
        cache=state.cache, lengths=state.lengths,
        last_token=state.last_token, active=state.active,
        presence=state.presence, key=state.key, budget=state.budget,
        draws=state.draws,
        pages=state.pages.at[jnp.int32(slot)].set(row),
    )


@partial(jax.jit, donate_argnums=(0,))
def _alias_slot_pages(
    state: BatchState, row: jax.Array, presence: jax.Array, slot
) -> BatchState:
    """Prefix-hit admission: table row (shared pages aliased in) plus
    the prefix's presence mask — the paged twin of ``_insert_prefix``,
    minus the row copies."""
    write = jnp.int32(slot)
    return BatchState(
        cache=state.cache, lengths=state.lengths,
        last_token=state.last_token, active=state.active,
        presence=state.presence.at[write].set(presence), key=state.key,
        budget=state.budget, draws=state.draws,
        pages=state.pages.at[write].set(row),
    )


@partial(jax.jit, donate_argnums=(0,))
def _copy_page(state: BatchState, src, dst) -> BatchState:
    """Copy one physical page (all layers) — the COW for a partially
    filled shared tail page. Donated: in-place on the pool buffer."""
    def cp(c):
        if c is None:
            return None
        page = jax.lax.dynamic_slice_in_dim(c, src, 1, axis=1)
        return jax.lax.dynamic_update_slice_in_dim(c, page, dst, axis=1)

    return BatchState(
        cache=jax.tree.map(cp, state.cache, is_leaf=lambda x: x is None),
        lengths=state.lengths, last_token=state.last_token,
        active=state.active, presence=state.presence, key=state.key,
        budget=state.budget, draws=state.draws, pages=state.pages,
    )


@partial(jax.jit, donate_argnums=(0,))
def _install_wire_pages(
    state: BatchState, wire: KVCache, ids, presence: jax.Array, slot
) -> BatchState:
    """Scatter transferred pool pages (a decoded KV wire blob — jit
    device_puts the host arrays) into the pool at the freshly allocated
    ``ids``, and seed the slot's presence mask — the import half of the
    disaggregated KV transfer. Donated: in-place on the pool buffers.
    Retraces per shipped-page count, which the prompt buckets bound,
    and runs once per installed admission — never per step."""
    def ins(full, part):
        if full is None:
            return None
        return full.at[:, ids].set(part)

    return BatchState(
        cache=jax.tree.map(ins, state.cache, wire,
                           is_leaf=lambda x: x is None),
        lengths=state.lengths, last_token=state.last_token,
        active=state.active,
        presence=state.presence.at[jnp.int32(slot)].set(presence),
        key=state.key, budget=state.budget, draws=state.draws,
        pages=state.pages,
    )


@partial(jax.jit, donate_argnums=(0,))
def _insert_prefix_rows_paged(
    state: BatchState, rows: KVCache, presence: jax.Array, slot
) -> BatchState:
    """Manual (dense) PrefixState into a paged slot: scatter the
    prefilled rows through the slot's freshly allocated pages. This IS a
    row copy (counted by the caller) — manual prefixes carry their own
    dense rows; only the automatic cache's paged entries alias."""
    ps = state.cache.k.shape[2]
    p = rows.k.shape[2]
    row = state.pages[jnp.int32(slot)]
    return BatchState(
        cache=_scatter_rows_paged(state.cache, rows, row, p, ps),
        lengths=state.lengths, last_token=state.last_token,
        active=state.active,
        presence=state.presence.at[jnp.int32(slot)].set(presence),
        key=state.key, budget=state.budget, draws=state.draws,
        pages=state.pages,
    )
