"""Sharded checkpoint/resume for training workloads (orbax-backed).

The daemon itself stays stateless by design (reference restart = full
re-enumeration, plugin/manager.go:177-194; SURVEY §5 "checkpoint/resume:
absent — stay stateless"); checkpointing belongs to the BENCHMARK WORKLOADS
(BASELINE configs #4/#5), where a preempted multi-hour Llama run must resume
rather than restart. TPU-first specifics:

- **Sharding-preserving**: leaves are saved from and restored to their
  NamedShardings directly — every process writes/reads only its own shards
  (no host gather; an 8B fsdp state never materializes on one host).
- **Async by default**: the save runs in a background thread after a fast
  device→host copy of the local shards, so the train loop loses only the
  copy time, not the filesystem write (HBM→disk overlaps with compute).
- **Multi-process correct**: under ``jax.distributed`` (see
  parallel/multihost.py) every worker participates in the same save/restore;
  orbax coordinates the commit so a partially-written step is never visible
  (crash-safe resumability for elastic recovery).
"""

from __future__ import annotations

import logging
from typing import Any

import jax
import orbax.checkpoint as ocp

from k8s_gpu_device_plugin_tpu.utils.log import get_logger

PyTree = Any


def abstract_like(state: PyTree) -> PyTree:
    """Shape/dtype/sharding skeleton of a live state — the restore target.

    Taking the skeleton (and dropping the live arrays) before calling
    :meth:`TrainCheckpointer.restore` keeps peak memory at one state, not
    two.
    """
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
        state,
    )


class TrainCheckpointer:
    """Save/restore a training-state pytree ({"params", "opt_state", "step"}).

    Thin policy wrapper over ``orbax.checkpoint.CheckpointManager``:
    retention (``max_to_keep``), cadence (``save_interval``), async commit.
    """

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 3,
        save_interval: int = 1000,
        async_save: bool = True,
        logger: logging.Logger | None = None,
    ) -> None:
        self.log = logger or get_logger()
        self._interval = max(int(save_interval), 1)
        self._mngr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=self._interval,
                enable_async_checkpointing=async_save,
                create=True,
            ),
        )

    # --- inspection ---

    @property
    def directory(self) -> str:
        return str(self._mngr.directory)

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(self._mngr.all_steps())

    # --- save / restore ---

    def save(self, state: PyTree, step: int | None = None, force: bool = False) -> bool:
        """Save if the cadence (or ``force``) says so; returns True if saved.

        Non-blocking when async: the device→host shard copy happens here,
        the write commits in the background (``wait()`` joins it).
        """
        if step is None:
            step = int(jax.device_get(state["step"]))
        if step in self._mngr.all_steps():
            # Already on disk (e.g. the trainer's final force-save landing on
            # a step the cadence just wrote): orbax raises
            # StepAlreadyExistsError even with force=True, so skip instead.
            return False
        saved = self._mngr.save(
            step, args=ocp.args.StandardSave(state), force=force
        )
        if saved:
            self.log.info(
                "checkpoint saved", extra={"fields": {"step": step,
                                                      "dir": self.directory}},
            )
        return saved

    def restore(self, target: PyTree, step: int | None = None) -> PyTree:
        """Restore into ``target``'s shapes/dtypes/shardings.

        ``target`` may be a live state (it is abstracted first — pass the
        result of :func:`abstract_like` and drop the live tree beforehand to
        halve peak memory) or an abstract skeleton.
        """
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        abstract = jax.tree.map(
            lambda x: x
            if isinstance(x, jax.ShapeDtypeStruct)
            else jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
            target,
        )
        state = self._mngr.restore(step, args=ocp.args.StandardRestore(abstract))
        self.log.info("checkpoint restored", extra={"fields": {"step": step}})
        return state

    def restore_unstructured(self, step: int | None = None) -> PyTree:
        """Restore WITHOUT a target skeleton: arrays come back with their
        saved shapes/dtypes on default devices. For consumers that only
        want a sub-tree (e.g. the inference server taking ``params`` out
        of a train state) and don't know the rest of the structure."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        state = self._mngr.restore(step)
        self.log.info(
            "checkpoint restored (unstructured)",
            extra={"fields": {"step": step}},
        )
        return state

    def restore_or_pass(self, state: PyTree) -> tuple[PyTree, bool]:
        """Resume from the latest checkpoint if one exists, else keep the
        freshly-initialized ``state``. Returns (state, resumed)."""
        if self.latest_step() is None:
            return state, False
        abstract = abstract_like(state)
        del state  # free before materializing the restored shards
        return self.restore(abstract), True

    # --- lifecycle ---

    def wait(self) -> None:
        """Join any in-flight async save (call before process exit)."""
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self.wait()
        self._mngr.close()

    def __enter__(self) -> "TrainCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
