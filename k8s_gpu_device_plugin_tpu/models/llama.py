"""Llama-3-family decoder, TPU-first.

Design choices (why this is not a torch port):

- **Functional params pytree**, layers stacked on a leading axis and applied
  with ``lax.scan`` — one trace/compile of the block regardless of depth,
  the XLA-friendly alternative to Python-loop-over-modules.
- **bf16 everywhere the MXU is involved, f32 where it matters**: params and
  activations bf16; attention logits/softmax, norm statistics, logits, and
  loss in f32 (matches TPU numerics guidance).
- **Sharding by annotation**: ``param_specs``/activation constraints carry
  dp/fsdp/tp/sp PartitionSpecs; XLA inserts the collectives (psum for TP
  reductions, all-gather for fsdp params) — no hand-written communication
  except the sequence-parallel attention (parallel/ring_attention.py,
  parallel/ulysses.py) where the ring/all-to-all structure IS the algorithm.
- **Remat**: each scanned block is wrapped in ``jax.checkpoint`` with a
  dots-saveable policy, trading FLOPs for HBM as usual on TPU.

BASELINE configs #4/#5 name Llama-3-8B/70B; those presets are provided, plus
a tiny config for tests and the graft entry.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from k8s_gpu_device_plugin_tpu.parallel.mesh import (
    AXIS_DP,
    AXIS_FSDP,
    AXIS_PP,
    AXIS_SP,
    AXIS_TP,
    constrain,
)
from k8s_gpu_device_plugin_tpu.parallel.ring_attention import ring_attention
from k8s_gpu_device_plugin_tpu.parallel.ulysses import ulysses_attention

BATCH = (AXIS_DP, AXIS_FSDP)


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    d_ff: int = 14336
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    max_seq: int = 8192
    # Mistral-style sliding-window attention (0 = full causal): query i
    # attends keys in (i - window, i]. Supported by the full/flash,
    # decode, AND ring sequence-parallel paths (windowed ring classifies
    # kv blocks by position offset; parallel/ring_attention.py). Ulysses
    # still rejects it.
    sliding_window: int = 0
    # Qwen2-style biases on the q/k/v projections (o/MLP stay bias-free —
    # that is the Qwen2 layout; HF Llama's all-four attention_bias is
    # refused at conversion rather than half-applied)
    attn_bias: bool = False
    # Gemma-family dials (all defaults = Llama behavior):
    act: str = "silu"          # MLP gate activation: "silu" | "gelu_tanh"
    norm_offset: bool = False  # RMSNorm scales by (1 + w), Gemma storage
    # lm_head = embed.T: ONE leaf, so training gradients accumulate into
    # the single tied tensor (XLA fuses the transpose; no copy)
    tied_embeddings: bool = False
    scale_embed: bool = False  # embeddings scaled by sqrt(d_model)
    head_dim_override: int = 0  # 0 = d_model // n_heads (Gemma-7B: 256)
    dtype: Any = jnp.bfloat16
    # Storage dtype for parameters (None = same as ``dtype``). Set
    # jnp.float32 for mixed-precision master weights: optimizer updates
    # smaller than a bf16 ulp are retained, while every matmul still runs
    # in ``dtype`` on the MXU (weights cast once per step). Costs 2x the
    # param/grad/moment HBM.
    param_dtype: Any = None
    remat: bool = True
    # What the block checkpoint saves (only read when remat=True); numerics
    # are identical across policies — this is a pure HBM-vs-recompute dial,
    # sweepable on hardware via the ``remat_tune`` bench workload:
    #   "save_dots_attn"  projection/MLP dots + the named attention output
    #                     (default: backward recomputes only VPU elementwise)
    #   "save_dots"       dots only — the flash forward is re-run in the
    #                     backward, trading MXU time for activation HBM
    #   "save_nothing"    full remat: minimum activation HBM, maximum
    #                     recompute (the long-context / big-model setting)
    remat_policy: str = "save_dots_attn"
    attn_impl: str = "auto"  # auto | full | ring | ulysses
    # decode-time cached attention: "auto"/"xla" = the fused XLA einsum
    # path; "ragged" opts decode (T=1) AND the speculative verify window
    # onto the unified ragged-paged Pallas kernel
    # (ops/ragged_paged_attention.py; bf16 caches; shard_map-ed per KV
    # head under tp>1) — flip the default once a hardware window
    # confirms the win
    decode_attn: str = "auto"
    # prefill-chunk cached attention: "ragged" routes chunk windows
    # (T <= MAX_PREFILL_T) through the SAME unified kernel. A separate
    # knob because it changes prefill's low-bit numerics profile (online
    # softmax vs the gather's plain softmax — different accumulation
    # order, same masked positions); decode/verify keep their own
    # opt-in unchanged
    prefill_attn: str = "auto"
    # "int8" runs the block projection/MLP matmuls on the MXU's double-rate
    # int8 path (ops/quant.py: quantized fwd, bf16 bwd); "none" = pure bf16.
    quant: str = "none"
    # KV-cache storage for decode (models/generate.py): "int8" quantizes
    # cached K/V per (position, head) with f32 scales — half the cache HBM
    # traffic and twice the context capacity of bf16, dequantized on read.
    cache_quant: str = "none"
    # Serving KV-cache LAYOUT (models/batching.py): "dense" preallocates
    # (n_slots, max_len) rows per slot; "paged" maps each slot's virtual
    # positions onto a shared (n_pages, kv_page_size) page pool through a
    # per-slot page table (models/paging.py) — HBM scales with LIVE
    # tokens, and prefix-cache reuse becomes page-table aliasing instead
    # of row copies. Composes with cache_quant: int8/int4 codes AND
    # their f32 scale planes ride the page pool (the scale planes share
    # the page geometry, one table lookup addresses both). Token/logprob
    # streams are bit-identical between the two layouts (test-pinned).
    kv_layout: str = "dense"
    # token rows per physical page when kv_layout == "paged"; must divide
    # the batcher's max_len, and multiples of 8 keep the Pallas paged
    # decode kernel's pages sublane-aligned
    kv_page_size: int = 64
    # Serving tensor parallelism (models/batching.py + parallel/
    # tp_serving.py): shards the decode path over a tp-axis device mesh —
    # q/k/v/gate/up projections and the lm_head column-wise, the KV cache
    # (dense rows and the paged pool alike) on the KV-head axis. 1 (the
    # default) is exactly the single-chip path: no mesh is ever built and
    # the traced graphs are unchanged. The sharding recipe is chosen so
    # no cross-device contraction ever splits a reduction (column shards
    # + gather-to-replicated before wo/w2/sampling), which is what keeps
    # tp>1 token/logprob streams BIT-identical to tp=1 (test-pinned).
    # Must divide n_kv_heads (and therefore n_heads); validated at mesh
    # construction with an actionable error.
    tp: int = 1
    # EXPLICIT bit-identity opt-out for tp>1 (the PR-8 follow-up): True
    # row-shards wo/w2 on their contraction axes and lets the SPMD
    # partitioner psum the partial products instead of gathering the
    # activation to replicated first. That removes the two all-gathers
    # the bit-safe recipe pays per layer, but a psum splits an f32
    # reduction into per-shard partials whose summation order differs
    # from the single-chip contraction (~1e-5 bf16 drift — enough to
    # flip a near-tie argmax), so tp>1 streams are no longer pinned
    # bit-identical to tp=1. Off (the default) is exactly the PR-8
    # recipe; flip it only when throughput beats exactness
    # (--tpPsum on the server).
    tp_allow_psum: bool = False
    # Fused lm_head+cross-entropy (ops/fused_ce.py): never materializes the
    # (B,S,V) logits. Training-loss only (no logits output, no accuracy);
    # requires the vocab axis unsharded (tp == 1) — loss_fn falls back
    # to the unfused path otherwise.
    fused_ce: bool = False
    # MoE (0 experts = dense MLP); Mixtral-style top-k routing, GShard dispatch
    n_experts: int = 0
    n_experts_per_token: int = 2
    capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    moe_z_weight: float = 1e-3
    # GShard routing-group size: capacity competition is local to groups of
    # this many tokens, keeping dispatch-tensor memory linear in seq length
    # (0 = one group per batch row).
    moe_group_size: int = 4096
    # pipeline parallelism: microbatches per step when the mesh has pp > 1
    # (bubble fraction is (pp-1)/(n_microbatches+pp-1))
    n_microbatches: int = 1

    def __post_init__(self) -> None:
        if self.quant not in ("none", "int8"):
            raise ValueError(
                f"quant must be 'none' or 'int8', got {self.quant!r} — "
                "an unknown value would silently run pure bf16"
            )
        if self.decode_attn not in ("auto", "xla", "ragged"):
            raise ValueError(
                f"decode_attn must be 'auto', 'xla' or 'ragged', got "
                f"{self.decode_attn!r}"
            )
        if self.prefill_attn not in ("auto", "xla", "ragged"):
            raise ValueError(
                f"prefill_attn must be 'auto', 'xla' or 'ragged', got "
                f"{self.prefill_attn!r}"
            )
        if self.remat_policy not in (
            "save_dots_attn", "save_dots", "save_nothing"
        ):
            raise ValueError(
                f"remat_policy must be 'save_dots_attn', 'save_dots' or "
                f"'save_nothing', got {self.remat_policy!r}"
            )
        if self.cache_quant not in ("none", "int8", "int4"):
            raise ValueError(
                f"cache_quant must be 'none', 'int8' or 'int4', got "
                f"{self.cache_quant!r} — an unknown value would silently "
                "run a bf16 cache"
            )
        if self.kv_layout not in ("dense", "paged"):
            raise ValueError(
                f"kv_layout must be 'dense' or 'paged', got "
                f"{self.kv_layout!r} — an unknown value would silently "
                "serve the dense layout"
            )
        if self.kv_page_size < 1:
            raise ValueError(
                f"kv_page_size must be >= 1, got {self.kv_page_size}"
            )
        if self.tp < 1:
            raise ValueError(
                f"tp must be >= 1 (1 = single-chip serving), got {self.tp}"
            )
        if self.act not in ("silu", "gelu_tanh"):
            raise ValueError(
                f"act must be 'silu' or 'gelu_tanh', got {self.act!r}"
            )
        if self.act != "silu" and self.n_experts > 0:
            raise NotImplementedError(
                "MoE expert MLPs hardcode silu (no Gemma-style MoE here)"
            )

    @property
    def head_dim(self) -> int:
        return self.head_dim_override or self.d_model // self.n_heads

    @property
    def p_dtype(self) -> Any:
        """Parameter storage dtype (master weights when f32)."""
        return self.param_dtype if self.param_dtype is not None else self.dtype

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0

    @property
    def attn_window(self) -> int:
        """Serving-facing alias of ``sliding_window`` — the name the
        server flag (--attnWindow), /v1/health's ``kv.attn_window``,
        and the long-context docs use. 0 = full causal attention (the
        default; every serving graph identical to a window-less build)."""
        return self.sliding_window

    def with_group_size(self, g: int) -> "LlamaConfig":
        return replace(self, moe_group_size=g)

    # --- presets ---

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=128256, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, d_ff=14336, rope_theta=500000.0, max_seq=8192,
        )

    @staticmethod
    def llama3_70b() -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=128256, d_model=8192, n_layers=80, n_heads=64,
            n_kv_heads=8, d_ff=28672, rope_theta=500000.0, max_seq=8192,
        )

    @staticmethod
    def gemma_2b() -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=256000, d_model=2048, n_layers=18, n_heads=8,
            n_kv_heads=1, d_ff=16384, rope_theta=10000.0, max_seq=8192,
            norm_eps=1e-6, act="gelu_tanh", norm_offset=True,
            tied_embeddings=True, scale_embed=True, head_dim_override=256,
        )

    @staticmethod
    def qwen2_7b() -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=152064, d_model=3584, n_layers=28, n_heads=28,
            n_kv_heads=4, d_ff=18944, rope_theta=1e6, max_seq=32768,
            attn_bias=True,
        )

    @staticmethod
    def mistral_7b() -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=32000, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, d_ff=14336, rope_theta=10000.0, max_seq=32768,
            sliding_window=4096,
        )

    @staticmethod
    def mixtral_8x7b() -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=32000, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, d_ff=14336, rope_theta=1e6, max_seq=32768,
            n_experts=8, n_experts_per_token=2,
        )

    @staticmethod
    def tiny(**overrides) -> "LlamaConfig":
        cfg = LlamaConfig(
            vocab_size=512, d_model=128, n_layers=2, n_heads=8,
            n_kv_heads=4, d_ff=256, max_seq=256, rope_theta=10000.0,
        )
        return replace(cfg, **overrides)

    def flops_per_token(self) -> float:
        """Dense training FLOPs/token: 6 * matmul params (fwd+bwd).

        attn term = wq + wo (each d*Hq*hd) + wk + wv (each d*Hkv*hd); the
        O(S) attention-score FLOPs are deliberately excluded (standard 6N
        model-FLOPs accounting), making reported MFU slightly conservative.
        """
        d, f, L = self.d_model, self.d_ff, self.n_layers
        hd = self.head_dim
        attn_proj = 2 * d * (self.n_heads * hd) + 2 * d * (self.n_kv_heads * hd)
        if self.is_moe:  # activated params only: k experts + router per token
            mlp = 3 * d * f * self.n_experts_per_token + d * self.n_experts
        else:
            mlp = 3 * d * f
        embed = self.vocab_size * d  # lm_head (embed table itself is a gather)
        params_matmul = L * (attn_proj + mlp) + embed
        return 6.0 * params_matmul


# --- parameter init & sharding -------------------------------------------


def init_params(key: jax.Array, cfg: LlamaConfig) -> dict:
    """Initialize the parameter pytree (layers stacked on axis 0)."""
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    d, hd = cfg.d_model, cfg.head_dim
    L = cfg.n_layers
    std = 0.02
    out_std = std / math.sqrt(2 * L)

    def norm_init(key, shape, scale):
        return (jax.random.truncated_normal(key, -3, 3, shape, jnp.float32) * scale
                ).astype(cfg.p_dtype)

    ks = jax.random.split(k_layers, 7)
    layers = {
        "attn_norm": jnp.ones((L, d), cfg.p_dtype),
        "mlp_norm": jnp.ones((L, d), cfg.p_dtype),
        "wq": norm_init(ks[0], (L, d, cfg.n_heads * hd), std),
        "wk": norm_init(ks[1], (L, d, cfg.n_kv_heads * hd), std),
        "wv": norm_init(ks[2], (L, d, cfg.n_kv_heads * hd), std),
        "wo": norm_init(ks[3], (L, cfg.n_heads * hd, d), out_std),
    }
    if cfg.attn_bias:
        # zeros: bias-free behavior at init; real values come from HF
        # checkpoints (models/convert.py)
        layers.update({
            "bq": jnp.zeros((L, cfg.n_heads * hd), cfg.p_dtype),
            "bk": jnp.zeros((L, cfg.n_kv_heads * hd), cfg.p_dtype),
            "bv": jnp.zeros((L, cfg.n_kv_heads * hd), cfg.p_dtype),
        })
    if cfg.is_moe:
        from k8s_gpu_device_plugin_tpu.models.moe import moe_param_init

        layers.update(moe_param_init(ks[4], cfg))
    else:
        layers.update({
            "w1": norm_init(ks[4], (L, d, cfg.d_ff), std),
            "w3": norm_init(ks[5], (L, d, cfg.d_ff), std),
            "w2": norm_init(ks[6], (L, cfg.d_ff, d), out_std),
        })
    out = {
        "embed": norm_init(k_embed, (cfg.vocab_size, d), std),
        "layers": layers,
        "final_norm": (jnp.zeros if cfg.norm_offset else jnp.ones)(
            (d,), cfg.p_dtype
        ),
    }
    if cfg.norm_offset:
        # zero-centered storage: (1 + w) = identity at init, like ones
        # in the plain convention
        layers["attn_norm"] = jnp.zeros((L, d), cfg.p_dtype)
        layers["mlp_norm"] = jnp.zeros((L, d), cfg.p_dtype)
    if not cfg.tied_embeddings:
        out["lm_head"] = norm_init(k_head, (d, cfg.vocab_size), std)
    return out


def param_specs(cfg: LlamaConfig, pp: int = 1) -> dict:
    """PartitionSpecs per parameter: tp shards head/ff dims, fsdp shards the
    complementary dim (ZeRO-3); layer axis is replicated (it is scanned).
    With ``pp > 1`` every layer leaf gains a leading *stage* dimension
    sharded over ``pp`` (shape (pp, L//pp, ...), see parallel/pipeline.py)."""
    layers = {
        "attn_norm": P(None, None),
        "mlp_norm": P(None, None),
        "wq": P(None, AXIS_FSDP, AXIS_TP),
        "wk": P(None, AXIS_FSDP, AXIS_TP),
        "wv": P(None, AXIS_FSDP, AXIS_TP),
        "wo": P(None, AXIS_TP, AXIS_FSDP),
    }
    if cfg.attn_bias:
        # biases shard with their output dim (tp), like the mats' columns
        layers.update({
            "bq": P(None, AXIS_TP),
            "bk": P(None, AXIS_TP),
            "bv": P(None, AXIS_TP),
        })
    if cfg.is_moe:
        from k8s_gpu_device_plugin_tpu.models.moe import moe_param_specs

        layers.update(moe_param_specs())
    else:
        layers.update({
            "w1": P(None, AXIS_FSDP, AXIS_TP),
            "w3": P(None, AXIS_FSDP, AXIS_TP),
            "w2": P(None, AXIS_TP, AXIS_FSDP),
        })
    if pp > 1:
        layers = {k: P(AXIS_PP, *spec) for k, spec in layers.items()}
    out = {
        "embed": P(AXIS_TP, AXIS_FSDP),
        "layers": layers,
        "final_norm": P(None),
    }
    if not cfg.tied_embeddings:
        out["lm_head"] = P(AXIS_FSDP, AXIS_TP)
    return out


def param_shardings(cfg: LlamaConfig, mesh: Mesh) -> dict:
    pp = mesh.shape.get(AXIS_PP, 1)
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        param_specs(cfg, pp=pp),
        is_leaf=lambda x: isinstance(x, P),
    )


def cast_params_for_compute(params: dict, cfg: LlamaConfig) -> dict:
    """Master-weight cast: layer stacks -> compute dtype, once.

    The MoE router is exempt: routing is precision-sensitive and moe.py
    consumes it in f32 — a bf16 round-trip would perturb top-k. No-op
    (returns ``params`` unchanged) when storage == compute dtype; callers
    that scan over microbatches (train.py grad accumulation) invoke this
    BEFORE their scan so the full-weight cast is not loop-body work.
    """
    if cfg.p_dtype == cfg.dtype:
        return params
    return {
        **params,
        "layers": {
            # router exempt (precision-sensitive); int8 serving leaves
            # ({"q","s"} dicts, models/quantized_serving.py) pass through
            # untouched — casting them would destroy the quantization
            k: (v if k == "router" or isinstance(v, dict)
                else v.astype(cfg.dtype))
            for k, v in params["layers"].items()
        },
    }


# --- model pieces ---------------------------------------------------------


@jax.custom_vjp
def _lm_head_matmul(x: jax.Array, w: jax.Array) -> jax.Array:
    """bf16-operand head projection with f32 accumulation (MXU rate).

    Upcasting both operands to f32 (the obvious ``x.astype(f32) @ w``) runs
    the single largest matmul in the model off the MXU's native bf16 path —
    measured on v5e it costs ~25 points of train MFU for no usable precision:
    what the loss needs is f32 *accumulation* and f32 logits, which
    ``preferred_element_type`` provides. The custom vjp keeps the backward
    dots on the bf16 path too by casting the (f32) logits cotangent to bf16
    — numerically the same information the bf16 parameter grads can hold.
    """
    return jnp.dot(x, w, preferred_element_type=jnp.float32)


def _lm_head_fwd(x, w):
    return _lm_head_matmul(x, w), (x, w)


def _lm_head_bwd(res, g):
    from k8s_gpu_device_plugin_tpu.ops.quant import bf16_ste_bwd

    x, w = res
    return bf16_ste_bwd(x, w, g)


_lm_head_matmul.defvjp(_lm_head_fwd, _lm_head_bwd)


def rms_norm(
    x: jax.Array, weight: jax.Array, eps: float, offset: bool = False
) -> jax.Array:
    """RMSNorm; ``offset`` scales by (1 + w) — Gemma checkpoints store
    the weight zero-centered."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jax.lax.rsqrt(var + eps)
    w = weight.astype(jnp.float32)
    if offset:
        w = 1.0 + w
    return (normed * w).astype(x.dtype)


def mlp_act(x: jax.Array, cfg: "LlamaConfig") -> jax.Array:
    """The gated-MLP activation: Llama silu or Gemma tanh-approx gelu."""
    if cfg.act == "silu":
        return jax.nn.silu(x)
    return jax.nn.gelu(x, approximate=True)


def head_weights(params: dict, cfg: "LlamaConfig"):
    """The lm_head operand: the dedicated leaf when present (incl. the
    int8/int4 dict leaves quantized serving installs), else the
    transposed embedding table for tied-embedding configs — ONE leaf, so
    training gradients flow into the single tied tensor and XLA fuses
    the transpose into the matmul."""
    if "lm_head" in params:
        return params["lm_head"]
    if cfg.tied_embeddings:
        return params["embed"].T
    raise KeyError("params has no lm_head and cfg is not tied_embeddings")


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding over (B, S, H, D) with integer positions (S,), or
    per-row positions (B, S) — continuous-batching decode runs every slot
    at its own absolute position (models/batching.py)."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d // 2, dtype=jnp.float32) / (d // 2))
    angles = positions[..., None].astype(jnp.float32) * freqs  # (..., S, D/2)
    if positions.ndim == 1:
        cos = jnp.cos(angles)[None, :, None, :]
        sin = jnp.sin(angles)[None, :, None, :]
    else:
        cos = jnp.cos(angles)[:, :, None, :]
        sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _attention(q, k, v, cfg: LlamaConfig, mesh: Mesh | None) -> jax.Array:
    impl = cfg.attn_impl
    sp = mesh.shape.get(AXIS_SP, 1) if mesh is not None else 1
    if impl == "auto":
        impl = "ring" if sp > 1 else "full"
    if impl in ("ring", "ulysses") and sp > 1:
        if impl == "ring":
            # windowed ring: per-step window classification (blocks fully
            # outside the window are skipped, so long-context windowed
            # work scales with W, not S — parallel/ring_attention.py)
            return ring_attention(
                q, k, v, mesh, causal=True, window=cfg.sliding_window
            )
        if cfg.sliding_window > 0:
            raise NotImplementedError(
                "sliding_window is not supported with Ulysses sequence "
                "parallelism; use attn_impl='ring'"
            )
        return ulysses_attention(q, k, v, mesh, causal=True)
    # single-shard path: full causal attention (f32 softmax)
    from k8s_gpu_device_plugin_tpu.ops.attention import attention

    return attention(q, k, v, causal=True, window=cfg.sliding_window)


def _block(x, layer, cfg: LlamaConfig, positions, mesh):
    """One transformer block: (B, S, D) -> ((B, S, D), aux losses)."""
    b, s, d = x.shape
    hd = cfg.head_dim

    if cfg.quant == "int8":
        from k8s_gpu_device_plugin_tpu.ops.quant import int8_matmul

        # custom_vjp calls are opaque to dot-matching remat policies, so tag
        # outputs by name — forward_with_aux's policy saves "quant_dot"
        # alongside plain dots (else the backward re-runs every quantized
        # matmul, erasing the int8 win).
        def mm(a, b):
            return checkpoint_name(int8_matmul(a, b), "quant_dot")
    else:
        mm = jnp.matmul

    h = rms_norm(x, layer["attn_norm"], cfg.norm_eps, cfg.norm_offset)
    q, k, v = mm(h, layer["wq"]), mm(h, layer["wk"]), mm(h, layer["wv"])
    if cfg.attn_bias:
        q = q + layer["bq"]
        k = k + layer["bk"]
        v = v + layer["bv"]
    q = q.reshape(b, s, cfg.n_heads, hd)
    k = k.reshape(b, s, cfg.n_kv_heads, hd)
    v = v.reshape(b, s, cfg.n_kv_heads, hd)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)
    qkv_spec = P(BATCH, AXIS_SP, AXIS_TP, None)
    q, k, v = (constrain(t, qkv_spec) for t in (q, k, v))

    attn = _attention(q, k, v, cfg, mesh)
    # Named so the remat policy can SAVE it: recomputing flash attention in
    # the backward is the one recompute that costs real MXU time.
    attn = checkpoint_name(attn, "attn_out")
    attn = attn.reshape(b, s, cfg.n_heads * hd)
    x = x + constrain(mm(attn, layer["wo"]), P(BATCH, AXIS_SP, None))

    h = rms_norm(x, layer["mlp_norm"], cfg.norm_eps, cfg.norm_offset)
    if cfg.is_moe:
        from k8s_gpu_device_plugin_tpu.models.moe import moe_mlp

        ff_out, aux = moe_mlp(h, layer, cfg)
    else:
        gate = mlp_act(mm(h, layer["w1"]).astype(jnp.float32), cfg).astype(x.dtype)
        up = mm(h, layer["w3"])
        ff = constrain(gate * up, P(BATCH, AXIS_SP, AXIS_TP))
        ff_out = constrain(mm(ff, layer["w2"]), P(BATCH, AXIS_SP, None))
        aux = {}
    x = x + ff_out
    return x, aux


def forward_with_aux(
    params: dict,
    tokens: jax.Array,
    cfg: LlamaConfig,
    mesh: Mesh | None = None,
    return_hidden: bool = False,
) -> tuple[jax.Array, dict]:
    """Token ids (B, S) -> (logits (B, S, V) f32, aux losses summed over
    layers — empty dict for dense configs, MoE balance/z terms otherwise).
    ``return_hidden`` stops before the lm_head and returns the final normed
    hidden states (B, S, D) instead — the seam fused-CE training uses."""
    b, s = tokens.shape
    # master-weight path (no-op otherwise); idempotent, so callers that
    # already cast (train.py hoists this out of the grad-accum scan) pay
    # nothing extra
    params = cast_params_for_compute(params, cfg)
    x = params["embed"].astype(cfg.dtype)[tokens]
    if cfg.scale_embed:
        x = x * jnp.asarray(math.sqrt(cfg.d_model), cfg.dtype)
    x = constrain(x, P(BATCH, AXIS_SP, None))
    positions = jnp.arange(s, dtype=jnp.int32)

    block = partial(_block, cfg=cfg, positions=positions, mesh=mesh)
    if cfg.remat:
        # Default ("save_dots_attn"): projection/MLP dot outputs are
        # saveable (no batch dims), plus the named attention output —
        # everything recomputed in the backward is then cheap VPU
        # elementwise (norms, rope, silu), never the flash kernel or an
        # MXU matmul. The other policies trade along the HBM/recompute
        # axis; all are numerics-identical (same ops, different schedule).
        if cfg.remat_policy == "save_nothing":
            policy = jax.checkpoint_policies.nothing_saveable
        else:
            names = (
                ("attn_out", "quant_dot")
                if cfg.remat_policy == "save_dots_attn"
                else ("quant_dot",)
            )
            policy = jax.checkpoint_policies.save_from_both_policies(
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                jax.checkpoint_policies.save_only_these_names(*names),
            )
        block = jax.checkpoint(block, policy=policy)

    pp = mesh.shape.get(AXIS_PP, 1) if mesh is not None else 1
    if pp > 1:
        # Looped GSPMD pipeline (parallel/pipeline.py): embed/head are cheap
        # and replicated over pp; only the block stack is pipelined. MoE aux
        # losses ride the pipeline as per-stage scalars: summed over stages,
        # averaged over microbatches (per-microbatch router statistics — the
        # standard pipelined-MoE semantics).
        from k8s_gpu_device_plugin_tpu.parallel.pipeline import pipeline_blocks

        def stage_fn(stage_layers, h):
            def body(carry, layer):
                return block(carry, layer)

            h, aux_stacked = jax.lax.scan(body, h, stage_layers)
            return h, {k: jnp.sum(v) for k, v in aux_stacked.items()}

        x, aux = pipeline_blocks(
            stage_fn,
            params["layers"],
            x,
            n_stages=pp,
            n_microbatches=max(cfg.n_microbatches, 1),
        )
    else:

        def scan_body(carry, layer):
            out, aux = block(carry, layer)
            return out, aux

        x, aux_stacked = jax.lax.scan(scan_body, x, params["layers"])
        aux = {k: jnp.sum(v) for k, v in aux_stacked.items()}
    x = rms_norm(x, params["final_norm"], cfg.norm_eps, cfg.norm_offset)
    if return_hidden:
        return constrain(x, P(BATCH, AXIS_SP, None)), aux
    logits = _lm_head_matmul(x, head_weights(params, cfg).astype(cfg.dtype))
    return constrain(logits, P(BATCH, AXIS_SP, AXIS_TP)), aux


def forward(
    params: dict,
    tokens: jax.Array,
    cfg: LlamaConfig,
    mesh: Mesh | None = None,
) -> jax.Array:
    """Token ids (B, S) -> logits (B, S, V) in f32."""
    return forward_with_aux(params, tokens, cfg, mesh)[0]
