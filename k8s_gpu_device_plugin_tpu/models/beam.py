"""Beam search over the KV-cache decode path.

Fixed-shape TPU construction: the cache is allocated at ``beam`` batch
rows up front; every step is one T=1 cached forward over all beams, a
(beam * V) top-k, and a batch-axis gather that reorders the cache and
token buffer by each survivor's parent beam — no dynamic shapes, one
``lax.scan``, one compile. Scores are exact cumulative log-probabilities
(log-softmax in f32); with a fixed ``max_new`` every hypothesis has the
same length, so no length normalization is applied.

Guarantees pinned by tests: ``beam=1`` emits exactly the greedy decode;
each returned score equals the sequence's recomputed log-probability
under the full-context forward; and for ``max_new=2`` with
``beam == vocab`` the search is exhaustive, matching brute force.

The reference daemon has no serving stack (SURVEY §2); this completes the
decode modes (greedy / sampled / speculative / beam).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from k8s_gpu_device_plugin_tpu.models.generate import KVCache, _forward_cached, prefill
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig


@partial(jax.jit, static_argnames=("cfg", "max_new", "beam"))
def beam_search(
    params,
    prompt: jax.Array,
    cfg: LlamaConfig,
    max_new: int,
    beam: int = 4,
) -> tuple[jax.Array, jax.Array]:
    """prompt (1, P) -> (sequences (beam, max_new), scores (beam,)).

    Sequences are sorted by score descending (row 0 is the best
    hypothesis); scores are cumulative token log-probabilities.
    """
    if cfg.quant != "none":
        raise NotImplementedError("decode path is bf16-only (quant='none')")
    b, p = prompt.shape
    if b != 1:
        raise NotImplementedError("beam search decodes one prompt at a time")
    if beam < 1:
        raise ValueError(f"beam must be >= 1, got {beam}")
    if beam > cfg.vocab_size:
        raise ValueError(
            f"beam ({beam}) cannot exceed vocab_size ({cfg.vocab_size}): "
            "there are only vocab_size distinct continuations per step"
        )
    if max_new < 1:
        raise ValueError(f"max_new must be >= 1, got {max_new}")
    v = cfg.vocab_size

    # prefill ONCE at batch 1 (all beams share the prompt — a beam-row
    # prefill would pay beam x the prompt FLOPs for identical results),
    # then replicate the filled K/V rows (and scale planes) across beams
    cache = KVCache.init(cfg, 1, p + max_new)
    logits, cache = prefill(params, prompt, cache, cfg)
    cache = jax.tree.map(
        lambda x: None if x is None else jnp.repeat(x, beam, axis=1),
        cache,
        is_leaf=lambda x: x is None,
    )
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)

    # first token: top-beam continuations of the single real hypothesis
    scores, first = jax.lax.top_k(logp[0], beam)        # (beam,)
    buf = jnp.zeros((beam, max_new), jnp.int32)
    buf = buf.at[:, 0].set(first)

    def gather_beams(tree, parent):
        # cache arrays are (L, beam, S, H, hd): reorder the beam axis
        return jax.tree.map(
            lambda x: None if x is None else jnp.take(x, parent, axis=1),
            tree,
            is_leaf=lambda x: x is None,
        )

    def step(carry, i):
        buf, scores, last, cache = carry
        logits, cache = _forward_cached(params, last[:, None], cache, i, cfg)
        lp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32), axis=-1)
        cand = scores[:, None] + lp                     # (beam, V)
        scores, flat_idx = jax.lax.top_k(cand.reshape(-1), beam)
        parent = flat_idx // v
        tok = (flat_idx % v).astype(jnp.int32)
        buf = jnp.take(buf, parent, axis=0).at[:, i + 1 - p].set(tok)
        cache = gather_beams(cache, parent)
        return (buf, scores, tok, cache), None

    if max_new > 1:
        (buf, scores, _, _), _ = jax.lax.scan(
            step,
            (buf, scores, first, cache),
            p + jnp.arange(max_new - 1, dtype=jnp.int32),
        )
    return buf, scores
