"""Token samplers for autoregressive decode (greedy / temperature / top-k /
top-p), as a static, hashable config so ``generate`` stays one compile.

TPU-first shape: everything is fixed-shape tensor algebra over the (B, V)
logits — ``top_k`` uses ``lax.top_k`` and a threshold compare rather than
scatter; ``top_p`` sorts once and masks by exclusive cumulative probability.
No data-dependent control flow, so the sampler composes with ``lax.scan``
decode loops and pjit.

The reference daemon has no sampling analogue (SURVEY §2); this belongs to
the model-family API of the workload stack (train + generate + sample).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

_NEG = -1e30


@dataclass(frozen=True)
class Sampler:
    """Static sampling config (hashable: usable as a jit static arg).

    Applied in the standard order: temperature -> top_k -> top_p ->
    categorical. ``temperature == 0`` is exact greedy (argmax) and ignores
    the other knobs. ``top_k == 0`` / ``top_p >= 1.0`` disable those filters.
    """

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    # CTRL-style repetition penalty (1.0 = off): logits of already-seen
    # tokens are divided by it when positive, multiplied when negative —
    # applied BEFORE temperature/filters, and also under greedy decoding.
    repetition_penalty: float = 1.0

    def __post_init__(self) -> None:
        if self.temperature < 0.0:
            raise ValueError(f"temperature must be >= 0, got {self.temperature}")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if self.repetition_penalty < 1.0:
            raise ValueError(
                f"repetition_penalty must be >= 1, got "
                f"{self.repetition_penalty}"
            )

    @property
    def is_greedy(self) -> bool:
        return self.temperature == 0.0


def _apply_top_k(logits: jax.Array, k: int) -> jax.Array:
    """Keep the k largest logits per row; mask the rest to -inf."""
    kth = jax.lax.top_k(logits, k)[0][..., -1:]        # (B, 1) k-th largest
    return jnp.where(logits >= kth, logits, _NEG)


def _apply_top_p(logits: jax.Array, p: float) -> jax.Array:
    """Nucleus filter: smallest set of tokens with cumulative prob >= p.

    Uses the EXCLUSIVE cumulative sum over descending-sorted probabilities,
    so the token that crosses the threshold is kept (the set always reaches
    >= p and is never empty) — the standard nucleus-sampling boundary rule.
    """
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]           # desc
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1) - probs                       # exclusive
    keep_sorted = cum < p                                          # (B, V)
    # threshold logit: smallest kept logit per row
    kth = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits >= kth, logits, _NEG)


def filtered_logits(logits: jax.Array, sampler: Sampler) -> jax.Array:
    """(..., V) logits after temperature + top-k + top-p filtering — the
    distribution the sampler actually draws from (masked entries -> -inf).
    Undefined for greedy samplers (temperature 0 has no distribution)."""
    logits = logits.astype(jnp.float32) / sampler.temperature
    if sampler.top_k > 0:
        logits = _apply_top_k(logits, min(sampler.top_k, logits.shape[-1]))
    if sampler.top_p < 1.0:
        logits = _apply_top_p(logits, sampler.top_p)
    return logits


def filtered_probs(logits: jax.Array, sampler: Sampler) -> jax.Array:
    """(..., V) normalized probabilities the sampler draws from; the input
    to speculative rejection sampling (models/speculative.py), which needs
    the draft and target to agree on the filtered distributions."""
    return jax.nn.softmax(filtered_logits(logits, sampler), axis=-1)


def apply_repetition_penalty(
    logits: jax.Array, presence: jax.Array, penalty: float
) -> jax.Array:
    """CTRL rule on already-seen tokens (presence (B, V) bool): positive
    logits divide by the penalty, negative multiply — both push the
    probability down regardless of sign."""
    logits = logits.astype(jnp.float32)
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(presence, penalized, logits)


def init_presence(prompt: jax.Array, vocab_size: int) -> jax.Array:
    """(B, P) prompt -> (B, V) bool mask of tokens already in context —
    the repetition-penalty state every decode loop threads (shared by
    generate and rolling_generate so the two cannot drift)."""
    b = prompt.shape[0]
    rows = jnp.arange(b)[:, None]
    return jnp.zeros((b, vocab_size), bool).at[rows, prompt].set(True)


def sample_and_mark(
    logits: jax.Array, key: jax.Array, sampler: "Sampler",
    presence: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """Sample one token per row and record it in the presence mask."""
    tok = sample_logits(logits, key, sampler, presence=presence)
    b = presence.shape[0]
    return tok, presence.at[jnp.arange(b), tok].set(True)


def sample_logits(
    logits: jax.Array,
    key: jax.Array,
    sampler: Sampler,
    presence: jax.Array | None = None,
) -> jax.Array:
    """(B, V) f32 logits -> (B,) int32 token ids.

    ``presence`` (B, V) bool marks tokens already in the context; it is
    required when ``sampler.repetition_penalty > 1`` (the penalty applies
    before temperature/filters and also affects greedy argmax)."""
    if sampler.repetition_penalty > 1.0:
        if presence is None:
            raise ValueError(
                "repetition_penalty needs the presence mask of prior tokens"
            )
        logits = apply_repetition_penalty(
            logits, presence, sampler.repetition_penalty
        )
    if sampler.is_greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(
        key, filtered_logits(logits, sampler), axis=-1
    ).astype(jnp.int32)


def sampler_knobs(sampler: Sampler) -> tuple[float, float, float, float]:
    """Sampler -> the (temperature, top_k, top_p, repetition_penalty)
    row the dynamic per-slot path consumes (top_k rides as f32; exact up
    to 2^24, far beyond any vocab)."""
    return (
        sampler.temperature,
        float(sampler.top_k),
        sampler.top_p,
        sampler.repetition_penalty,
    )


def sample_logits_dyn(
    logits: jax.Array,
    key: jax.Array,
    knobs: jax.Array,     # (B, 4) f32: temp, top_k, top_p, rep_penalty
    presence: jax.Array,  # (B, V) bool
    bias: jax.Array | None = None,  # (B, V) f32 per-row logit bias
    seeds: jax.Array | None = None,   # (B,) i32 per-row seed (-1 = none)
    draws: jax.Array | None = None,   # (B,) i32 per-row draw index
) -> jax.Array:
    """Per-ROW sampler knobs as traced values — continuous batching serves
    requests with different sampling settings in one compiled step.

    Bit-identical to :func:`sample_logits` at equal knob values: same
    filter order (penalty -> temperature -> top-k -> top-p), the top-p
    cut applied to the post-top-k logits exactly as ``filtered_logits``
    does, same -inf mask value, and per-row categorical draws that only
    depend on the key and that row's logits. Greedy rows (temperature 0)
    take the penalized argmax, ignoring the filters, as the static path
    does. Costs one (B, V) sort per call (the post-top-k ordering is
    derived by masking the same sorted array) — noise next to the
    weight-streaming a decode step already does.

    ``bias`` adds to the RAW logits before every filter (OpenAI
    logit_bias semantics: -100 effectively bans a token, +100 forces
    it); greedy rows argmax the biased logits. token_logprob stays over
    the unbiased distribution by design (model confidence, not sampler
    state).

    ``seeds``/``draws`` (both (B,) int32, -1/any for unseeded rows)
    give a row its OWN key stream: the i-th draw of a seeded request
    uses fold_in(key(seed), i) — its sampled tokens depend only on its
    seed and its own logits, so the stream reproduces exactly
    regardless of batch composition, admission timing, or neighbors
    (stronger than OpenAI's best-effort ``seed``). Unseeded rows keep
    the shared step key, bit-identical to the seedless path.
    """
    logits = logits.astype(jnp.float32)
    if bias is not None:
        logits = logits + bias
    temp, top_k, top_p, rep = (
        knobs[:, 0], knobs[:, 1], knobs[:, 2], knobs[:, 3]
    )
    pen = rep[:, None]
    penalized = jnp.where(logits > 0, logits / pen, logits * pen)
    logits = jnp.where(presence, penalized, logits)  # pen 1.0 = identity
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    v = logits.shape[-1]
    scaled = logits / jnp.maximum(temp, 1e-6)[:, None]
    k = jnp.clip(top_k.astype(jnp.int32), 0, v)
    sorted_k = jnp.sort(scaled, axis=-1)[..., ::-1]
    kth = jnp.take_along_axis(
        sorted_k, jnp.clip(k - 1, 0, v - 1)[:, None], axis=-1
    )
    use_k = (k > 0)[:, None]
    scaled = jnp.where(use_k & (scaled < kth), _NEG, scaled)
    # the post-top-k sort is DERIVABLE from sorted_k: the kept values
    # (>= kth, ties included) are a contiguous descending prefix, so
    # masking sorted_k in place is the second sort — one (B, V) sort
    # total on the per-token decode path
    sorted_p = jnp.where(use_k & (sorted_k < kth), _NEG, sorted_k)
    probs = jax.nn.softmax(sorted_p, axis=-1)
    cum = jnp.cumsum(probs, axis=-1) - probs  # exclusive (nucleus rule)
    pth = jnp.min(
        jnp.where(cum < top_p[:, None], sorted_p, jnp.inf),
        axis=-1, keepdims=True,
    )
    scaled = jnp.where((top_p < 1.0)[:, None] & (scaled < pth), _NEG, scaled)
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    if seeds is not None:
        if draws is None:  # first draw (prefill callers): index 0
            draws = jnp.zeros(seeds.shape, jnp.int32)

        def draw_one(s, d, row):
            k = jax.random.fold_in(
                jax.random.key(jnp.maximum(s, 0).astype(jnp.uint32)), d
            )
            return jax.random.categorical(k, row).astype(jnp.int32)

        seeded = jax.vmap(draw_one)(seeds, draws, scaled)
        sampled = jnp.where(seeds >= 0, seeded, sampled)
    return jnp.where(temp == 0.0, greedy_tok, sampled)


def sample_and_mark_dyn(
    logits: jax.Array, key: jax.Array, knobs: jax.Array, presence: jax.Array,
    bias: jax.Array | None = None,
    seeds: jax.Array | None = None,
    draws: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Dynamic-knob twin of :func:`sample_and_mark`."""
    tok = sample_logits_dyn(logits, key, knobs, presence, bias, seeds, draws)
    b = presence.shape[0]
    return tok, presence.at[jnp.arange(b), tok].set(True)


def token_logprob(logits: jax.Array, tok: jax.Array) -> jax.Array:
    """log P(tok) under the RAW model distribution (f32 log-softmax of the
    unfiltered logits) — the "model confidence" number serving APIs
    report, deliberately independent of temperature/top-k/top-p/penalty
    so it stays comparable across sampler settings."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(logp, tok[..., None], axis=-1)[..., 0]
