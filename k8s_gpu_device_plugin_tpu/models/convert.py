"""HuggingFace Llama checkpoint import.

Bridges the ecosystem's weight format to this framework's functional
pytree so trained checkpoints (Llama-3, Mixtral-dense-equivalents, any
LlamaForCausalLM) run on the TPU stack without retraining. Pure layout
transformation — no torch ops beyond reading tensors, so the function also
serves as the parity oracle seam: ``tests/test_convert.py`` builds a
random-init HF model, converts it, and pins our forward's logits against
``transformers``' reference implementation.

Layout mapping (HF -> here):

- torch ``Linear.weight`` is (out, in); our matmuls are ``x @ W`` with W
  (in, out) -> transpose every projection.
- per-layer tensors stack on a leading L axis (the ``lax.scan`` layout).
- rope is the rotate-half convention in both; RMSNorm epsilon and theta
  come from the HF config.

No network access is required or attempted: callers pass an in-memory
model/state_dict (e.g. loaded from local safetensors).
"""

from __future__ import annotations

from typing import Any, Mapping

import jax.numpy as jnp
import numpy as np

from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig


def config_from_hf(hf_config: Any, dtype: Any = jnp.bfloat16) -> LlamaConfig:
    """Map a ``transformers.LlamaConfig`` onto :class:`LlamaConfig`."""
    model_type = getattr(hf_config, "model_type", "llama")
    # tied embeddings are family-agnostic here (head_weights serves
    # embed.T); params_from_hf verifies the materialized head really
    # equals the embedding table
    tied = bool(getattr(hf_config, "tie_word_embeddings", False))
    scaling = getattr(hf_config, "rope_scaling", None)
    if scaling:
        # Llama-3.1+ ships rope_scaling (rope_type "llama3" frequency
        # rescale); converting while silently dropping it would compute
        # wrong rotary frequencies at every position — refuse instead.
        raise NotImplementedError(
            f"rope_scaling {scaling!r} not supported: this stack computes "
            "plain rotary frequencies from rope_theta"
        )
    act = (
        getattr(hf_config, "hidden_activation", None)
        or getattr(hf_config, "hidden_act", "silu")
    )
    if act in ("silu", "swish"):
        our_act = "silu"
    elif act in ("gelu_pytorch_tanh", "gelu_tanh"):
        our_act = "gelu_tanh"
    else:
        raise NotImplementedError(
            f"hidden_act {act!r} not supported (silu or tanh-gelu only)"
        )
    # Qwen2 is Llama-layout plus q/k/v projection biases (no o bias).
    # HF Llama's own attention_bias puts a bias on o_proj TOO — converting
    # that would half-apply it, so it is refused below via the
    # unconsumed-tensor check (o_proj.bias is never taken).
    attn_bias = model_type == "qwen2" or bool(
        getattr(hf_config, "attention_bias", False)
    )
    gemma = model_type == "gemma"
    hd = int(getattr(hf_config, "head_dim", 0) or 0)
    default_hd = hf_config.hidden_size // hf_config.num_attention_heads
    return LlamaConfig(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=hf_config.num_key_value_heads,
        d_ff=hf_config.intermediate_size,
        rope_theta=float(hf_config.rope_theta),
        norm_eps=float(hf_config.rms_norm_eps),
        max_seq=int(getattr(hf_config, "max_position_embeddings", 8192)),
        # Mistral-style checkpoints are layout-identical to Llama but were
        # trained with windowed attention — dropping the window would
        # silently attend beyond what the model ever saw
        sliding_window=_window_from_hf(hf_config),
        attn_bias=attn_bias,
        # Gemma family: GeGLU, zero-centered norm weights, tied lm_head,
        # sqrt(d)-scaled embeddings, and an explicit head_dim
        act=our_act,
        norm_offset=gemma,
        tied_embeddings=tied,
        scale_embed=gemma,
        head_dim_override=hd if hd and hd != default_hd else 0,
        dtype=dtype,
    )


def _window_from_hf(hf_config: Any) -> int:
    """Sliding window with Qwen2's gating honored.

    Qwen2 checkpoints SHIP sliding_window=4096 but apply it only when
    ``use_sliding_window`` — and then only to layers with index >=
    ``max_window_layers`` (the FIRST mwl layers attend fully; verified
    against transformers' configuration_qwen2.py layer_types). So:
    mwl >= n_layers means ZERO layers windowed (Qwen2-7B's own default),
    mwl == 0 means every layer windowed (expressible here), and anything
    between is layer-partial, which this stack cannot express and must
    refuse rather than silently change logits."""
    window = int(getattr(hf_config, "sliding_window", None) or 0)
    if not getattr(hf_config, "use_sliding_window", True):
        return 0
    mwl = getattr(hf_config, "max_window_layers", None)
    if window and mwl is not None:
        if mwl >= hf_config.num_hidden_layers:
            return 0  # no layer actually windows
        if mwl > 0:
            raise NotImplementedError(
                f"layer-partial sliding window (layers >= "
                f"max_window_layers={mwl} of "
                f"{hf_config.num_hidden_layers} windowed) not supported: "
                "this stack applies one window to every layer"
            )
    return window


# per-layer tensor mapping, shared by BOTH directions so the round-trip
# can never drift: ours -> (HF name suffix, transpose?)
_LAYER_MAP = {
    "attn_norm": ("input_layernorm.weight", False),
    "mlp_norm": ("post_attention_layernorm.weight", False),
    "wq": ("self_attn.q_proj.weight", True),
    "wk": ("self_attn.k_proj.weight", True),
    "wv": ("self_attn.v_proj.weight", True),
    "wo": ("self_attn.o_proj.weight", True),
    "w1": ("mlp.gate_proj.weight", True),
    "w3": ("mlp.up_proj.weight", True),
    "w2": ("mlp.down_proj.weight", True),
}

# Qwen2 extension: q/k/v biases (1-D, no transpose), only consumed when
# cfg.attn_bias — a Llama checkpoint never has them and a Qwen2 convert
# without the flag fails loudly on unconsumed tensors.
_BIAS_MAP = {
    "bq": ("self_attn.q_proj.bias", False),
    "bk": ("self_attn.k_proj.bias", False),
    "bv": ("self_attn.v_proj.bias", False),
}


def _layer_map(cfg: LlamaConfig) -> dict:
    return {**_LAYER_MAP, **_BIAS_MAP} if cfg.attn_bias else _LAYER_MAP


def _to_np(t: Any) -> np.ndarray:
    """torch tensor / np array -> f32 numpy (torch never imported here)."""
    if hasattr(t, "detach"):  # torch tensor
        return t.detach().to("cpu").float().numpy()
    return np.asarray(t, np.float32)


def params_from_hf(
    state_dict: Mapping[str, Any], cfg: LlamaConfig
) -> dict:
    """HF ``LlamaForCausalLM.state_dict()`` -> this framework's pytree.

    Accepts torch tensors or numpy arrays. Raises KeyError on missing
    weights (a truncated checkpoint must not silently produce a random
    layer) and ValueError on shape mismatches.
    """
    sd = dict(state_dict)

    def take(name: str, transpose: bool = False) -> np.ndarray:
        w = _to_np(sd.pop(name))
        return w.T if transpose else w

    def stack(fmt: str, transpose: bool = False) -> jnp.ndarray:
        ws = [take(fmt.format(i), transpose) for i in range(cfg.n_layers)]
        return jnp.asarray(np.stack(ws), cfg.p_dtype)

    embed_raw = take("model.embed_tokens.weight")
    params = {
        "embed": jnp.asarray(embed_raw, cfg.p_dtype),
        "layers": {
            ours: stack("model.layers.{}." + suffix, transpose)
            for ours, (suffix, transpose) in _layer_map(cfg).items()
        },
        "final_norm": jnp.asarray(take("model.norm.weight"), cfg.p_dtype),
    }
    if cfg.tied_embeddings:
        # HF state_dicts materialize the tied head as a duplicate tensor;
        # consume it, but refuse a checkpoint whose "tied" head actually
        # diverged from the embedding (an untied fine-tune mislabeled)
        head = sd.pop("lm_head.weight", None)
        if head is not None and not np.array_equal(_to_np(head), embed_raw):
            raise ValueError(
                "config claims tied embeddings but lm_head.weight differs "
                "from embed_tokens.weight — convert as untied instead"
            )
    else:
        params["lm_head"] = jnp.asarray(
            take("lm_head.weight", True), cfg.p_dtype
        )

    expected = {"embed": (cfg.vocab_size, cfg.d_model)}
    if not cfg.tied_embeddings:
        expected["lm_head"] = (cfg.d_model, cfg.vocab_size)
    for name, shape in expected.items():
        if params[name].shape != shape:
            raise ValueError(
                f"{name}: checkpoint shape {params[name].shape} != config "
                f"shape {shape}"
            )
    hd = cfg.head_dim
    if params["layers"]["wq"].shape != (
        cfg.n_layers, cfg.d_model, cfg.n_heads * hd
    ):
        raise ValueError(
            f"wq: checkpoint shape {params['layers']['wq'].shape} "
            f"incompatible with config {cfg}"
        )
    # rotary_emb.inv_freq buffers etc. are derived, not parameters
    leftover = [k for k in sd if "rotary_emb" not in k]
    if leftover:
        raise ValueError(f"unconsumed checkpoint tensors: {leftover[:5]}")
    return params


def params_to_hf(params: dict, cfg: LlamaConfig) -> dict:
    """This framework's pytree -> an HF ``LlamaForCausalLM`` state dict of
    f32 numpy arrays (load with ``model.load_state_dict`` after wrapping in
    torch tensors, or write to safetensors). Inverse of
    :func:`params_from_hf`; the round-trip is test-pinned.
    """
    if "router" in params["layers"]:
        raise NotImplementedError(
            "MoE pytrees have no LlamaForCausalLM equivalent"
        )

    def np32(x) -> np.ndarray:
        # contiguous: transposes are views, and torch/safetensors refuse to
        # serialize non-contiguous tensors
        return np.ascontiguousarray(np.asarray(x, np.float32))

    sd: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np32(params["embed"]),
        "model.norm.weight": np32(params["final_norm"]),
    }
    if "lm_head" in params:
        sd["lm_head.weight"] = np32(np.asarray(params["lm_head"]).T)
    for ours, (theirs, transpose) in _layer_map(cfg).items():
        stacked = np.asarray(params["layers"][ours], np.float32)
        if stacked.shape[0] != cfg.n_layers:
            raise ValueError(
                f"{ours}: pytree has {stacked.shape[0]} stacked layers but "
                f"config says n_layers={cfg.n_layers} — a mismatched config "
                "would silently truncate the exported checkpoint"
            )
        for i in range(cfg.n_layers):
            w = stacked[i]
            sd[f"model.layers.{i}.{theirs}"] = np32(w.T if transpose else w)
    return sd
