"""HuggingFace Llama checkpoint import.

Bridges the ecosystem's weight format to this framework's functional
pytree so trained checkpoints (Llama-3, Mixtral-dense-equivalents, any
LlamaForCausalLM) run on the TPU stack without retraining. Pure layout
transformation — no torch ops beyond reading tensors, so the function also
serves as the parity oracle seam: ``tests/test_convert.py`` builds a
random-init HF model, converts it, and pins our forward's logits against
``transformers``' reference implementation.

Layout mapping (HF -> here):

- torch ``Linear.weight`` is (out, in); our matmuls are ``x @ W`` with W
  (in, out) -> transpose every projection.
- per-layer tensors stack on a leading L axis (the ``lax.scan`` layout).
- rope is the rotate-half convention in both; RMSNorm epsilon and theta
  come from the HF config.

No network access is required or attempted: callers pass an in-memory
model/state_dict (e.g. loaded from local safetensors).
"""

from __future__ import annotations

from typing import Any, Mapping

import jax.numpy as jnp
import numpy as np

from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig


def config_from_hf(hf_config: Any, dtype: Any = jnp.bfloat16) -> LlamaConfig:
    """Map a ``transformers.LlamaConfig`` onto :class:`LlamaConfig`."""
    if getattr(hf_config, "tie_word_embeddings", False):
        raise NotImplementedError(
            "tied embeddings not supported: this stack keeps a separate "
            "lm_head (untie the checkpoint before converting)"
        )
    scaling = getattr(hf_config, "rope_scaling", None)
    if scaling:
        # Llama-3.1+ ships rope_scaling (rope_type "llama3" frequency
        # rescale); converting while silently dropping it would compute
        # wrong rotary frequencies at every position — refuse instead.
        raise NotImplementedError(
            f"rope_scaling {scaling!r} not supported: this stack computes "
            "plain rotary frequencies from rope_theta"
        )
    act = getattr(hf_config, "hidden_act", "silu")
    if act not in ("silu", "swish"):
        raise NotImplementedError(
            f"hidden_act {act!r} not supported: the MLP hardcodes silu"
        )
    return LlamaConfig(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=hf_config.num_key_value_heads,
        d_ff=hf_config.intermediate_size,
        rope_theta=float(hf_config.rope_theta),
        norm_eps=float(hf_config.rms_norm_eps),
        max_seq=int(getattr(hf_config, "max_position_embeddings", 8192)),
        dtype=dtype,
    )


def _to_np(t: Any) -> np.ndarray:
    """torch tensor / np array -> f32 numpy (torch never imported here)."""
    if hasattr(t, "detach"):  # torch tensor
        return t.detach().to("cpu").float().numpy()
    return np.asarray(t, np.float32)


def params_from_hf(
    state_dict: Mapping[str, Any], cfg: LlamaConfig
) -> dict:
    """HF ``LlamaForCausalLM.state_dict()`` -> this framework's pytree.

    Accepts torch tensors or numpy arrays. Raises KeyError on missing
    weights (a truncated checkpoint must not silently produce a random
    layer) and ValueError on shape mismatches.
    """
    sd = dict(state_dict)

    def take(name: str, transpose: bool = False) -> np.ndarray:
        w = _to_np(sd.pop(name))
        return w.T if transpose else w

    def stack(fmt: str, transpose: bool = False) -> jnp.ndarray:
        ws = [take(fmt.format(i), transpose) for i in range(cfg.n_layers)]
        return jnp.asarray(np.stack(ws), cfg.p_dtype)

    params = {
        "embed": jnp.asarray(take("model.embed_tokens.weight"), cfg.p_dtype),
        "layers": {
            "attn_norm": stack("model.layers.{}.input_layernorm.weight"),
            "mlp_norm": stack(
                "model.layers.{}.post_attention_layernorm.weight"
            ),
            "wq": stack("model.layers.{}.self_attn.q_proj.weight", True),
            "wk": stack("model.layers.{}.self_attn.k_proj.weight", True),
            "wv": stack("model.layers.{}.self_attn.v_proj.weight", True),
            "wo": stack("model.layers.{}.self_attn.o_proj.weight", True),
            "w1": stack("model.layers.{}.mlp.gate_proj.weight", True),
            "w3": stack("model.layers.{}.mlp.up_proj.weight", True),
            "w2": stack("model.layers.{}.mlp.down_proj.weight", True),
        },
        "final_norm": jnp.asarray(take("model.norm.weight"), cfg.p_dtype),
        "lm_head": jnp.asarray(take("lm_head.weight", True), cfg.p_dtype),
    }

    expected = {
        "embed": (cfg.vocab_size, cfg.d_model),
        "lm_head": (cfg.d_model, cfg.vocab_size),
    }
    for name, shape in expected.items():
        if params[name].shape != shape:
            raise ValueError(
                f"{name}: checkpoint shape {params[name].shape} != config "
                f"shape {shape}"
            )
    hd = cfg.head_dim
    if params["layers"]["wq"].shape != (
        cfg.n_layers, cfg.d_model, cfg.n_heads * hd
    ):
        raise ValueError(
            f"wq: checkpoint shape {params['layers']['wq'].shape} "
            f"incompatible with config {cfg}"
        )
    # rotary_emb.inv_freq buffers etc. are derived, not parameters
    leftover = [k for k in sd if "rotary_emb" not in k]
    if leftover:
        raise ValueError(f"unconsumed checkpoint tensors: {leftover[:5]}")
    return params
