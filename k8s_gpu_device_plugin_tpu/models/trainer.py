"""End-to-end training driver: data pipeline + sharded step + checkpoint +
resume + optional xprof traces.

This is the workload the plugin schedules (BASELINE configs #4/#5) — the
reference's "benchmark" package was a Go self-profiler with no workload
(benchmark/benchmark.go:54-124); here the benchmark IS a real training run.
Composition, all TPU-first pieces defined elsewhere:

- model/step: models/llama.py + models/train.py (pjit over a Mesh);
- data: data/pipeline.py (prefetching, deterministic, per-process rows);
- checkpoint: models/checkpoint.py (sharded async orbax, exact resume);
- multi-host: parallel/multihost.py (zero-arg jax.distributed init);
- tracing: jax.profiler around a steady-state step window, producing
  xplane dumps readable by tensorboard/xprof (SURVEY §5 tracing note).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import jax

from k8s_gpu_device_plugin_tpu.data.pipeline import (
    DataLoader,
    SyntheticSource,
    make_token_source,
)
from k8s_gpu_device_plugin_tpu.models.checkpoint import TrainCheckpointer
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig
from k8s_gpu_device_plugin_tpu.models.train import (
    init_train_state,
    make_eval_step,
    make_optimizer,
    make_train_step,
)
from k8s_gpu_device_plugin_tpu.obs.trace import get_tracer
from k8s_gpu_device_plugin_tpu.parallel.mesh import AXIS_TP, MeshSpec
from k8s_gpu_device_plugin_tpu.parallel.multihost import initialize, make_global_mesh
from k8s_gpu_device_plugin_tpu.utils.log import get_logger


@dataclass
class TrainerConfig:
    """Everything a run needs; defaults give a laptop-size smoke run."""

    model: LlamaConfig = field(default_factory=lambda: LlamaConfig.tiny(n_layers=2))
    mesh: MeshSpec = field(default_factory=MeshSpec)
    num_slices: int = 1
    batch_size: int = 8
    seq_len: int = 128
    # microbatches per optimizer update (1 = no accumulation)
    grad_accum: int = 1
    total_steps: int = 20
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    # held-out evaluation (0 disables): every eval_every steps (and after
    # the final step) run eval_batches deterministic validation batches.
    # eval_micro chunks each eval batch so the unfused eval forward's
    # (B, S, V) logits fit wherever training fits (0 = follow grad_accum).
    eval_every: int = 0
    eval_batches: int = 4
    eval_micro: int = 0
    # checkpointing ("" disables)
    checkpoint_dir: str = ""
    checkpoint_interval: int = 1000
    max_checkpoints: int = 3
    # profiling ("" disables): xplane trace of steps [trace_start, trace_stop)
    trace_dir: str = ""
    trace_start: int = 3
    trace_stop: int = 6
    log_every: int = 10
    # optimizer implementation: "optax" (staged chain) or "fused"
    # (ops/fused_optim.py single-pass AdamW; same numerics)
    opt_impl: str = "optax"
    # token corpus ("" = synthetic): a flat binary token file served
    # through data/pipeline.make_token_source — the native C++ gather by
    # default when libdataload.so is built, the Python memmap otherwise
    # (bit-identical batches either way)
    data_file: str = ""
    data_dtype: str = "uint16"


@dataclass
class TrainResult:
    steps_run: int
    final_loss: float
    tokens_per_second: float
    resumed_from: int | None
    metrics_history: list[dict]
    final_eval: dict | None = None  # {"loss", "perplexity", "accuracy"}
    data_source: str = "synthetic"  # which gather fed the run (factory label)


class Trainer:
    """Owns one training run; ``run()`` is restartable (resume-aware)."""

    def __init__(
        self,
        cfg: TrainerConfig,
        loader: DataLoader | None = None,
        eval_loader: DataLoader | None = None,
        logger: logging.Logger | None = None,
    ) -> None:
        self.cfg = cfg
        self.log = logger or get_logger()
        # no-op on single-process pods; rendezvous via plugin-injected envs
        initialize()
        self.mesh = make_global_mesh(cfg.mesh, cfg.num_slices)
        if cfg.model.fused_ce and self.mesh.shape.get(AXIS_TP, 1) > 1:
            # loss_fn would silently fall back to the unfused path while
            # accuracy is disabled below — fail loudly, for library callers
            # and the CLI alike.
            raise ValueError(
                "fused_ce requires tp == 1 (the fused scan cannot slice a "
                "tp-sharded vocab axis)"
            )
        self.optimizer = make_optimizer(
            learning_rate=cfg.learning_rate,
            warmup_steps=cfg.warmup_steps,
            total_steps=cfg.total_steps,
            impl=cfg.opt_impl,
        )
        # fused CE has no logits to argmax, so accuracy is off on that path
        self.step_fn = make_train_step(
            cfg.model, self.mesh, self.optimizer,
            with_accuracy=not cfg.model.fused_ce,
            grad_accum=cfg.grad_accum,
        )
        if loader is not None:
            self.loader = loader
            self.data_source_label = "caller-provided"
        else:
            source, self.data_source_label = make_token_source(
                cfg.data_file, cfg.model.vocab_size, dtype=cfg.data_dtype
            )
            self.loader = DataLoader(
                source, cfg.batch_size, cfg.seq_len, self.mesh
            )
            if cfg.data_file:
                self.log.info(
                    "token source",
                    extra={"fields": {"source": self.data_source_label,
                                      "file": cfg.data_file}},
                )
        self.eval_loader: DataLoader | None = None
        self.eval_step_fn = None
        if eval_loader is not None and cfg.eval_every <= 0:
            raise ValueError(
                "eval_loader passed but eval_every is 0 — the loader would "
                "be silently ignored; set eval_every > 0"
            )
        if cfg.eval_every > 0:
            if cfg.eval_batches < 1:
                raise ValueError(
                    f"eval_batches must be >= 1 when eval_every > 0, got "
                    f"{cfg.eval_batches}"
                )
            # held-out stream: a different seed than the train default, no
            # prefetch thread (eval passes are short and restart at step 0
            # every time so the SAME validation batches score every pass).
            # With a corpus file, eval reads the SAME corpus (seed-1
            # windows) — not synthetic tokens unrelated to what the run
            # trains on. Different-seed windows of one corpus can overlap
            # the training stream; for a strictly held-out set, pass an
            # eval_loader over a separate file.
            if eval_loader is not None:
                self.eval_loader = eval_loader
            else:
                eval_source, _ = make_token_source(
                    cfg.data_file, cfg.model.vocab_size,
                    dtype=cfg.data_dtype, seed=1,
                )
                self.eval_loader = DataLoader(
                    eval_source,
                    cfg.batch_size,
                    cfg.seq_len,
                    self.mesh,
                    prefetch=0,
                )
            self.eval_step_fn = make_eval_step(
                cfg.model, self.mesh,
                micro=cfg.eval_micro or cfg.grad_accum,
            )
        self.ckpt: TrainCheckpointer | None = None
        if cfg.checkpoint_dir:
            self.ckpt = TrainCheckpointer(
                cfg.checkpoint_dir,
                max_to_keep=cfg.max_checkpoints,
                save_interval=cfg.checkpoint_interval,
                logger=self.log,
            )

    def _init_or_resume(self) -> tuple[dict, int | None]:
        state = init_train_state(
            jax.random.key(0), self.cfg.model, self.mesh, self.optimizer
        )
        resumed_from = None
        if self.ckpt is not None:
            state, resumed = self.ckpt.restore_or_pass(state)
            if resumed:
                resumed_from = int(jax.device_get(state["step"]))
                self.loader.seek(resumed_from)
        return state, resumed_from

    def _evaluate(self, params) -> dict:
        """Mean held-out metrics over ``eval_batches`` deterministic batches
        (the loader restarts at step 0 each pass, so every eval scores the
        same validation set)."""
        import math

        assert self.eval_loader is not None and self.eval_step_fn is not None
        self.eval_loader.seek(0)
        it = iter(self.eval_loader)
        loss_sum, acc_sum = 0.0, 0.0
        for _ in range(self.cfg.eval_batches):
            m = self.eval_step_fn(params, next(it))
            loss_sum += float(m["loss"])
            acc_sum += float(m["accuracy"])
        loss = loss_sum / self.cfg.eval_batches
        return {
            "loss": loss,
            "perplexity": math.exp(min(loss, 700.0)),
            "accuracy": acc_sum / self.cfg.eval_batches,
        }

    def run(self, on_step: Callable[[int, dict], None] | None = None) -> TrainResult:
        cfg = self.cfg
        state, resumed_from = self._init_or_resume()
        start_step = int(jax.device_get(state["step"]))
        history: list[dict] = []
        tokens_per_batch = cfg.batch_size * cfg.seq_len

        it = iter(self.loader)
        metrics: dict[str, Any] = {}
        t_start = None
        steps_timed = 0
        eval_seconds = 0.0
        tracing = False
        # Step-phase spans (obs/): one trace per step with the host-side
        # phases — data wait, dispatch, checkpoint, eval. The fused
        # forward/backward/optimizer split lives in the xplane trace
        # (trace_dir); spans cover what the HOST spends per step.
        tr = get_tracer()
        try:
            for step in range(start_step, cfg.total_steps):
                if cfg.trace_dir and step == cfg.trace_start and not tracing:
                    jax.profiler.start_trace(cfg.trace_dir)
                    tracing = True
                with tr.span("train_step", component="trainer", step=step):
                    with tr.span("data_load", component="trainer"):
                        batch = next(it)
                    with tr.span("step_dispatch", component="trainer"):
                        state, metrics = self.step_fn(state, batch)
                    if step + 1 == cfg.trace_stop and tracing:
                        jax.block_until_ready(state["params"])
                        jax.profiler.stop_trace()
                        tracing = False
                        self.log.info(
                            "trace written",
                            extra={"fields": {"dir": cfg.trace_dir}},
                        )
                    if t_start is None:
                        # start the clock after step 0 retires: excludes
                        # compile
                        jax.block_until_ready(metrics["loss"])
                        t_start = time.perf_counter()
                    else:
                        steps_timed += 1
                    if self.ckpt is not None:
                        with tr.span("checkpoint", component="trainer"):
                            self.ckpt.save(state, step=step + 1)
                    if (step + 1) % cfg.log_every == 0 \
                            or step + 1 == cfg.total_steps:
                        snap = {
                            "step": step + 1,
                            "loss": float(metrics["loss"]),
                            "grad_norm": float(metrics["grad_norm"]),
                        }
                        history.append(snap)
                        self.log.info("train step", extra={"fields": snap})
                    if (
                        self.eval_loader is not None
                        and (step + 1) % cfg.eval_every == 0
                        and step + 1 != cfg.total_steps  # final eval below
                    ):
                        # eval wall time must not deflate the reported
                        # train tokens/s: finish in-flight work, then
                        # pause the clock
                        jax.block_until_ready(metrics["loss"])
                        t_eval = time.perf_counter()
                        with tr.span("eval", component="trainer"):
                            ev = self._evaluate(state["params"])
                        eval_seconds += time.perf_counter() - t_eval
                        self.log.info(
                            "eval", extra={"fields": {"step": step + 1, **ev}}
                        )
                        history.append({"step": step + 1, "eval": ev})
                    if on_step is not None:
                        on_step(step + 1, metrics)
        finally:
            if tracing:
                jax.profiler.stop_trace()
            if self.ckpt is not None:
                # final state is always recoverable, cadence notwithstanding
                if int(jax.device_get(state["step"])) > start_step:
                    self.ckpt.save(state, force=True)
                self.ckpt.wait()

        jax.block_until_ready(metrics["loss"] if metrics else state["step"])
        elapsed = (
            time.perf_counter() - t_start - eval_seconds if t_start else 0.0
        )
        tps = tokens_per_batch * steps_timed / elapsed if elapsed > 0 else 0.0
        final_eval = None
        if self.eval_loader is not None and cfg.total_steps > start_step:
            final_eval = self._evaluate(state["params"])
            self.log.info(
                "final eval",
                extra={"fields": {"step": cfg.total_steps, **final_eval}},
            )
        return TrainResult(
            steps_run=cfg.total_steps - start_step,
            final_loss=float(metrics["loss"]) if metrics else float("nan"),
            tokens_per_second=tps,
            resumed_from=resumed_from,
            metrics_history=history,
            final_eval=final_eval,
            data_source=self.data_source_label,
        )


def _main(argv: list[str] | None = None) -> int:
    """CLI: run a (default tiny synthetic) training job in-pod.

    ``python -m k8s_gpu_device_plugin_tpu.models.trainer --preset tiny
    --steps 20`` — presets llama3_8b/llama3_70b/mixtral_8x7b match
    BASELINE configs #4/#5.
    """
    import argparse

    parser = argparse.ArgumentParser(prog="tpu-trainer")
    parser.add_argument("--preset", default="tiny",
                        choices=["tiny", "llama3_8b", "llama3_70b",
                                 "mistral_7b", "mixtral_8x7b"])
    parser.add_argument("--steps", type=int, default=20)
    parser.add_argument("--batchSize", type=int, default=8)
    parser.add_argument("--seqLen", type=int, default=128)
    parser.add_argument("--gradAccum", type=int, default=1,
                        help="microbatches per optimizer update (splits the "
                        "batch; grads accumulate in f32)")
    parser.add_argument("--evalEvery", type=int, default=0,
                        help="held-out eval cadence in steps (0 = off)")
    parser.add_argument("--evalBatches", type=int, default=4)
    parser.add_argument("--tp", type=int, default=1)
    parser.add_argument("--sp", type=int, default=1)
    parser.add_argument("--pp", type=int, default=1)
    parser.add_argument("--ep", type=int, default=1)
    parser.add_argument("--fsdp", type=int, default=None)
    parser.add_argument("--numSlices", type=int, default=1)
    parser.add_argument("--checkpointDir", default="")
    parser.add_argument("--checkpointInterval", type=int, default=1000)
    parser.add_argument("--traceDir", default="")
    parser.add_argument("--quant", default="none", choices=["none", "int8"],
                        help="int8 runs block matmuls on the MXU double-rate "
                        "path (quantized fwd, bf16 bwd)")
    parser.add_argument("--masterWeights", action="store_true",
                        help="store params/grads/optimizer moments in f32 "
                        "(bf16 compute stays on the MXU); retains updates "
                        "smaller than a bf16 ulp at 2x param memory")
    parser.add_argument("--optImpl", default="optax",
                        choices=["optax", "fused"],
                        help="optimizer implementation: optax chain or the "
                        "fused single-pass AdamW (same numerics, fewer HBM "
                        "passes)")
    parser.add_argument("--dataFile", default="",
                        help="flat binary token corpus; served by the "
                        "native C++ gather when libdataload.so is built, "
                        "the Python memmap otherwise (empty = synthetic)")
    parser.add_argument("--dataDtype", default="uint16",
                        choices=["uint16", "uint32"],
                        help="corpus token dtype")
    parser.add_argument("--fusedCE", action="store_true",
                        help="fused lm_head+cross-entropy (no materialized "
                        "logits; tp==1 only, accuracy reported as -1)")
    args = parser.parse_args(argv)
    if args.fusedCE and args.tp > 1:
        # loss_fn would silently fall back to the unfused path (the scan
        # slices the vocab axis, which tp shards) while accuracy is already
        # disabled — fail loudly instead of running a degraded combination.
        parser.error("--fusedCE requires --tp 1 (the fused scan cannot "
                     "slice a tp-sharded vocab axis)")

    initialize()  # multi-host rendezvous BEFORE jax.devices()
    model = getattr(LlamaConfig, args.preset)()
    if args.quant != "none" or args.fusedCE or args.masterWeights:
        import jax.numpy as jnp
        from dataclasses import replace as _replace

        model = _replace(
            model, quant=args.quant, fused_ce=args.fusedCE,
            param_dtype=jnp.float32 if args.masterWeights else None,
        )
    # the shared mesh-flag rule (also behind the inference server's
    # --tp): axis sizes validated against the device count at startup
    # with an actionable error instead of deep inside a pjit trace
    spec = MeshSpec.from_flags(
        tp=args.tp, sp=args.sp, pp=args.pp, ep=args.ep, fsdp=args.fsdp,
        n_devices=len(jax.devices()),
    )
    cfg = TrainerConfig(
        model=model,
        mesh=spec,
        num_slices=args.numSlices,
        batch_size=args.batchSize,
        seq_len=args.seqLen,
        grad_accum=args.gradAccum,
        eval_every=args.evalEvery,
        eval_batches=args.evalBatches,
        total_steps=args.steps,
        checkpoint_dir=args.checkpointDir,
        checkpoint_interval=args.checkpointInterval,
        trace_dir=args.traceDir,
        opt_impl=args.optImpl,
        data_file=args.dataFile,
        data_dtype=args.dataDtype,
    )
    result = Trainer(cfg).run()
    eval_str = (
        f" eval_loss={result.final_eval['loss']:.4f}"
        f" ppl={result.final_eval['perplexity']:.2f}"
        if result.final_eval
        else ""
    )
    print(
        f"trainer: steps={result.steps_run} loss={result.final_loss:.4f} "
        f"tokens/s={result.tokens_per_second:.0f} "
        f"resumed_from={result.resumed_from} data={result.data_source}"
        f"{eval_str}"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
