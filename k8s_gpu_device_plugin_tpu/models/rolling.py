"""Bounded-memory decode for sliding-window models: a ring KV cache.

With window W, position p only ever serves queries in [p, p + W), so the
cache needs exactly W slots: token p lives at slot ``p % W`` and is
overwritten the step it leaves every future query's window. Decode memory
is O(W) regardless of how many tokens are generated — the practical
Mistral serving property (a 32k-token generation holds a 4k cache).

TPU-first shape: slot positions are a pure function of (length, slot)
(``p_s = L - 1 - ((L - 1 - s) mod W)``), so nothing tracks them — the
attention mask recomputes them from the traced length each step, and all
writes are single ``dynamic_update_slice`` calls at ``p % W``. Prefill
runs through the ordinary cache at prompt size (prompt activations are
O(P) anyway), then the last ``min(P, W)`` roped K/V rows roll into the
ring; the decode loop is one ``lax.scan``.

The oracle test pins ``rolling_generate`` token-exact (f32) against the
unbounded windowed ``generate`` across p < W, p > W, and generations that
wrap the ring several times.
"""

from __future__ import annotations

from functools import partial

import math

import jax
import jax.numpy as jnp

from k8s_gpu_device_plugin_tpu.models.generate import (
    KVCache,
    _cache_write,
    _forward_cached,
    _mlp_out,
    _project_qkv,
    rms_norm,
)
from k8s_gpu_device_plugin_tpu.models.llama import (
    LlamaConfig,
    cast_params_for_compute,
    head_weights,
)
from k8s_gpu_device_plugin_tpu.models.quantized_serving import (
    qhead_matmul,
    qmatmul,
)
from k8s_gpu_device_plugin_tpu.models.sampling import (
    Sampler,
    init_presence,
    sample_and_mark,
)


def _ring_from_prefill(cache_kv: jax.Array, p: int, w: int) -> jax.Array:
    """(L, B, P, H, hd) prefill rows -> (L, B, W, H, hd) ring.

    Keeps the last m = min(P, W) positions; position q lands at slot
    q % W. For P < W the tail slots stay zero (masked by position math);
    for P >= W the W consecutive positions are a rotation of the slots."""
    if p < w:
        pad = [(0, 0)] * cache_kv.ndim
        pad[2] = (0, w - p)
        return jnp.pad(cache_kv, pad)
    last = cache_kv[:, :, p - w:p]
    return jnp.roll(last, shift=(p - w) % w, axis=2)


def _ring_attention_step(q, ring_k, ring_v, k_scale, v_scale, length,
                         cfg: LlamaConfig):
    """T=1 attention over the ring AFTER the current token's K/V landed.

    q: (B, 1, Hq, hd); ring: (B, W, Hkv, hd). ``length`` counts tokens
    written so far INCLUDING the current one (the query sits at position
    length - 1). Slot s holds position L-1 - ((L-1-s) mod W); negatives
    are unwritten slots. The window mask is implied: every live slot is
    within W of the query by construction.

    Quantized rings — int8 or int4 (``k_scale``/``v_scale``
    (B, W, Hkv, 1), None on bf16) — follow generate._cached_attention
    exactly: the narrow-dtype arrays stay the
    dot operands (a bare convert fuses into the dot), and the
    per-(slot, head) scales apply to scores after the K contraction and
    to probs before the V contraction."""
    b, t, hq, hd = q.shape
    w = ring_k.shape[1]
    group = hq // cfg.n_kv_heads
    qg = q.reshape(b, 1, cfg.n_kv_heads, group, hd)
    scores = jnp.einsum(
        "btkgd,bskd->btkgs", qg, ring_k.astype(q.dtype),
        preferred_element_type=jnp.float32,
    ) * (hd ** -0.5)
    if k_scale is not None:
        ks = k_scale[..., 0].transpose(0, 2, 1)         # (B, Hkv, W)
        scores = scores * ks[:, None, :, None, :]
    last = length - 1
    s_idx = jnp.arange(w)
    slot_pos = last - ((last - s_idx) % w)              # (W,)
    keep = slot_pos >= 0
    scores = jnp.where(keep[None, None, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    if v_scale is not None:
        vs = v_scale[..., 0].transpose(0, 2, 1)         # (B, Hkv, W)
        probs = probs * vs[:, None, :, None, :]
    out = jnp.einsum(
        "btkgs,bskd->btkgd", probs.astype(q.dtype), ring_v.astype(q.dtype),
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, 1, hq, hd).astype(q.dtype)


def _ring_decode_block(x, layer, ring_k, ring_v, rk_s, rv_s, pos,
                       cfg: LlamaConfig):
    """One block over ONE new token at absolute position ``pos``; writes
    its K/V at slot pos % W, then attends the ring. Projection/rope and
    the MLP branch are the SAME helpers the linear-cache block uses
    (generate._project_qkv/_mlp_out), so the two paths cannot drift.
    Quantized rings (int8/int4) write through generate's ``_cache_write``
    (one recipe for quantize + value/scale placement; the scale planes
    ``rk_s``/``rv_s`` are None on bf16) — the shared-helper rule again."""
    b, t, d = x.shape
    w = ring_k.shape[1]

    positions = pos + jnp.arange(t, dtype=jnp.int32)
    q, k, v = _project_qkv(x, layer, positions, cfg)

    slot = (pos % w).astype(jnp.int32)
    ring_k, rk_s = _cache_write(ring_k, rk_s, k, slot)
    ring_v, rv_s = _cache_write(ring_v, rv_s, v, slot)

    attn = _ring_attention_step(q, ring_k, ring_v, rk_s, rv_s, pos + 1, cfg)
    x = x + qmatmul(attn.reshape(b, t, cfg.n_heads * cfg.head_dim), layer["wo"])
    return x + _mlp_out(x, layer, cfg), ring_k, ring_v, rk_s, rv_s


def _ring_forward(params, tok, ring: KVCache, pos, cfg: LlamaConfig):
    """One token through all layers against the ring; returns
    ((B, V) f32 logits, updated ring)."""
    params = cast_params_for_compute(params, cfg)
    x = params["embed"].astype(cfg.dtype)[tok[:, None]]
    if cfg.scale_embed:
        x = x * jnp.asarray(math.sqrt(cfg.d_model), cfg.dtype)

    # None scale planes are empty pytree leaves — lax.scan carries them
    # through untouched, so the bf16 and int8 rings share one body (the
    # same structure generate's _forward_cached scan uses)
    def body(carry, xs):
        layer, rk, rv, rks, rvs = xs
        x, rk, rv, rks, rvs = _ring_decode_block(
            carry, layer, rk, rv, rks, rvs, pos, cfg
        )
        return x, (rk, rv, rks, rvs)

    x, (k_new, v_new, ks_new, vs_new) = jax.lax.scan(
        body, x,
        (params["layers"], ring.k, ring.v, ring.k_scale, ring.v_scale),
    )
    new_ring = KVCache(k=k_new, v=v_new, k_scale=ks_new, v_scale=vs_new)
    x = rms_norm(x, params["final_norm"], cfg.norm_eps, cfg.norm_offset)
    logits = qhead_matmul(x[:, -1], head_weights(params, cfg), cfg.dtype)
    return logits, new_ring


@partial(jax.jit, static_argnames=("cfg", "max_new", "sampler"))
def rolling_generate(
    params,
    prompt: jax.Array,
    cfg: LlamaConfig,
    max_new: int,
    key: jax.Array | None = None,
    sampler: "Sampler | None" = None,
) -> jax.Array:
    """Windowed generation with an O(window) ring cache.

    Same contract as ``generate`` (greedy by default, ``Sampler`` for
    sampling) for configs with ``sliding_window > 0``; the cache never
    grows past the window no matter how long the generation runs.
    """
    if cfg.sliding_window <= 0:
        raise ValueError(
            "rolling_generate needs cfg.sliding_window > 0 (full-causal "
            "models need every past position: use generate)"
        )
    if cfg.quant != "none":
        raise NotImplementedError("decode path is bf16-only (quant='none')")
    b, p = prompt.shape
    if max_new < 1:
        raise ValueError(f"max_new must be >= 1, got {max_new}")
    w = cfg.sliding_window
    sampler = sampler if sampler is not None else Sampler()
    key = key if key is not None else jax.random.key(0)

    # prefill at prompt size (activations are O(P) regardless), then keep
    # only the live window in the ring
    pre_cache = KVCache.init(cfg, b, p)
    logits, pre_cache = _forward_cached(
        params, prompt, pre_cache, 0, cfg, last_only=True
    )
    # scale planes (int8 cache) ring-roll identically to the K/V arrays:
    # _ring_from_prefill is shape-generic over the trailing dims
    ring = KVCache(
        k=_ring_from_prefill(pre_cache.k, p, w),
        v=_ring_from_prefill(pre_cache.v, p, w),
        k_scale=(
            _ring_from_prefill(pre_cache.k_scale, p, w)
            if pre_cache.k_scale is not None else None
        ),
        v_scale=(
            _ring_from_prefill(pre_cache.v_scale, p, w)
            if pre_cache.v_scale is not None else None
        ),
    )

    # presence mask for the repetition penalty (same shared helpers as
    # generate._generate_jit; carried unconditionally, ignored when off)
    presence = init_presence(prompt, cfg.vocab_size)

    def pick(logits, key, presence):
        return sample_and_mark(logits, key, sampler, presence)

    key, sub = jax.random.split(key)
    first, presence = pick(logits[:, -1], sub, presence)

    def step(carry, i):
        last, ring, key, presence = carry
        logits, ring = _ring_forward(params, last, ring, p + i, cfg)
        key, sub = jax.random.split(key)
        tok, presence = pick(logits, sub, presence)
        return (tok, ring, key, presence), tok

    if max_new == 1:
        return first[:, None]
    _, toks = jax.lax.scan(
        step, (first, ring, key, presence),
        jnp.arange(max_new - 1, dtype=jnp.int32),
    )
    return jnp.concatenate([first[:, None], toks.T], axis=1)
