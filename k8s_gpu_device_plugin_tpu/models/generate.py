"""KV-cache autoregressive generation (prefill + jitted decode loop).

TPU-first decode design:

- **Static shapes**: the cache is allocated at ``max_len`` up front and the
  decode loop is one ``lax.scan`` over steps — one compile, no per-step
  retrace, position handled by masking (dynamic-slice writes, masked
  reads). The classic TPU decode shape.
- **GQA-native cache**: K/V are cached at ``n_kv_heads`` (the same
  no-expansion rule as ops/flash_attention.py) — a Llama-3-8B cache is
  4x smaller than a naively expanded one; q heads fold onto their group
  at score time via a reshape, not a materialized repeat.
- **bf16 cache, f32 scores/softmax**: matches the training numerics
  contract (models/llama.py).

The layer math deliberately reuses the training building blocks
(``rms_norm``/``rope`` and the same weight layout) so the decode block
cannot drift from ``_block``; the oracle test pins cached decode against
the full-context training forward exactly.

The reference has no model stack at all (it is a device-plugin daemon,
SURVEY §2); this completes the workload framework's model-family API
(train + generate) the rebuilt benchmark ships.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import math

import jax
import jax.numpy as jnp

from k8s_gpu_device_plugin_tpu.models.llama import (
    LlamaConfig,
    head_weights,
    mlp_act,
    rms_norm,
    rope,
)
from k8s_gpu_device_plugin_tpu.parallel.mesh import constrain
from k8s_gpu_device_plugin_tpu.parallel.tp_serving import HEADS, REPLICATED
from k8s_gpu_device_plugin_tpu.models.quantized_serving import (
    qexpert_einsum,
    qhead_matmul,
    qmatmul,
)
from k8s_gpu_device_plugin_tpu.models.sampling import (
    Sampler,
    init_presence,
    sample_and_mark,
    sample_logits,
)


@dataclass(frozen=True)
class KVCache:
    """Per-layer stacked K/V at native kv heads: (L, B, max_len, Hkv, hd).

    With ``cfg.cache_quant == "int8"`` the K/V arrays are int8 and
    ``k_scale``/``v_scale`` hold per-(position, head) f32 scales
    (L, B, max_len, Hkv, 1): half the cache HBM traffic and twice the
    context capacity, dequantized on read (the dequant fuses into the
    attention einsums). ``"int4"`` halves it again (XLA bit-packs the
    native narrow dtype two-per-byte in HBM; same scale planes, coarser
    codes — an accuracy trade the caller opts into). Scales are None on
    the bf16 path."""

    k: jax.Array
    v: jax.Array
    k_scale: jax.Array | None = None
    v_scale: jax.Array | None = None

    @staticmethod
    def init(cfg: LlamaConfig, batch: int, max_len: int) -> "KVCache":
        shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
        if cfg.cache_quant in ("int8", "int4"):
            qdtype = jnp.int8 if cfg.cache_quant == "int8" else jnp.int4
            sshape = shape[:-1] + (1,)
            return KVCache(
                k=jnp.zeros(shape, qdtype), v=jnp.zeros(shape, qdtype),
                k_scale=jnp.zeros(sshape, jnp.float32),
                v_scale=jnp.zeros(sshape, jnp.float32),
            )
        return KVCache(
            k=jnp.zeros(shape, cfg.dtype), v=jnp.zeros(shape, cfg.dtype)
        )

    @staticmethod
    def init_paged(cfg: LlamaConfig, n_pages: int, page_size: int) -> "KVCache":
        """Paged-layout pool: (L, n_pages, page_size, Hkv, hd). Slots map
        virtual positions onto pages through ``BatchState.pages`` tables
        (models/batching.py); page 0 is the reserved trap page
        (models/paging.py). With ``cfg.cache_quant`` the pool holds
        int8/int4 codes and the per-(position, head) f32 scale planes
        ride the SAME page geometry — (L, n_pages, page_size, Hkv, 1) —
        so one table lookup addresses a page's codes and its scale rows
        alike (the quantized-paged design: every write/alias/COW path
        tree-maps over all four leaves with one page index)."""
        shape = (cfg.n_layers, n_pages, page_size, cfg.n_kv_heads,
                 cfg.head_dim)
        if cfg.cache_quant in ("int8", "int4"):
            qdtype = jnp.int8 if cfg.cache_quant == "int8" else jnp.int4
            sshape = shape[:-1] + (1,)
            return KVCache(
                k=jnp.zeros(shape, qdtype), v=jnp.zeros(shape, qdtype),
                k_scale=jnp.zeros(sshape, jnp.float32),
                v_scale=jnp.zeros(sshape, jnp.float32),
            )
        return KVCache(
            k=jnp.zeros(shape, cfg.dtype), v=jnp.zeros(shape, cfg.dtype)
        )


jax.tree_util.register_dataclass(KVCache, ("k", "v", "k_scale", "v_scale"), ())


def _quantize_kv(x: jax.Array, qdtype=None) -> tuple[jax.Array, jax.Array]:
    """(B, T, H, hd) -> (int4/int8 values, f32 per-(token, head) scales).

    One symmetric per-row recipe for both code widths
    (ops/quant._quantize_symmetric), shared with the int8
    weight/activation path so those numerics cannot drift. The int4
    WEIGHT path is deliberately different (grouped scales, GPTQ/AWQ
    storage — quantized_serving.quantize_weights_int4); this is the
    cache recipe. ``qdtype`` picks the code width (the cache's own
    dtype; int8 when unspecified)."""
    from k8s_gpu_device_plugin_tpu.ops.quant import (
        quantize_int4_sym,
        quantize_int8,
    )

    if qdtype == jnp.int4:
        return quantize_int4_sym(x, axis=-1)
    return quantize_int8(x, axis=-1)


def _cache_write(cache, scale, x, length, pages=None, page_size=0):  # graftlint: hot-path=traced
    """Write T new tokens' K or V at ``length``; quantizing to the
    cache's own dtype when it is int8/int4 (scale is the matching scale
    plane, else None).

    ``length`` may be a scalar (uniform batch — the classic decode) or a
    (B,) vector (continuous batching: every slot writes at its own
    position; a vmapped dynamic_update_slice is one per-row scatter).

    With ``pages`` (B, n_slot_pages) int32 the cache is a PAGED pool
    (n_pages, page_size, Hkv, hd): position p of row b lands in page
    ``pages[b, p // page_size]`` at offset ``p % page_size`` — one
    scatter through the table instead of a dynamic-slice write. The
    batcher zeroes inactive rows' table entries before the step, so
    their garbage writes land in the reserved trap page 0, never in a
    page that may have been reallocated to a live slot."""
    if pages is not None:
        b, t = x.shape[:2]
        if jnp.ndim(length) == 0:
            pos = jnp.broadcast_to(
                length + jnp.arange(t, dtype=jnp.int32), (b, t)
            )
        else:
            pos = length[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
        # clamp keeps the page lookup in-bounds for inactive slots parked
        # at the virtual last row (their writes are trapped anyway)
        pos = jnp.clip(pos, 0, pages.shape[1] * page_size - 1)
        pidx = jnp.take_along_axis(pages, pos // page_size, axis=1)
        off = pos % page_size
        if scale is None:
            return cache.at[pidx, off].set(x.astype(cache.dtype)), None
        # quantized pool: codes and their scale rows scatter through the
        # SAME (page, offset) pair — the scale planes are paged too
        q, s = _quantize_kv(x, cache.dtype)
        return cache.at[pidx, off].set(q), scale.at[pidx, off].set(s)

    def write(c, val, l):
        if jnp.ndim(l) == 0:
            return jax.lax.dynamic_update_slice(c, val, (0, l, 0, 0))
        return jax.vmap(
            lambda cr, vr, lr: jax.lax.dynamic_update_slice(cr, vr, (lr, 0, 0))
        )(c, val, l)

    if scale is None:
        return write(cache, x.astype(cache.dtype), length), None
    q, s = _quantize_kv(x, cache.dtype)
    return write(cache, q, length), write(scale, s, length)


def _cached_attention(q, k_cache, v_cache, k_scale, v_scale, length,  # graftlint: hot-path=traced
                      cfg: LlamaConfig, pages=None, verify=False):
    """q: (B, T, Hq, hd) attends over cache[:, :max_len] masked to
    positions < length + T (rows are the T new tokens at absolute
    positions length..length+T-1). All-f32 softmax.

    With ``pages`` (B, n_slot_pages) the cache is a paged pool
    (n_pages, page_size, Hkv, hd). The unified dispatcher
    (ops/attention.serving_cache_attention) routes every opted-in shape
    — decode T=1, the speculative verify window (the EXPLICIT ``verify``
    flag, so a small prefill chunk can never ride the verify opt-in),
    and prefill chunks under ``prefill_attn="ragged"`` — onto the
    ragged-paged Pallas kernel, dense or paged, shard_map-ed over the
    serving mesh's KV-head axis at tp>1 (each shard's heads bitwise the
    tp=1 kernel's). Everything else falls through to the XLA path: the
    paged branch GATHERS the slot's pages into the same (B, S, Hkv, hd)
    view the dense layout stores directly and runs the identical einsum
    — identical values in identical positions, so the two layouts'
    gather outputs are bitwise equal (garbage rows differ but sit
    behind exact-zero softmax weights in both)."""
    b, t, hq, hd = q.shape
    if cfg.decode_attn == "ragged" or cfg.prefill_attn == "ragged":
        from k8s_gpu_device_plugin_tpu.ops.attention import (
            serving_cache_attention,
        )

        out = serving_cache_attention(
            q, k_cache, v_cache, length, pages=pages, verify=verify,
            decode_attn=cfg.decode_attn, prefill_attn=cfg.prefill_attn,
            window=cfg.sliding_window, tp=cfg.tp,
            k_scale=k_scale, v_scale=v_scale,
        )
        if out is not None:
            return out
    if pages is not None:
        k_cache = k_cache[pages].reshape(b, -1, *k_cache.shape[-2:])
        v_cache = v_cache[pages].reshape(b, -1, *v_cache.shape[-2:])
        if k_scale is not None:
            # quantized pool: the scale planes ride the same page
            # geometry, so the identical gather rebuilds the dense
            # (B, S, Hkv, 1) view the einsums below expect
            k_scale = k_scale[pages].reshape(b, -1, *k_scale.shape[-2:])
            v_scale = v_scale[pages].reshape(b, -1, *v_scale.shape[-2:])
        pages = None  # below here the gathered view IS the dense cache
    max_len = k_cache.shape[1]
    group = hq // cfg.n_kv_heads
    # bf16 operands + f32 accumulation (MXU native rate); the cache is
    # never upcast in HBM — decode is bandwidth-bound. int8 caches keep
    # the int8 arrays as the dot operands (a bare convert fuses into the
    # dot; an elementwise scale-multiply producer may not, which would
    # materialize a full bf16 cache copy and invert the HBM saving); the
    # per-(position, head) scales commute through the s-contractions, so
    # they apply to scores after the K dot and to probs before the V dot.
    qg = q.reshape(b, t, cfg.n_kv_heads, group, hd)
    scores = jnp.einsum(
        "btkgd,bskd->btkgs", qg, k_cache.astype(q.dtype),
        preferred_element_type=jnp.float32,
    ) * (hd ** -0.5)
    if k_scale is not None:
        # (B, S, Hkv, 1) -> (B, Hkv, S) -> broadcast over (b, t, k, g, s)
        ks = k_scale[..., 0].transpose(0, 2, 1)
        scores = scores * ks[:, None, :, None, :]
    # scalar length broadcasts; a (B,) vector gives every slot its own
    # causal horizon (continuous batching)
    base = length if jnp.ndim(length) == 0 else length[:, None, None, None, None]
    q_pos = base + jnp.arange(t)[None, :, None, None, None]
    k_pos = jnp.arange(max_len)[None, None, None, None, :]
    keep = k_pos <= q_pos
    if cfg.sliding_window > 0:
        keep &= q_pos - k_pos < cfg.sliding_window
    scores = jnp.where(keep, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)  # f32
    if v_scale is not None:
        vs = v_scale[..., 0].transpose(0, 2, 1)
        probs = probs * vs[:, None, :, None, :]
    out = jnp.einsum(
        "btkgs,bskd->btkgd", probs.astype(q.dtype), v_cache.astype(q.dtype),
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, t, hq, hd).astype(q.dtype)


_MOE_PREFILL_CHUNK = 128


def _decode_moe_mlp(h: jax.Array, layer: dict, cfg: LlamaConfig) -> jax.Array:
    """MoE MLP for decode: dense-compute every expert, mix by the top-k
    renormalized gates (the same ``router_topk`` as training).

    Decode has no capacity competition — each token simply runs its top-k
    experts — so this matches the training forward exactly whenever
    training's capacity didn't drop tokens (always true for the ample-
    capacity serving case). Computing all E experts costs E/k times the
    sparse FLOPs, which at decode's T=1..few tokens is noise and buys a
    gather-free static-shape graph. Prefill (large T) is scanned in
    token chunks so the (B, T, E, F) intermediates never materialize
    beyond one chunk — routing is per-token, so chunking is exact.
    """
    from k8s_gpu_device_plugin_tpu.models.moe import router_topk

    b, t, d = h.shape
    if t > _MOE_PREFILL_CHUNK:
        c = _MOE_PREFILL_CHUNK
        n = -(-t // c)
        hp = jnp.pad(h, ((0, 0), (0, n * c - t), (0, 0)))
        chunks = hp.reshape(b, n, c, d).transpose(1, 0, 2, 3)  # (n,B,c,D)

        def body(_, hc):
            return None, _decode_moe_mlp(hc, layer, cfg)

        _, out = jax.lax.scan(body, None, chunks)
        return out.transpose(1, 0, 2, 3).reshape(b, n * c, d)[:, :t]

    logits = h.astype(jnp.float32) @ layer["router"].astype(jnp.float32)
    gates, idx, _ = router_topk(logits, cfg.n_experts_per_token)  # (B,T,k)
    mix = jnp.sum(
        jax.nn.one_hot(idx, cfg.n_experts, dtype=jnp.float32)
        * gates[..., None],
        axis=2,
    )                                                            # (B,T,E)
    gate = jax.nn.silu(
        qexpert_einsum("btd,edf->btef", h, layer["moe_w1"]).astype(jnp.float32)
    ).astype(h.dtype)
    up = qexpert_einsum("btd,edf->btef", h, layer["moe_w3"])
    y = qexpert_einsum("btef,efd->bted", gate * up, layer["moe_w2"])
    return jnp.einsum("bte,bted->btd", mix.astype(h.dtype), y)


def _qm_lora(h, layer, name, sel):
    """qmatmul + the per-row stacked-adapter delta when this layer
    carries factors and a selection is threaded (models/lora_serving.py);
    the base path (sel None / no factors) compiles exactly as before."""
    y = qmatmul(h, layer[name])
    from k8s_gpu_device_plugin_tpu.models.lora_serving import maybe_lora

    d = maybe_lora(h, layer, name, sel)
    return y if d is None else y + d


def _project_qkv(x, layer, positions, cfg, sel=None):
    """Shared decode-side QKV projection + rope (used by the linear cache
    here and the ring cache in models/rolling.py — one implementation so
    the rolling oracle's token-exactness can never drift). Weight leaves
    may be int8 {"q", "s"} serving leaves (models/quantized_serving.py);
    qmatmul dispatches. ``sel`` (B, S) selects per-row stacked LoRA
    adapters (multi-LoRA serving); S is whatever stack the params carry
    — all N registered adapters on the dense path, the ≤K batch-active
    ones on the gathered path (models/lora_serving.py "N-vs-K cost
    model"), with the one-hot over stack POSITIONS either way."""
    b, t, d = x.shape
    hd = cfg.head_dim
    h = rms_norm(x, layer["attn_norm"], cfg.norm_eps, cfg.norm_offset)
    q = _qm_lora(h, layer, "wq", sel)
    k = _qm_lora(h, layer, "wk", sel)
    v = _qm_lora(h, layer, "wv", sel)
    if cfg.attn_bias:
        # Qwen2 layout: biases are base-model leaves (adapters and int8
        # weight quantization never touch them), added after any LoRA delta
        q, k, v = q + layer["bq"], k + layer["bk"], v + layer["bv"]
    q = q.reshape(b, t, cfg.n_heads, hd)
    k = k.reshape(b, t, cfg.n_kv_heads, hd)
    v = v.reshape(b, t, cfg.n_kv_heads, hd)
    if cfg.tp > 1:
        # tensor-parallel serving: pin q/k/v to the head shards the
        # column-cut wq/wk/wv produced (parallel/tp_serving.py) so the
        # cache write and attention stay head-local — per-head bits are
        # exactly the tp=1 bits (no contraction ever crosses a shard).
        # constrain() no-ops when no mesh scope is active (tp=1 never
        # enters one), so the single-chip graph is untouched.
        q = constrain(q, HEADS)
        k = constrain(k, HEADS)
        v = constrain(v, HEADS)
    return rope(q, positions, cfg.rope_theta), rope(k, positions, cfg.rope_theta), v


def _mlp_out(x, layer, cfg, sel=None):
    """Shared decode-side MLP residual branch (dense silu or MoE mix)."""
    h = rms_norm(x, layer["mlp_norm"], cfg.norm_eps, cfg.norm_offset)
    if cfg.is_moe:
        return _decode_moe_mlp(h, layer, cfg)
    gate = mlp_act(
        _qm_lora(h, layer, "w1", sel).astype(jnp.float32), cfg
    ).astype(x.dtype)
    up = _qm_lora(h, layer, "w3", sel)
    hidden = gate * up
    if cfg.tp > 1 and not cfg.tp_allow_psum:
        # same no-psum rule as wo: gather the (column-sharded) hidden
        # activation and run the replicated w2 contraction whole.
        # tp_allow_psum drops the gather — w2 row-shards on d_ff and the
        # partitioner psums the partials (bit-identity opt-out)
        hidden = constrain(hidden, REPLICATED)
    return _qm_lora(hidden, layer, "w2", sel)


def _decode_block(x, layer, k_cache, v_cache, k_scale, v_scale, length,
                  positions, cfg, sel=None, pages=None, verify=False):
    """One transformer block over T new tokens with cache read+write.

    Returns (x_out, k_cache, v_cache, k_scale, v_scale) with the new
    tokens' K/V written at ``length + arange(T)``. Same algebra as the
    training ``_block`` (models/llama.py) minus sharding annotations; MoE
    MLPs run the dense-mix decode path (``_decode_moe_mlp``). ``pages``
    (B, n_slot_pages) switches the cache leaves to the paged pool layout
    — writes scatter through the table, reads gather through it."""
    b, t, d = x.shape

    q, k, v = _project_qkv(x, layer, positions, cfg, sel)
    ps = cfg.kv_page_size if pages is not None else 0
    k_cache, k_scale = _cache_write(k_cache, k_scale, k, length, pages, ps)
    v_cache, v_scale = _cache_write(v_cache, v_scale, v, length, pages, ps)

    attn = _cached_attention(q, k_cache, v_cache, k_scale, v_scale, length,
                             cfg, pages=pages, verify=verify)
    if cfg.tp > 1 and not cfg.tp_allow_psum:
        # gather the head-sharded attention output to replicated BEFORE
        # the wo contraction: wo stays replicated and the matmul runs
        # whole on every shard — identical bits, where a row-sharded wo
        # + psum would split the f32 accumulation (the one thing that
        # breaks the tp=1-vs-tp=N stream pin). tp_allow_psum is the
        # EXPLICIT opt-out: the head-sharded activation feeds a
        # row-sharded wo and the partitioner inserts the psum
        attn = constrain(attn, REPLICATED)
    x = x + _qm_lora(
        attn.reshape(b, t, cfg.n_heads * cfg.head_dim), layer, "wo", sel
    )
    return x + _mlp_out(x, layer, cfg, sel), k_cache, v_cache, k_scale, v_scale


def _forward_cached(
    params, tokens, cache: KVCache, length, cfg: LlamaConfig,
    last_only: bool = False,
    select_pos: jax.Array | None = None,
    lora_sel: jax.Array | None = None,
    pages: jax.Array | None = None,
    verify: bool = False,
):
    """Run T tokens (starting at absolute position ``length``) through all
    layers with cache update. Returns (logits (B, T, V) f32, new cache);
    ``last_only`` projects only the final position (prefill wants one
    next-token distribution, not a (B, P, V) logits tensor), and
    ``select_pos`` (traced scalar) projects only that position — for
    bucket-padded prefills where the last REAL token is not the last row
    (continuous batching), keeping the lm_head matmul and its logits at
    1/T the cost. ``lora_sel`` (B, N) selects per-row stacked LoRA
    adapters when ``params["layers"]`` carries them
    (models/lora_serving.py). ``pages`` (B, n_slot_pages) marks the
    cache as a paged pool and routes every layer's cache write/read
    through the table (models/batching.py owns the tables); ``verify``
    marks a speculative T=gamma verify window, the only multi-token
    paged read allowed onto the flash verify kernel (prefill chunks
    must keep the bit-identical gather)."""
    from k8s_gpu_device_plugin_tpu.models.llama import cast_params_for_compute

    # master-weight checkpoints (param_dtype=f32) decode in compute dtype —
    # without this, every matmul would promote to f32 and the bf16 cache
    # contract in _cached_attention would silently upcast
    params = cast_params_for_compute(params, cfg)
    b, t = tokens.shape
    x = params["embed"].astype(cfg.dtype)[tokens]
    if cfg.scale_embed:
        x = x * jnp.asarray(math.sqrt(cfg.d_model), cfg.dtype)
    if jnp.ndim(length) == 0:
        positions = length + jnp.arange(t, dtype=jnp.int32)
    else:  # per-slot positions (B, T) — rope handles 2D
        positions = length[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]

    # None scale planes are empty pytree leaves — lax.scan carries them
    # through untouched, so the bf16 and int8 paths share one structure
    def body(carry, layer_and_cache):
        x = carry
        layer, k_c, v_c, k_s, v_s = layer_and_cache
        x, k_c, v_c, k_s, v_s = _decode_block(
            x, layer, k_c, v_c, k_s, v_s, length, positions, cfg,
            sel=lora_sel, pages=pages, verify=verify,
        )
        return x, (k_c, v_c, k_s, v_s)

    x, (k_new, v_new, ks_new, vs_new) = jax.lax.scan(
        body, x,
        (params["layers"], cache.k, cache.v, cache.k_scale, cache.v_scale),
    )
    x = rms_norm(x, params["final_norm"], cfg.norm_eps, cfg.norm_offset)
    if last_only:
        x = x[:, -1:]
    elif select_pos is not None:
        x = jax.lax.dynamic_slice_in_dim(x, select_pos, 1, axis=1)
    logits = qhead_matmul(x, head_weights(params, cfg), cfg.dtype)
    if cfg.tp > 1:
        # the lm_head is column-sharded over vocab (each shard's logit
        # columns are bitwise the tp=1 columns); sampling needs the full
        # distribution on every device — gather, pure data movement
        logits = constrain(logits, REPLICATED)
    return logits, KVCache(
        k=k_new, v=v_new, k_scale=ks_new, v_scale=vs_new
    )


def prefill(params, prompt, cache: KVCache, cfg: LlamaConfig):
    """Prompt (B, P) -> (last-position logits (B, V), filled cache)."""
    logits, cache = _forward_cached(params, prompt, cache, 0, cfg, last_only=True)
    return logits[:, -1], cache


def _mask_after_eos(toks, eos_id, pad_id):
    """Pad everything strictly after each row's first EOS (the EOS itself
    is kept): exclusive cumulative count of EOS occurrences. One
    implementation for every decode entry point."""
    is_eos = (toks == eos_id).astype(jnp.int32)
    after_eos = (jnp.cumsum(is_eos, axis=1) - is_eos) > 0
    return jnp.where(after_eos, pad_id, toks)


def generate(
    params,
    prompt: jax.Array,
    cfg: LlamaConfig,
    max_new: int,
    key: jax.Array | None = None,
    temperature: float = 0.0,
    sampler: "Sampler | None" = None,
    eos_id: int | None = None,
    pad_id: int = 0,
) -> jax.Array:
    """Greedy (temperature 0) or sampled generation.

    prompt: (B, P) int32; returns (B, max_new) generated ids. One compile:
    prefill over the prompt, then a scanned single-token decode loop
    against the static-size cache.

    ``sampler`` (models/sampling.py) gives top-k/top-p control; the plain
    ``temperature`` arg is shorthand for ``Sampler(temperature=...)``.

    ``eos_id`` stops each row at its first EOS: positions after it come
    back as ``pad_id``. Shapes stay static (the loop always runs
    ``max_new`` steps — the fixed-shape TPU trade; rows that finished
    early just decode ignored tokens), and the masking is a thin
    elementwise postprocess OUTSIDE the jitted core, so different
    eos/pad ids never recompile the decode loop.
    """
    toks = _generate_jit(params, prompt, cfg, max_new, key, temperature,
                         sampler)
    if eos_id is not None:
        toks = _mask_after_eos(toks, eos_id, pad_id)
    return toks


@partial(jax.jit, static_argnames=("cfg", "max_new", "temperature", "sampler"))
def _generate_jit(
    params,
    prompt: jax.Array,
    cfg: LlamaConfig,
    max_new: int,
    key: jax.Array | None = None,
    temperature: float = 0.0,
    sampler: "Sampler | None" = None,
) -> jax.Array:
    if cfg.quant != "none":
        # _decode_block runs plain bf16 matmuls; silently accepting an int8
        # config would decode with different numerics than the training
        # forward and greedy tokens could drift from the full-context oracle.
        raise NotImplementedError("decode path is bf16-only (quant='none')")
    if sampler is None:
        sampler = Sampler(temperature=temperature)
    elif temperature != 0.0:
        # Both given: the sampler would silently win and e.g.
        # generate(..., temperature=0.8, sampler=Sampler(top_k=50)) would
        # decode greedily (Sampler's temperature defaults to 0).
        raise ValueError(
            "pass temperature inside the Sampler, not alongside it"
        )
    b, p = prompt.shape
    cache = KVCache.init(cfg, b, p + max_new)
    logits, cache = prefill(params, prompt, cache, cfg)
    return _decode_loop(
        params, prompt, cache, logits, p, cfg, max_new, sampler, key
    )


def _decode_loop(params, prompt, cache, logits, length, cfg, max_new,
                 sampler, key):
    """The scanned decode loop shared by ``generate`` and prefix-cached
    continuation (``generate_from``): ``logits`` is the next-token
    distribution at position ``length``; the cache holds everything
    before it and has >= max_new free rows."""
    key = key if key is not None else jax.random.key(0)

    # presence mask of every context token (prompt + generated) for the
    # repetition penalty; a (B, V) bool is negligible, so it is carried
    # unconditionally and simply ignored when the penalty is off
    presence = init_presence(prompt, cfg.vocab_size)

    def pick(logits, key, presence):
        return sample_and_mark(logits, key, sampler, presence)

    def step(carry, i):
        logits, cache, key, presence = carry
        key, sub = jax.random.split(key)
        tok, presence = pick(logits, sub, presence)   # (B,)
        logits, cache = _forward_cached(
            params, tok[:, None], cache, length + i, cfg
        )
        return (logits[:, -1], cache, key, presence), tok

    # max_new - 1 cached forwards; the final token needs only a pick from
    # the last carried logits (no wasted trailing forward).
    (logits, _, key, presence), toks = jax.lax.scan(
        step, (logits, cache, key, presence), jnp.arange(max_new - 1)
    )
    key, sub = jax.random.split(key)
    last, _ = pick(logits, sub, presence)
    return jnp.concatenate([toks, last[None]], axis=0).T  # (B, max_new)


_prefill_jit = jax.jit(prefill, static_argnames=("cfg",))


def prefill_prompt(
    params, prompt: jax.Array, cfg: LlamaConfig, max_new_capacity: int
) -> tuple[KVCache, jax.Array]:
    """Prefill once for prefix-cached serving: returns (cache with
    ``max_new_capacity`` free rows, next-token logits (B, V)).

    JAX arrays are immutable, so the returned state can seed ANY number of
    divergent continuations via :func:`generate_from` — the classic
    system-prompt reuse pattern costs one prefill total, not one per
    continuation."""
    if cfg.quant != "none":
        # fail BEFORE the expensive prefill: generate_from would reject
        # the continuation anyway
        raise NotImplementedError("decode path is bf16-only (quant='none')")
    b, p = prompt.shape
    cache = KVCache.init(cfg, b, p + max_new_capacity)
    logits, cache = _prefill_jit(params, prompt, cache, cfg=cfg)
    return cache, logits


def generate_from(
    params,
    prompt: jax.Array,
    cache: KVCache,
    logits: jax.Array,
    cfg: LlamaConfig,
    max_new: int,
    key: jax.Array | None = None,
    sampler: "Sampler | None" = None,
    eos_id: int | None = None,
    pad_id: int = 0,
) -> jax.Array:
    """Continue from a :func:`prefill_prompt` state — the same decode loop
    ``generate`` runs, so a continuation is TOKEN-IDENTICAL to a fresh
    ``generate`` with the same prompt/key/sampler (test-pinned). ``prompt``
    is the prefilled prompt (needed for the repetition-penalty presence
    mask); the state is never mutated, so call this repeatedly with
    different keys/samplers to branch."""
    if cfg.quant != "none":
        raise NotImplementedError("decode path is bf16-only (quant='none')")
    sampler = sampler if sampler is not None else Sampler()
    p = prompt.shape[1]
    if cache.k.shape[2] < p + max_new:
        raise ValueError(
            f"cache has {cache.k.shape[2] - p} free rows but max_new="
            f"{max_new}; prefill with a larger max_new_capacity"
        )
    toks = _generate_from_jit(
        params, prompt, cache, logits, cfg, max_new, key, sampler
    )
    if eos_id is not None:
        toks = _mask_after_eos(toks, eos_id, pad_id)
    return toks


@partial(jax.jit, static_argnames=("cfg", "max_new", "sampler"))
def _generate_from_jit(params, prompt, cache, logits, cfg, max_new, key,
                       sampler):
    return _decode_loop(
        params, prompt, cache, logits, prompt.shape[1], cfg, max_new,
        sampler, key
    )
