"""Host-side page pool for the paged KV cache (models/batching.py).

The dense serving cache reserves ``n_slots * max_len`` token rows of HBM
up front, so a 40-token request in a 2048-token slot strands 98% of its
reservation. The paged layout (the vLLM idea, TPU-shaped by the "Ragged
Paged Attention" line of work — PAPERS.md) carves the KV HBM into
fixed-size *pages* of ``page_size`` token rows and maps each slot's
virtual positions onto physical pages through a per-slot int32 page
table. This module is the HOST half of that design: a free-list
allocator with per-page reference counts. It never touches device
memory — the device side is the ``(L, n_pages, page_size, Hkv, hd)``
pool arrays in :class:`~..models.generate.KVCache` and the page-table
rows in ``BatchState.pages``; the batcher keeps the two in sync (every
table row it uploads was first reserved here).

Refcounts are what make prefix sharing zero-copy: a promoted prefix
holds a reference on the pages it spans, every admission that aliases
it takes another, and a page returns to the free list only when the
last holder drops it. Page 0 is RESERVED as the trap page: unset table
entries point at it, and the decode step redirects inactive slots'
writes to it — so a freed-and-reallocated page can never be scribbled
on by its previous owner's lagging compute (the paged analogue of the
dense layout's last-row write redirect).

Single-threaded by design, like the batcher that owns it: every call
happens on the engine thread.
"""

from __future__ import annotations

import base64

import numpy as np


class PagePool:
    """Free-list page allocator with reference counts.

    ``n_pages`` counts physical pages INCLUDING the reserved trap page 0,
    so ``capacity`` (allocatable pages) is ``n_pages - 1``. ``alloc``
    raises on exhaustion — callers must check :attr:`free_pages` first
    (the batcher defers admission instead of failing mid-flight).
    """

    def __init__(self, n_pages: int, page_size: int):
        if n_pages < 2:
            raise ValueError(
                f"page pool needs >= 2 pages (1 allocatable + the "
                f"reserved trap page 0), got {n_pages}"
            )
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        # LIFO free list: recently freed pages are re-used first (their
        # pool rows are likelier to still be warm in any cache hierarchy)
        self._free: list[int] = list(range(n_pages - 1, 0, -1))  # owner: engine
        self._refs: dict[int, int] = {}  # owner: engine
        #: high-water mark of pages simultaneously in use (the serve
        #: bench's kv_hbm_saved_pct denominator needs the peak, not the
        #: instantaneous value)
        self.peak_in_use = 0  # owner: engine
        #: pages returned through :meth:`recycle` — the out-of-window
        #: reclamation path, counted separately from release-on-retire
        #: decrefs (kv_stats' pages_recycled_total reads this)
        self.recycled_total = 0  # owner: engine

    # --- capacity views ---

    @property
    def capacity(self) -> int:
        """Allocatable pages (the trap page excluded)."""
        return self.n_pages - 1

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return self.capacity - len(self._free)

    def pages_for_tokens(self, n_tokens: int) -> int:
        """Pages covering ``n_tokens`` contiguous rows (ceil division)."""
        return -(-int(n_tokens) // self.page_size)

    # --- allocation ---

    def alloc(self, n: int) -> list[int]:
        """Take ``n`` pages off the free list (each at refcount 1)."""
        if n > len(self._free):
            raise RuntimeError(
                f"page pool exhausted: need {n}, free {len(self._free)} "
                f"(capacity {self.capacity})"
            )
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self._refs[p] = 1
        self.peak_in_use = max(self.peak_in_use, self.in_use)
        return out

    def incref(self, pages) -> None:
        """Add one reference to each of ``pages`` (prefix aliasing: the
        new holder shares the physical rows instead of copying them)."""
        for p in pages:
            if p not in self._refs:
                raise ValueError(f"incref of unallocated page {p}")
            self._refs[p] += 1

    def decref(self, pages) -> list[int]:
        """Drop one reference from each of ``pages``; pages reaching
        zero return to the free list. Returns the freed page ids."""
        freed = []
        for p in pages:
            r = self._refs.get(p)
            if r is None:
                raise ValueError(f"decref of unallocated page {p}")
            if r == 1:
                del self._refs[p]
                self._free.append(p)
                freed.append(p)
            else:
                self._refs[p] = r - 1
        return freed

    def recycle(self, pages) -> int:
        """Return pages whose positions fell out of every live window
        (sliding-window serving, models/batching.py). Semantically a
        :meth:`decref` — a prefix-shared page just drops this row's
        reference and stays live for its other holders — but tallied
        separately: :attr:`recycled_total` counts pages actually freed
        here, so observability can tell O(window) steady-state
        reclamation apart from ordinary retire-time release. Returns
        the number of pages freed."""
        freed = len(self.decref(pages))
        self.recycled_total += freed
        return freed

    # --- integrity ---

    def check(self) -> None:
        """Invariant sweep (tests call this after racy interleavings):
        refcounts positive, free list disjoint from the allocated set and
        trap-free, and the two partitions cover the capacity exactly."""
        assert all(r > 0 for r in self._refs.values()), "non-positive ref"
        free = set(self._free)
        assert len(free) == len(self._free), "duplicate page in free list"
        assert 0 not in free and 0 not in self._refs, "trap page leaked"
        assert not (free & set(self._refs)), "page both free and allocated"
        assert len(free) + len(self._refs) == self.capacity, "pages lost"


def kv_token_bytes(cfg) -> int:
    """HBM bytes one cached token row costs (K + V across all layers,
    scale planes included on the quantized-cache paths) — the
    denominator both layouts' resident-bytes gauges share, so the dense
    reservation, the paged pool, and ``--prefixCacheMB`` all mean the
    same bytes for bf16/int8/int4 alike. The paged layout pages the
    scale planes on the same (page, offset) geometry as the codes
    (generate.KVCache.init_paged), so the quant arms price BOTH layouts:
    a paged quantized token is its code bytes plus its two f32 scale
    rows, exactly like a dense one.

    This is the AGGREGATE across tensor-parallel shards: the cache
    shards on the KV-head axis (parallel/tp_serving.py), so a page id
    names the same page on every shard and the ALLOCATOR above stays
    one host-side free list regardless of tp — only the bytes behind
    each page split, by :func:`kv_shard_token_bytes`."""
    import jax.numpy as jnp

    per_elt = {"int8": 1.0, "int4": 0.5}.get(cfg.cache_quant)
    if per_elt is None:
        per_elt = jnp.dtype(cfg.dtype).itemsize
    nbytes = 2 * cfg.n_layers * cfg.n_kv_heads * cfg.head_dim * per_elt
    if cfg.cache_quant in ("int8", "int4"):
        nbytes += 2 * cfg.n_layers * cfg.n_kv_heads * 4  # f32 scales
    return int(nbytes)


def kv_shard_token_bytes(cfg) -> int:
    """Per-SHARD HBM bytes of one cached token row under tensor-parallel
    serving: each of ``cfg.tp`` shards holds ``n_kv_heads / tp`` heads'
    worth of every page/row — K/V values AND the quantized scale planes,
    which are per-(position, head) and shard on the same axis
    (parallel/tp_serving.py ``batch_state_shardings``) — so the division
    is exact (the mesh validation guarantees tp | n_kv_heads). tp=1
    degenerates to :func:`kv_token_bytes`."""
    return kv_token_bytes(cfg) // max(1, getattr(cfg, "tp", 1))


# ---------------- KV page transfer wire format ----------------
#
# Disaggregated prefill/decode ships a request's finished cache rows
# from a prefill replica to a decode replica (serving/router.py drives
# export -> transfer -> resubmit). The unit of transfer is the POOL
# PAGE: the exporter gathers the pages its page-table row references —
# codes AND quantized scale planes, so bf16/int8/int4 all transfer the
# same way — and the importer scatters them into freshly allocated
# pages of its own pool. The blob is self-describing (geometry, quant
# mode, per-plane shape/dtype) so a mismatched receiver refuses with an
# actionable error instead of corrupting KV, and it is JSON-safe
# (base64 payloads) so it rides the same HTTP surface as the PR-14
# resume seam. Pages are GLOBAL arrays regardless of tensor-parallel
# degree — a page id names the same rows on every shard — so a blob
# exported under tp=1 installs under tp=2 and vice versa.

KV_WIRE_VERSION = 1


def _wire_dtype(name: str):
    """Resolve a wire dtype name, including the ml_dtypes extension
    types (bfloat16) that plain numpy cannot name."""
    try:
        return np.dtype(name)
    except TypeError:
        pass
    import ml_dtypes

    dt = getattr(ml_dtypes, name, None)
    if dt is None:
        raise ValueError(f"kv wire blob names unknown dtype {name!r}")
    return np.dtype(dt)


def pack_kv_wire(planes: dict, *, page_size: int, cache_quant,
                 tokens: int) -> dict:
    """Serialize exported pool pages into a self-describing, JSON-safe
    wire blob. ``planes`` maps cache plane names (k/v and, quantized,
    k_scale/v_scale) to host arrays of shape
    ``(L, n_pages, page_size, Hkv, d)``; ``tokens`` is the count of
    VALID leading rows (the importer's consistency check against the
    folded prompt it is asked to install under)."""
    n_pages = 0
    out = {}
    for name, arr in planes.items():
        arr = np.ascontiguousarray(arr)
        n_pages = int(arr.shape[1])
        out[name] = {
            "shape": [int(d) for d in arr.shape],
            "dtype": arr.dtype.name,
            "data": base64.b64encode(arr.tobytes()).decode("ascii"),
        }
    return {
        "version": KV_WIRE_VERSION,
        "layout": "paged",
        "page_size": int(page_size),
        "cache_quant": cache_quant,
        "tokens": int(tokens),
        "n_pages": n_pages,
        "planes": out,
    }


def unpack_kv_wire(blob) -> "tuple[dict, dict]":
    """Decode a :func:`pack_kv_wire` blob into ``(meta, planes)`` with
    numpy arrays, validating internal consistency (version, layout,
    payload sizes against the declared shapes/dtypes). Compatibility
    with a RECEIVING pool (page size, quant mode, plane geometry) is
    the batcher's job — it knows its own cache."""
    if not isinstance(blob, dict) or "planes" not in blob:
        raise ValueError(
            "kv_pages is not a KV wire blob (expected the dict "
            "pack_kv_wire builds, with a 'planes' mapping)"
        )
    if blob.get("version") != KV_WIRE_VERSION:
        raise ValueError(
            f"unsupported KV wire version {blob.get('version')!r} "
            f"(this build speaks version {KV_WIRE_VERSION})"
        )
    if blob.get("layout") != "paged":
        raise ValueError(
            f"KV wire layout {blob.get('layout')!r} is not 'paged': "
            "only paged pools export/import pages"
        )
    n_pages = int(blob.get("n_pages", 0))
    planes = {}
    for name, p in blob["planes"].items():
        dt = _wire_dtype(p["dtype"])
        shape = tuple(int(d) for d in p["shape"])
        if len(shape) != 5 or shape[1] != n_pages:
            raise ValueError(
                f"kv wire plane {name!r} has shape {shape}; expected "
                f"5-d (L, n_pages={n_pages}, page_size, Hkv, d)"
            )
        raw = base64.b64decode(p["data"])
        want = dt.itemsize * int(np.prod(shape))
        if len(raw) != want:
            raise ValueError(
                f"kv wire plane {name!r}: payload is {len(raw)} bytes "
                f"but shape {shape} / dtype {dt.name} needs {want}"
            )
        planes[name] = np.frombuffer(raw, dtype=dt).reshape(shape)
    meta = {k: v for k, v in blob.items() if k != "planes"}
    return meta, planes
