"""Daemon entry point.

Reference: main.go — flag/config parse (31-52), logger init (55-60),
readiness channel (63-71), PluginManager (74) + web server (77) wired into an
oklog/run group with a signal handler (79-138), optional profiling harness
(141-154). Here the run group is an asyncio gather; SIGINT/SIGTERM set the
shared stop event; the HTTP server starts only after the manager signals
readiness (≙ main.go:128), which the Server itself awaits.

Run:  python -m k8s_gpu_device_plugin_tpu.main --configFile config
"""

from __future__ import annotations

import asyncio
import signal
import sys

from k8s_gpu_device_plugin_tpu.benchmark.profiler import Profiler
from k8s_gpu_device_plugin_tpu.config import Config, load_config
from k8s_gpu_device_plugin_tpu.device.health import assessor_from_config
from k8s_gpu_device_plugin_tpu.metrics.runtime_metrics import (
    usage_reader_from_config,
)
from k8s_gpu_device_plugin_tpu.plugin.manager import PluginManager
from k8s_gpu_device_plugin_tpu.server.server import Server
from k8s_gpu_device_plugin_tpu.utils.latch import Latch
from k8s_gpu_device_plugin_tpu.utils.log import LogConfig, init_logger

SHUTDOWN_TIMEOUT_SECONDS = 10.0  # bounded SIGTERM drain (2x the 5s dial timeout)


async def run_daemon(cfg: Config, stop_event: asyncio.Event | None = None) -> None:
    """Run manager + HTTP server until the stop event fires."""
    logger = init_logger(
        LogConfig(
            level=cfg.log.level,
            file_dir=cfg.log.file_dir or None,
            dev_mode=cfg.log.dev_mode,
        )
    )
    stop = stop_event or asyncio.Event()
    loop = asyncio.get_running_loop()
    for signum in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(signum, stop.set)
        except (NotImplementedError, RuntimeError):
            pass  # non-main thread or non-unix: tests drive stop directly

    if cfg.tracing:
        # enable BEFORE building the server so its span-duration
        # histograms see an enabled tracer and register
        from k8s_gpu_device_plugin_tpu.obs.trace import configure

        configure(enabled=True, max_traces=cfg.trace_buffer_traces)
        logger.info(
            "span tracing enabled",
            extra={"fields": {"buffer_traces": cfg.trace_buffer_traces}},
        )

    profiler: Profiler | None = None
    if cfg.benchmark:  # ≙ main.go:141-154
        profiler = Profiler(logger)
        # block.prof analogue: meter THIS loop's scheduling lag
        profiler.watch_loop(loop)
        profiler.run()

    ready = Latch()
    # ONE usage reader shared by the metrics endpoint and the health
    # assessor: one gRPC channel set, one scrape-timeout budget per tick.
    usage_reader = usage_reader_from_config(cfg)
    manager = PluginManager(
        cfg,
        ready,
        logger=logger,
        health_assessor=assessor_from_config(
            cfg, logger=logger, reader=usage_reader
        ),
    )
    server = Server(
        cfg, manager, ready, logger=logger, usage_reader=usage_reader,
        profiler=profiler,
    )

    manager_task = asyncio.create_task(manager.start(), name="plugin-manager")
    server_task = asyncio.create_task(server.run(stop), name="http-server")
    logger.info(
        "daemon starting",
        extra={"fields": {"strategy": cfg.slice_strategy,
                          "backend": manager.backend.name}},
    )
    stop_task = asyncio.create_task(stop.wait(), name="stop-wait")
    try:
        # ≙ the oklog/run group (main.go:79-138): the first actor to fail
        # takes the whole daemon down; a clean stop shuts everything down.
        done, _ = await asyncio.wait(
            {stop_task, manager_task, server_task},
            return_when=asyncio.FIRST_COMPLETED,
        )
        for task in done:
            if task is not stop_task and task.exception() is not None:
                raise task.exception()
    finally:
        stop.set()
        stop_task.cancel()
        await manager.stop()
        tasks = (manager_task, server_task, stop_task)
        try:
            # Bounded drain: if an actor is wedged (e.g. a gRPC call with a
            # peer that stopped answering), cancel it rather than hang the
            # whole process on SIGTERM.
            await asyncio.wait_for(
                asyncio.gather(*tasks, return_exceptions=True),
                timeout=SHUTDOWN_TIMEOUT_SECONDS,
            )
        except (asyncio.TimeoutError, TimeoutError):
            logger.warning("shutdown deadline exceeded; cancelling actors")
            for task in tasks:
                task.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
        if profiler is not None:
            profiler.stop()
        logger.info("daemon stopped")


def main(argv: list[str] | None = None) -> int:
    cfg = load_config(argv if argv is not None else sys.argv[1:])
    asyncio.run(run_daemon(cfg))
    return 0


if __name__ == "__main__":
    sys.exit(main())
