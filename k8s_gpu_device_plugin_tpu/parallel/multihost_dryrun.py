"""Multi-host dryrun orchestrator: Allocate contract -> N processes ->
one global sharded train step, no hardware required.

``__graft_entry__.dryrun_multichip`` certifies the sharding story inside
ONE process (8 virtual CPU devices); this certifies the story ACROSS
processes, the way a real multi-host slice runs it:

1. For each of N workers, boot the real control plane (PluginManager +
   fake chip backend against an in-process kubelet) configured as one
   host of an N-host slice, and Allocate every chip — capturing the exact
   TPU_WORKER_ID / TPU_WORKER_HOSTNAMES / TPU_PROCESS_BOUNDS envs
   ``_container_allocate`` emits (plugin/plugin.py:254-292).
2. Spawn one SUBPROCESS per worker wearing exactly those envs plus a
   virtual-CPU device count, running
   ``parallel/multihost_step.py``: jax.distributed rendezvous (gloo),
   one global mesh with dp across the process boundary, and the
   framework's real train step — gradient psum crossing processes.
3. Assert every rank reports the SAME finite global loss: a mesh/axis/
   collective wiring bug shows up as divergent or non-finite losses, a
   contract bug as a failed rendezvous.

The reference never tests its worker-side story at all (its benchmark
measures map lookups; cross-process is delegated to whatever the
workload does with NVIDIA_VISIBLE_DEVICES). Here it is a one-call
artifact: ``dryrun_multihost()`` returns the combined report that
MULTIHOST_r*.json records.
"""

from __future__ import annotations

import asyncio
import json
import os
import subprocess
import sys
import tempfile

from k8s_gpu_device_plugin_tpu.plugin.testing import (
    allocate_whole_host,
    free_port,
    join_json_workers,
)

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def _allocate_worker_envs(
    n_workers: int, base_dir: str, host_topology: str, slice_topology: str
) -> list[dict[str, str]]:
    hostnames = ",".join(["127.0.0.1"] * n_workers)

    async def allocate_all():
        out = []
        for wid in range(n_workers):
            envs = await allocate_whole_host(
                os.path.join(base_dir, f"w{wid}"),
                topology=host_topology,
                slice_topology=slice_topology,
                worker_id=wid,
                worker_hostnames=hostnames,
            )
            out.append(envs)
        return out

    return asyncio.run(asyncio.wait_for(allocate_all(), timeout=120))


def dryrun_multihost(
    n_processes: int = 2,
    devices_per_process: int = 4,
    steps: int = 2,
    timeout: float = 420.0,
) -> dict:
    """Run the full multi-host dryrun; returns the combined report."""
    if n_processes != 2:
        raise ValueError(
            "the fake slice topologies are sized for 2 workers "
            "(v5e-4 hosts of a v5e-8 slice); extend the table for more"
        )
    with tempfile.TemporaryDirectory(prefix="mh_dryrun_") as base:
        envs = _allocate_worker_envs(
            n_processes, base, host_topology="v5e-4", slice_topology="v5e-8"
        )
        # contract sanity before spending subprocess time
        assert [e["TPU_WORKER_ID"] for e in envs] == [
            str(i) for i in range(n_processes)
        ], envs
        assert len({e["TPU_WORKER_HOSTNAMES"] for e in envs}) == 1, envs
        assert len({e["TPU_PROCESS_BOUNDS"] for e in envs}) == 1, envs

        port = free_port()
        procs = []
        for worker_envs in envs:
            env = {**os.environ, **worker_envs}
            existing = env.get("PYTHONPATH", "")
            env["PYTHONPATH"] = (
                f"{REPO_ROOT}{os.pathsep}{existing}" if existing else REPO_ROOT
            )
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = (
                f"--xla_force_host_platform_device_count={devices_per_process}"
            )
            procs.append(subprocess.Popen(
                [
                    sys.executable, "-m",
                    "k8s_gpu_device_plugin_tpu.parallel.multihost_step",
                    "--port", str(port), "--steps", str(steps),
                ],
                env=env, cwd=REPO_ROOT,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            ))

        reports = join_json_workers(procs, timeout=timeout)

    expected_ndev = n_processes * devices_per_process
    assert all(r["ok"] and r["distributed"] for r in reports), reports
    assert {r["rank"] for r in reports} == set(range(n_processes)), reports
    assert all(r["nprocs"] == n_processes for r in reports), reports
    assert all(r["ndev"] == expected_ndev for r in reports), reports
    # the decisive check: one GLOBAL computation, so every rank must see
    # the identical loss trajectory — divergence means a sharding or
    # collective wiring bug even though every process "ran fine"
    assert len({tuple(r["losses"]) for r in reports}) == 1, reports
    return {
        "ok": True,
        "n_processes": n_processes,
        "devices_per_process": devices_per_process,
        "global_devices": expected_ndev,
        "mesh": reports[0]["mesh"],
        "steps": steps,
        "losses": reports[0]["losses"],
        "grad_norms": reports[0]["grad_norms"],
        "env_contract_keys": sorted(
            k for k in envs[0] if k.startswith(("TPU_", "MEGASCALE_"))
        ),
    }


if __name__ == "__main__":
    print(json.dumps(dryrun_multihost()))
