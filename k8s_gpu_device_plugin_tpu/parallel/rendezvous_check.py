"""Multi-host contract preflight: rendezvous + one cross-process psum.

Run as a pod entrypoint on EVERY worker of a slice (or every slice of a
multislice job). It initializes ``jax.distributed`` from the plugin's
Allocate env contract (TPU_WORKER_ID / TPU_WORKER_HOSTNAMES / MEGASCALE_*,
plugin/plugin.py:_container_allocate), runs one psum over all processes'
devices, and prints ONE JSON line ``{rank, nprocs, ndev, psum, ok}``.
Exit 0 iff the collective produced the expected value on this process.

This is the TPU analogue of running nccl-tests before a job: a cheap,
CI-able proof that every worker agrees on coordinator, rank, and world size
before real training starts. The reference has no equivalent — its only
cross-process channel was kubelet gRPC (SURVEY §2 "distributed
communication backend: absent"); here the contract is first-class and this
tool makes a wrong coordinator/rank/world-size fail loudly at t=0 instead
of stranding a slice at first collective.

Usage: ``python -m k8s_gpu_device_plugin_tpu.parallel.rendezvous_check
[--port N]`` — the coordinator HOST and this process's rank come from the
injected envs; only the jax coordination port is a flag (it is a jobset
choice, not part of the allocation).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def run_check(port: int | None = None, init_timeout: int = 60) -> dict:
    """Initialize from envs, psum across every process, return the report.

    Raises on a broken contract (failed rendezvous, rank mismatch, wrong
    collective result) — callers wanting a process exit code use main().
    """
    import jax

    # A sitecustomize may have pinned another platform at interpreter start;
    # re-assert what this process was handed (same recipe as the allocated
    # bench child) so CPU-mesh callers are not routed to a TPU tunnel.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)
    # Cross-process collectives on the CPU backend need an implementation
    # picked explicitly; gloo is the in-tree one. No effect on TPU.
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    from k8s_gpu_device_plugin_tpu.parallel import multihost

    env = multihost.initialize(
        port=port or multihost.DEFAULT_COORDINATOR_PORT,
        initialization_timeout=init_timeout,
    )
    if env is None or env.num_workers <= 1:
        return {"rank": 0, "nprocs": 1, "distributed": False, "ok": True}

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    try:
        shard_map = jax.shard_map
    except AttributeError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map  # type: ignore

    if jax.process_count() != env.num_workers:
        raise RuntimeError(
            f"world size mismatch: envs promise {env.num_workers} processes, "
            f"jax.distributed sees {jax.process_count()}"
        )
    if jax.process_index() != env.process_id:
        raise RuntimeError(
            f"rank mismatch: envs assign process_id {env.process_id}, "
            f"jax.distributed assigned {jax.process_index()}"
        )

    devices = jax.devices()  # global device list, spans processes
    mesh = Mesh(np.array(devices), ("x",))
    x = jax.jit(
        lambda: jnp.arange(len(devices), dtype=jnp.float32),
        out_shardings=NamedSharding(mesh, P("x")),
    )()
    psum = jax.jit(
        shard_map(
            lambda v: jax.lax.psum(v, "x"), mesh=mesh,
            in_specs=P("x"), out_specs=P("x"),
        )
    )(x)
    # every shard must hold sum(0..ndev-1); check the locally-addressable ones
    expected = float(len(devices) * (len(devices) - 1) // 2)
    local = [float(np.asarray(s.data)[0]) for s in psum.addressable_shards]
    if any(v != expected for v in local):
        raise RuntimeError(f"psum produced {local}, expected {expected}")
    return {
        "rank": jax.process_index(),
        "nprocs": jax.process_count(),
        "ndev": len(devices),
        "psum": expected,
        "distributed": True,
        "ok": True,
    }


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--port", type=int, default=None,
        help="jax.distributed coordination port (host + rank come from envs)",
    )
    parser.add_argument(
        "--init-timeout", type=int, default=60,
        help="seconds to wait for the rendezvous before failing (short fuse: "
        "a preflight should fail in seconds, not jax's 300s default)",
    )
    args = parser.parse_args(argv)
    try:
        report = run_check(port=args.port, init_timeout=args.init_timeout)
    except Exception as e:  # noqa: BLE001 - the contract is one JSON line
        print(json.dumps({"ok": False, "error": f"{type(e).__name__}: {e}"}))
        return 1
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
