"""Tensor-parallel SERVING shardings: the decode path over a tp mesh.

The training stack shards for throughput (parallel/mesh.py +
models/llama.py ``param_specs``: megatron column/row pairs whose row
halves psum partial products). Serving has a harder contract — the
house style pins greedy+seeded token AND logprob streams BIT-identical
across every engine knob, and a psum splits a floating-point reduction
into per-shard partials whose summation order differs from the
single-chip contraction (measurably: bf16 operands, f32 accumulation,
~1e-5 drift — enough to flip a near-tie argmax). So the serving recipe
shards only what stays bitwise exact:

- **Column shards** (``wq``/``wk``/``wv`` + the Qwen2 biases,
  ``w1``/``w3``, ``lm_head``): the contraction runs whole on every
  shard — each device computes its output columns with the same
  K-accumulation order the full matmul uses, so the sharded columns are
  bitwise equal to the corresponding columns of the tp=1 result.
- **Head shards** (the KV cache — dense rows and the paged pool alike —
  and the q/k/v/attention activations): attention is embarrassingly
  parallel over heads (scores, softmax and the V-contraction never
  cross a head), so each shard's heads are bitwise the tp=1 heads. This
  is the serving win the ROADMAP names: the KV HBM per chip drops by
  tp, so a replica holds tp times the pages/slots/prefix entries.
- **Replicated reductions** (``wo``, ``w2``, sampling): the activation
  is gathered to replicated (pure data movement) and the contraction
  runs whole on every device — identical bits, no psum anywhere.

``cfg.tp`` is static (models/llama.py), so the tp=1 graphs are
LITERALLY today's graphs — no mesh, no constraints, nothing to pin.
The constraints in models/generate.py bind only when the dispatch is
traced under the mesh scope the batcher enters around ``step()``.

``cfg.tp_allow_psum`` is the EXPLICIT opt-out: wo/w2 row-shard on their
contraction axes (the megatron pairing) and the partitioner psums the
partials — one collective fewer per layer, at the price of the
bit-identity pin (the operator trades exactness for the last gather).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from k8s_gpu_device_plugin_tpu.parallel.mesh import AXIS_TP, MeshSpec

#: the KV cache's head axis in both layouts — dense (L, B, S, Hkv, hd)
#: and paged (L, n_pages, page_size, Hkv, hd) put it third-from-last
KV_SPEC = P(None, None, None, AXIS_TP, None)
#: (B, T, H, hd) activation sharding for q/k/v and the attention output
HEADS = P(None, None, AXIS_TP, None)
#: fully replicated (the gather point before wo/w2/sampling)
REPLICATED = P()


def serving_mesh(tp: int, n_kv_heads: int, devices: list | None = None
                 ) -> Mesh:
    """A 1-axis ``tp`` mesh over the first ``tp`` devices, validated by
    the shared flag rule (``MeshSpec.from_flags``): tp must divide both
    the visible device count and the KV-head count, failing at startup
    with an actionable error rather than inside a trace."""
    n = len(devices) if devices is not None else len(jax.devices())
    MeshSpec.from_flags(tp=tp, n_devices=n, n_kv_heads=n_kv_heads,
                        exact=True)
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices[:tp]).reshape(tp), (AXIS_TP,))


def ambient_mesh() -> "Mesh | None":
    """The mesh whose scope the caller is tracing under (None outside
    any ``with mesh:`` block). The batcher enters its serving mesh
    around every device dispatch (``_dispatch_scope``), so kernel
    dispatchers traced inside a step can recover the mesh here and
    ``shard_map`` themselves over the tp axis — the seam that keeps the
    Pallas kernels (opaque to the SPMD partitioner) running per-shard
    instead of falling back to the XLA gather. Uses jax's thread-local
    mesh resource (the same state ``with mesh:`` sets); wrapped so the
    private-API touch lives in exactly one place."""
    try:
        from jax._src import mesh as _mesh_lib

        m = _mesh_lib.thread_resources.env.physical_mesh
    except (ImportError, AttributeError):  # pragma: no cover - jax drift
        return None
    return None if m.empty else m


def serving_param_specs(cfg) -> dict:
    """PartitionSpecs per serving parameter (see module docstring for
    why this is NOT training's ``param_specs``): column shards where a
    slice is bitwise the full result, replicated everywhere a shard
    would split a reduction. Dimensions tp does not divide fall back to
    replicated (correct, just unsharded) — only the KV-head divisibility
    is a hard startup requirement."""
    col = P(None, None, AXIS_TP)
    row = P(None, AXIS_TP, None)
    rep2 = P(None, None)
    ff_ok = cfg.d_ff % cfg.tp == 0
    # the explicit bit-identity opt-out (cfg.tp_allow_psum): wo/w2
    # row-shard on their contraction axes — the megatron pairing of the
    # column cuts above — and the partitioner psums the partials instead
    # of gathering the activation first. One collective fewer per layer,
    # but the split f32 reduction ends the tp=1 stream pin.
    psum_ok = bool(getattr(cfg, "tp_allow_psum", False))
    layers = {
        "attn_norm": P(None, None),
        "mlp_norm": P(None, None),
        # q/k/v columns are head-aligned (tp | n_kv_heads | n_heads)
        "wq": col, "wk": col, "wv": col,
        # wo contracts over heads: replicated (the no-psum rule), or
        # row-sharded under the explicit opt-out
        "wo": row if psum_ok else rep2,
    }
    if cfg.attn_bias:
        layers.update({
            "bq": P(None, AXIS_TP), "bk": P(None, AXIS_TP),
            "bv": P(None, AXIS_TP),
        })
    if cfg.is_moe:
        # expert MLPs stay replicated: the dense-mix decode path
        # contracts over experts and d_ff both — no bit-safe column cut
        layers.update({
            "router": rep2,
            "moe_w1": P(None, None, None), "moe_w2": P(None, None, None),
            "moe_w3": P(None, None, None),
        })
    else:
        layers.update({
            "w1": col if ff_ok else rep2,
            "w3": col if ff_ok else rep2,
            # contracts over d_ff: replicated, or row-sharded (psum)
            # under the opt-out — only when the column cuts engaged too
            "w2": row if (psum_ok and ff_ok) else rep2,
        })
    out = {
        "embed": P(None, None),  # token gather: replicated lookup
        "layers": layers,
        "final_norm": P(None),
    }
    if not cfg.tied_embeddings:
        out["lm_head"] = (
            P(None, AXIS_TP) if cfg.vocab_size % cfg.tp == 0
            else P(None, None)
        )
    return out


def _spec_tree_map(fn, specs, tree):
    """Map ``fn(spec, leaf)`` over ``tree`` following ``specs``; leaves
    the spec tree lacks (LoRA stacks, quantized {"q","s"} dicts, Gemma
    extras) replicate — sharding them is a later optimization, serving
    them bit-identically is the contract."""
    if isinstance(tree, dict):
        return {
            k: _spec_tree_map(
                fn, specs.get(k, P()) if isinstance(specs, dict) else P(), v
            )
            for k, v in tree.items()
        }
    if tree is None:
        return None
    return fn(specs if isinstance(specs, P) else P(), tree)


def shard_serving_params(params: dict, cfg, mesh: Mesh) -> dict:
    """device_put the serving weight tree onto the tp mesh per
    :func:`serving_param_specs` — the pjit/NamedSharding load-time shard
    (SNIPPETS.md [1][2]); leaves the spec tree doesn't name (adapter
    stacks, quantized leaves) are replicated."""
    specs = serving_param_specs(cfg)

    def put(spec, leaf):
        try:
            return jax.device_put(leaf, NamedSharding(mesh, spec))
        except ValueError:
            # a dimension the spec's axis doesn't divide (converted
            # checkpoints with odd head counts): replicate it instead
            return jax.device_put(leaf, NamedSharding(mesh, P()))

    return _spec_tree_map(put, specs, params)


def batch_state_shardings(mesh: Mesh) -> dict:
    """NamedShardings per BatchState field: the cache — K/V AND the
    quantized scale planes, whose per-(position, head) layout puts the
    head axis in the same third-from-last slot — on the KV-head axis,
    every other leaf (lengths, masks, key, budgets, page tables)
    replicated. Dense rows and the paged pool share the specs: both
    5-D layouts carry Hkv third-from-last. The page TABLE is replicated
    by design: one host-side allocator hands out page ids that mean the
    same physical page slice on every shard."""
    kv = NamedSharding(mesh, KV_SPEC)
    rep = NamedSharding(mesh, REPLICATED)
    return {
        "cache": {"k": kv, "v": kv, "k_scale": kv, "v_scale": kv},
        "lengths": rep, "last_token": rep, "active": rep,
        "presence": rep, "key": rep, "budget": rep, "draws": rep,
        "pages": rep,
    }


def shard_batch_state(state, mesh: Mesh):
    """device_put a freshly initialized BatchState onto the mesh (init
    only: every jitted step preserves these shardings thereafter)."""
    sh = batch_state_shardings(mesh)

    def put(x, s):
        return None if x is None else jax.device_put(x, s)

    from k8s_gpu_device_plugin_tpu.models.batching import BatchState
    from k8s_gpu_device_plugin_tpu.models.generate import KVCache

    return BatchState(
        cache=KVCache(
            k=put(state.cache.k, sh["cache"]["k"]),
            v=put(state.cache.v, sh["cache"]["v"]),
            k_scale=put(state.cache.k_scale, sh["cache"]["k_scale"]),
            v_scale=put(state.cache.v_scale, sh["cache"]["v_scale"]),
        ),
        lengths=put(state.lengths, sh["lengths"]),
        last_token=put(state.last_token, sh["last_token"]),
        active=put(state.active, sh["active"]),
        presence=put(state.presence, sh["presence"]),
        key=put(state.key, sh["key"]),
        budget=put(state.budget, sh["budget"]),
        draws=put(state.draws, sh["draws"]),
        pages=put(state.pages, sh["pages"]),
    )


def replicate(x, mesh: Mesh):
    """Commit a host-built array onto the mesh replicated — the tp>1
    twin of the batcher's cached device uploads (knobs, masks, seeds,
    the EOS scalar): committed once per membership change, resident
    thereafter, so the steady-state decode loop still transfers nothing
    per step."""
    return jax.device_put(x, NamedSharding(mesh, REPLICATED))
