"""Pipeline parallelism: GSPMD looped pipeline over the ``pp`` mesh axis.

TPU-first construction (no reference analogue — the reference daemon has no
parallelism, SURVEY §2): instead of per-stage processes exchanging
activations over point-to-point sends (the GPU/NCCL idiom), the pipeline is
a single SPMD program. Layer-stacked parameters get an extra leading
*stage* dimension sharded over ``pp``; a circulating activation buffer of
shape ``(pp, microbatch, S, D)`` is also sharded over ``pp`` on its stage
dimension. One pipeline *tick* is:

1. ``jnp.roll(state, 1, axis=0)`` — because the stage dim is sharded over
   ``pp``, XLA lowers this to a single collective-permute hop per tick
   (stage i's output moves to stage i+1's device over ICI/DCN);
2. stage 0's slot is overwritten with the next microbatch;
3. ``vmap`` over the stage dimension applies every stage's layers to the
   microbatch it currently holds — all devices compute every tick.

Running ``n_microbatches + pp - 1`` ticks drains the pipeline; the bubble
fraction is the usual ``(pp-1)/(M+pp-1)``. Autodiff just works: the
transpose of roll is roll, so the backward pass pipelines in reverse with
no hand-written schedule. This is the standard JAX/XLA pipelining idiom
(as used by MaxText/praxis) rather than a port of torch-style stage
processes.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from k8s_gpu_device_plugin_tpu.parallel.mesh import (
    AXIS_DP,
    AXIS_FSDP,
    AXIS_PP,
    AXIS_SP,
    constrain,
)


def stack_for_stages(layer_params, n_stages: int):
    """Reshape layer-stacked params (L, ...) -> (pp, L//pp, ...).

    Layer order is preserved: stage 0 gets layers [0, L/pp), stage 1 the
    next chunk, etc.
    """

    def reshape(leaf):
        L = leaf.shape[0]
        if L % n_stages != 0:
            raise ValueError(
                f"layer count {L} not divisible by pp={n_stages}"
            )
        return leaf.reshape(n_stages, L // n_stages, *leaf.shape[1:])

    return jax.tree.map(reshape, layer_params)


def unstack_stages(layer_params):
    """Inverse of :func:`stack_for_stages`: (pp, Lp, ...) -> (L, ...)."""
    return jax.tree.map(
        lambda leaf: leaf.reshape(leaf.shape[0] * leaf.shape[1], *leaf.shape[2:]),
        layer_params,
    )


def pipeline_blocks(
    stage_fn: Callable,
    stage_params,
    x: jax.Array,
    *,
    n_stages: int,
    n_microbatches: int,
) -> tuple[jax.Array, dict]:
    """Run ``x`` (B, S, D) through ``n_stages`` pipeline stages.

    ``stage_fn(stage_params_i, x_mb) -> (x_mb, aux)`` applies ONE stage's
    layers to one microbatch and returns that stage's scalar aux losses
    (``{}`` for dense stacks); it is vmapped over the leading stage
    dimension of ``stage_params`` (each leaf shaped (pp, L//pp, ...),
    sharded over ``pp``). ``n_microbatches`` must divide the batch B.

    Returns ``(out (B, S, D), aux)`` where each aux leaf is summed over
    stages and averaged over microbatches — matching the unpipelined
    semantics of "sum over layers of a full-batch mean loss" (MoE balance /
    router-z terms are per-token means, so microbatch means average, not
    add). During fill and drain ticks, stages holding no live microbatch
    contribute zero: stage ``s`` holds microbatch ``t - s`` at tick ``t``,
    valid only when ``0 <= t - s < M``.
    """
    B, S, D = x.shape
    M = n_microbatches
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by n_microbatches={M}")
    mb = B // M

    inputs = x.reshape(M, mb, S, D)
    state_spec = P(AXIS_PP, (AXIS_DP, AXIS_FSDP), AXIS_SP, None)
    state = jnp.zeros((n_stages, mb, S, D), x.dtype)
    state = constrain(state, state_spec)
    outputs = jnp.zeros((M, mb, S, D), x.dtype)
    outputs = constrain(outputs, P(None, (AXIS_DP, AXIS_FSDP), AXIS_SP, None))

    # spmd_axis_name keeps the vmapped stage dimension sharded over pp when
    # stage_fn crosses a shard_map boundary (ring/ulysses attention): without
    # it the batching rule threads the stage dim in replicated, all-gathering
    # q/k/v over pp and making every device compute every stage's attention.
    vstages = jax.vmap(stage_fn, spmd_axis_name=AXIS_PP)

    # aux accumulator structure (leaves are (pp,)-shaped per-stage scalars)
    aux_struct = jax.eval_shape(vstages, stage_params, state)[1]
    aux_acc0 = jax.tree.map(lambda _: jnp.zeros((), jnp.float32), aux_struct)

    def tick(carry, t):
        state, outputs, aux_acc = carry
        # stage-dim roll = one collective-permute hop stage i -> i+1
        state = jnp.roll(state, 1, axis=0)
        inp = jax.lax.dynamic_index_in_dim(
            inputs, jnp.clip(t, 0, M - 1), axis=0, keepdims=False
        )
        state = state.at[0].set(inp)
        state = constrain(state, state_spec)
        state, aux_stage = vstages(stage_params, state)
        state = constrain(state, state_spec)
        # mask out stages computing on fill/drain garbage, then accumulate
        live = ((t - jnp.arange(n_stages)) >= 0) & ((t - jnp.arange(n_stages)) < M)
        aux_acc = jax.tree.map(
            lambda acc, leaf: acc
            + jnp.sum(jnp.where(live, leaf.astype(jnp.float32), 0.0)),
            aux_acc,
            aux_stage,
        )
        # collect the last stage's result once the pipeline has filled
        done = state[n_stages - 1]
        out_idx = jnp.clip(t - (n_stages - 1), 0, M - 1)
        updated = jax.lax.dynamic_update_index_in_dim(
            outputs, done, out_idx, axis=0
        )
        outputs = jnp.where(t >= n_stages - 1, updated, outputs)
        return (state, outputs, aux_acc), None

    (_, outputs, aux_acc), _ = jax.lax.scan(
        tick, (state, outputs, aux_acc0), jnp.arange(M + n_stages - 1)
    )
    aux = jax.tree.map(lambda a: a / M, aux_acc)
    return outputs.reshape(B, S, D), aux
