"""Ring attention: blockwise causal attention over a sequence-parallel axis.

Long-context design (first-class per the build goals): the sequence dimension
is sharded over the ``sp`` mesh axis; each device holds one Q block and
rotates K/V blocks around the ring with ``ppermute`` (one ICI hop per step),
accumulating attention with an online (flash-style) softmax in f32. Peak
memory per device is O(S/sp * S/sp) for scores instead of O(S^2), and the
K/V transfer rides exactly the contiguous ICI ring the plugin's aligned
allocation hands out (plugin/allocator.py).

No reference analogue (the reference daemon has no sequence dimension,
SURVEY §5); the technique is the standard Ring Attention construction
(Liu et al., 2023) built from jax shard_map + lax.ppermute collectives.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from k8s_gpu_device_plugin_tpu.parallel.mesh import AXIS_DP, AXIS_FSDP, AXIS_SP

try:  # jax >= 0.4.35 exposes shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

_NEG_BIG = -1e30

from k8s_gpu_device_plugin_tpu.ops.attention import _expand_kv  # noqa: E402


def _block_attn_update(carry, scores, v, mask):
    """One online-softmax accumulation step. All f32.

    carry: (m, l, o) with m,l: (b, h, lq); o: (b, lq, h, d)
    scores: (b, h, lq, lk); v: (b, lk, h, d); mask: broadcastable to scores.
    """
    m, l, o = carry
    scores = jnp.where(mask, scores, _NEG_BIG)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    # fully-masked rows contribute nothing (exp(-BIG - m_new) underflows to 0)
    l_new = l * alpha + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = True,
    axis: str = AXIS_SP,
    batch_axes: tuple[str, ...] = (AXIS_DP, AXIS_FSDP),
    head_axis: str | None = "tp",
    scale: float | None = None,
    window: int = 0,
) -> jax.Array:
    """Attention over sequence-sharded q/k/v of shape (B, S, H, D).

    K/V may have fewer (grouped) heads: the flash path keeps them grouped
    end-to-end (smaller ring hops); the einsum fallback expands locally.
    Returns (B, S, Hq, D) in q's dtype, sharded like q.

    ``window > 0`` adds Mistral-style sliding-window masking (query i
    sees keys in (i - W, i], GLOBAL positions; requires ``causal``). On
    the flash path each ring step classifies its kv block by position
    offset: the diagonal runs the windowed flash kernel, blocks fully
    inside the window run plain flash, blocks fully outside contribute
    zero (and at W << S, most are — windowed ring work scales with W),
    and straddling blocks (up to two: the straddle interval for the
    block offset spans 2*lq - 1 positions) run a masked einsum merged by
    logsumexp. Each case is exact, so the composition is too.
    """
    if window > 0 and not causal:
        raise ValueError("sliding window requires causal attention")
    sp = mesh.shape[axis]
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    spec = P(batch_axes, axis, head_axis, None)

    local = functools.partial(
        _ring_attention_local, sp=sp, causal=causal, axis=axis, scale=scale,
        window=window,
    )
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)


def _flash_ok(lq, lk, d) -> bool:
    """Local shapes the Pallas kernel tiles without padding."""
    from k8s_gpu_device_plugin_tpu.ops.flash_attention import _HAS_PLTPU

    return (
        _HAS_PLTPU
        and d in (64, 128)
        and lq % 128 == 0
        and lk % 128 == 0
        and lq >= 128
        and lk >= 128
    )


def _masked_block_softmax(q, k_blk, v_blk, *, scale, dist, window, hq):
    """Exact softmax attention of one (q-shard, kv-shard) pair under the
    window mask, returning (o normalized f32, lse) for logsumexp merging.
    Used only for ring steps whose block STRADDLES the window boundary
    (at most two per device); grouped KV expands locally here."""
    b, lq, _, d = q.shape
    kb = _expand_kv(k_blk, hq).astype(jnp.float32)
    vb = _expand_kv(v_blk, hq).astype(jnp.float32)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), kb) * scale
    qi = jax.lax.broadcasted_iota(jnp.int32, (lq, k_blk.shape[1]), 0)
    ki = jax.lax.broadcasted_iota(jnp.int32, (lq, k_blk.shape[1]), 1)
    offset = dist + qi - ki        # global q_pos - k_pos for this pair
    mask = (offset >= 1) & (offset < window)
    scores = jnp.where(mask, scores, _NEG_BIG)
    # rows with NO in-window key in this block: max would be _NEG_BIG and
    # exp(scores - max) would be 1 everywhere — derive emptiness from the
    # mask and pin those rows to (o=0, lse=-inf) so the merge ignores them
    empty = ~jnp.any(mask, axis=-1)[None, None, :]   # (1, 1, lq)
    m = jnp.where(empty, 0.0, scores.max(axis=-1))   # (b, h, lq)
    p = jnp.exp(scores - m[..., None])
    l = p.sum(axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, vb)
    o = o / jnp.where(empty, 1.0, l).transpose(0, 2, 1)[..., None]
    lse = jnp.where(empty, _NEG_BIG, m + jnp.log(jnp.where(empty, 1.0, l)))
    return o, lse


def _ring_attention_local(q, k, v, *, sp, causal, axis, scale, window=0):
    """Per-device body: rotate K/V blocks around the ring, accumulate.

    The hot path computes each (q-shard, kv-shard) pair with the Pallas
    flash kernel and merges partial softmaxes via the kernel's lse output;
    a causal ring step is one of three static cases by block owner:
    diagonal (flash causal), past (flash non-causal), future (skipped —
    zero contribution). Falls back to a plain f32 einsum online-softmax
    body when the local shard shapes don't tile the kernel.
    """
    b, lq, h, d = q.shape
    lk = k.shape[1]
    my_idx = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % sp) for i in range(sp)]

    if _flash_ok(lq, lk, d) and lq == lk:
        # GQA-native: K/V rotate around the ring at their Hkv heads — each
        # ppermute hop moves group-times-fewer ICI bytes than the expanded
        # form, and the flash kernel maps q heads onto kv groups itself.
        from k8s_gpu_device_plugin_tpu.ops.flash_attention import flash_attention

        interpret = jax.default_backend() != "tpu"

        def fa(causal_step, win=0):
            o_t, lse_t = flash_attention(
                q, k_blk_ref[0], v_blk_ref[0], causal=causal_step,
                scale=scale, interpret=interpret, return_lse=True,
                window=win,
            )
            return o_t.astype(jnp.float32), lse_t

        def zero():
            return (
                jnp.zeros((b, lq, h, d), jnp.float32),
                jnp.full((b, h, lq), _NEG_BIG, jnp.float32),
            )

        # captured via a mutable cell so both cond branches see the carry
        k_blk_ref = [k]
        v_blk_ref = [v]

        def step(carry, t):
            lse, o, k_blk, v_blk = carry
            k_blk_ref[0] = k_blk
            v_blk_ref[0] = v_blk
            kv_idx = (my_idx - t) % sp
            if causal and window > 0:
                # classify the held block by its global position offset
                # dist = q_block_start - kv_block_start (lq == lk here)
                dist = (my_idx - kv_idx) * lq
                o_t, lse_t = jax.lax.cond(
                    kv_idx == my_idx,
                    lambda: fa(True, win=window),       # diagonal: windowed
                    lambda: jax.lax.cond(
                        kv_idx > my_idx,
                        zero,                           # future block
                        lambda: jax.lax.cond(
                            dist - lq + 1 >= window,
                            zero,                       # fully OUTSIDE window
                            lambda: jax.lax.cond(
                                dist + lq - 1 < window,
                                lambda: fa(False),      # fully INSIDE window
                                lambda: _masked_block_softmax(
                                    q, k_blk_ref[0], v_blk_ref[0],
                                    scale=scale, dist=dist, window=window,
                                    hq=h,
                                ),                      # straddling block
                            ),
                        ),
                    ),
                )
            elif causal:
                o_t, lse_t = jax.lax.cond(
                    kv_idx == my_idx,
                    lambda: fa(True),
                    lambda: jax.lax.cond(
                        kv_idx < my_idx,
                        lambda: fa(False),
                        zero,
                    ),
                )
            else:
                o_t, lse_t = fa(False)
            # merge normalized partials by their logsumexp weights
            m = jnp.maximum(lse, lse_t)
            w1 = jnp.exp(lse - m)
            w2 = jnp.exp(lse_t - m)
            tot = w1 + w2
            wa = (w1 / tot).transpose(0, 2, 1)[..., None]
            wb = (w2 / tot).transpose(0, 2, 1)[..., None]
            o = o * wa + o_t * wb
            lse = m + jnp.log(tot)
            k_blk = jax.lax.ppermute(k_blk, axis, perm)
            v_blk = jax.lax.ppermute(v_blk, axis, perm)
            return (lse, o, k_blk, v_blk), None

        lse0 = jnp.full((b, h, lq), _NEG_BIG, jnp.float32)
        o0 = jnp.zeros((b, lq, h, d), jnp.float32)
        (lse, o, _, _), _ = jax.lax.scan(step, (lse0, o0, k, v), jnp.arange(sp))
        return o.astype(q.dtype)

    # einsum fallback only: expand grouped KV heads to match q's
    k = _expand_kv(k, h)
    v = _expand_kv(v, h)
    qf = q.astype(jnp.float32)
    m0 = jnp.full((b, h, lq), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((b, h, lq), jnp.float32)
    o0 = jnp.zeros((b, lq, h, d), jnp.float32)

    q_pos = my_idx * lq + jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 0)
    k_local_pos = jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 1)

    def step(carry, t):
        m, l, o, k_blk, v_blk = carry
        kv_idx = (my_idx - t) % sp  # owner of the block we currently hold
        scores = (
            jnp.einsum(
                "bqhd,bkhd->bhqk",
                qf,
                k_blk.astype(jnp.float32),
            )
            * scale
        )
        if causal:
            k_pos = kv_idx * lk + k_local_pos
            mask = q_pos >= k_pos                        # global causal mask
            if window > 0:
                mask &= q_pos - k_pos < window           # global window
        else:
            mask = jnp.ones((lq, lk), bool)
        m, l, o = _block_attn_update((m, l, o), scores, v_blk, mask)
        # rotate K/V to the next device; after sp steps they are back home
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        return (m, l, o, k_blk, v_blk), None

    (m, l, o, _, _), _ = jax.lax.scan(
        step, (m0, l0, o0, k, v), jnp.arange(sp)
    )
    l = jnp.where(l == 0.0, 1.0, l)  # rows with nothing attendable
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)
