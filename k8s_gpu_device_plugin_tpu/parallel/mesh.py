"""Device mesh construction and axis conventions.

Axis vocabulary (the scaling-book recipe: pick a mesh, annotate shardings,
let XLA insert collectives):

- ``dp``   pure data parallelism (gradients all-reduced over ICI/DCN)
- ``fsdp`` data parallelism with parameter sharding (ZeRO-3 style;
           params all-gathered per layer, grads reduce-scattered)
- ``tp``   tensor (megatron-style) parallelism within attention/MLP blocks
- ``sp``   sequence/context parallelism for long sequences (ring attention
           or Ulysses all-to-all over this axis)
- ``ep``   expert parallelism for MoE layers

On hardware, mesh axes should be laid out so ``tp``/``sp`` (latency-bound,
per-layer collectives) map to the innermost ICI dimensions of the slice the
plugin allocated, and ``dp``/``fsdp`` to the outer dimensions / DCN.
``jax.experimental.mesh_utils.create_device_mesh`` does that given the axis
sizes in this order.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_DP = "dp"
AXIS_FSDP = "fsdp"
AXIS_TP = "tp"
AXIS_SP = "sp"
AXIS_EP = "ep"
AXIS_PP = "pp"

# Outer-to-inner order: dp/pp ride DCN / outer ICI (pipeline stage hops are
# infrequent point-to-point transfers, tolerant of low bandwidth); fsdp next;
# tp/sp want the innermost (fastest, all-neighbors) ICI links.
AXIS_ORDER = (AXIS_DP, AXIS_PP, AXIS_FSDP, AXIS_EP, AXIS_SP, AXIS_TP)


@dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape. Axes of size 1 still exist in the Mesh (so the
    same PartitionSpecs work at any scale)."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.fsdp * self.tp * self.sp * self.ep * self.pp

    def sizes(self) -> dict[str, int]:
        return {
            AXIS_DP: self.dp,
            AXIS_PP: self.pp,
            AXIS_FSDP: self.fsdp,
            AXIS_EP: self.ep,
            AXIS_SP: self.sp,
            AXIS_TP: self.tp,
        }

    @staticmethod
    def for_devices(
        n: int,
        tp: int = 1,
        sp: int = 1,
        ep: int = 1,
        pp: int = 1,
        fsdp: int | None = None,
    ) -> "MeshSpec":
        """Fill dp (or fsdp) with whatever ``n`` leaves over tp*sp*ep*pp."""
        inner = tp * sp * ep * pp
        if n % inner != 0:
            raise ValueError(f"{n} devices not divisible by tp*sp*ep*pp={inner}")
        rest = n // inner
        if fsdp is None:
            return MeshSpec(dp=rest, fsdp=1, tp=tp, sp=sp, ep=ep, pp=pp)
        if rest % fsdp != 0:
            raise ValueError(f"remainder {rest} not divisible by fsdp={fsdp}")
        return MeshSpec(dp=rest // fsdp, fsdp=fsdp, tp=tp, sp=sp, ep=ep, pp=pp)

    @staticmethod
    def from_flags(
        tp: int = 1,
        sp: int = 1,
        ep: int = 1,
        pp: int = 1,
        fsdp: int | None = None,
        n_devices: int | None = None,
        n_kv_heads: int | None = None,
        exact: bool = False,
    ) -> "MeshSpec":
        """The ONE mesh-construction/validation rule behind every CLI
        surface (the trainer's --tp/--sp/... flags and the inference
        server's --tp), so flag errors mean the same thing everywhere
        and fail at STARTUP with an actionable message instead of deep
        inside a pjit trace.

        ``n_devices`` defaults to ``len(jax.devices())``. ``exact=True``
        is the SERVING shape: dp/fsdp stay 1 (the returned spec spans
        exactly tp*sp*ep*pp devices — the serving mesh never
        data-parallels leftovers, chips beyond it simply stay unused);
        the divisibility check below still applies either way, because
        a tp that doesn't divide the allocated chip count is almost
        always a mis-sized flag, and failing loudly at startup beats
        silently serving a lopsided slice. ``n_kv_heads`` adds the
        serving KV-shard divisibility check: the cache shards on the
        KV-head axis, so tp must divide it."""
        if n_devices is None:
            n_devices = len(jax.devices())
        inner = tp * sp * ep * pp
        if tp < 1:
            raise ValueError(f"tp must be >= 1, got {tp}")
        if inner > n_devices:
            raise ValueError(
                f"mesh tp*sp*ep*pp={inner} needs {inner} devices but only "
                f"{n_devices} are visible; lower the axis sizes or "
                "allocate a larger slice"
            )
        if n_devices % inner != 0:
            raise ValueError(
                f"{n_devices} devices not divisible by tp*sp*ep*pp="
                f"{inner}; pick axis sizes whose product divides the "
                "device count (a non-dividing size is almost always a "
                "mis-sized flag — fail at startup, not mid-trace)"
            )
        if n_kv_heads is not None and n_kv_heads % tp != 0:
            raise ValueError(
                f"tp={tp} does not divide n_kv_heads={n_kv_heads}: the "
                "serving KV cache shards on the KV-head axis, so every "
                "shard must hold a whole number of heads — pick a tp "
                f"from the divisors of {n_kv_heads}"
            )
        if exact:
            # serving: the mesh IS the device set (dp/fsdp stay 1)
            return MeshSpec(dp=1, fsdp=1, tp=tp, sp=sp, ep=ep, pp=pp)
        return MeshSpec.for_devices(
            n_devices, tp=tp, sp=sp, ep=ep, pp=pp, fsdp=fsdp
        )


def make_mesh(spec: MeshSpec, devices: list | None = None) -> Mesh:
    """Build a Mesh with ICI-friendly physical layout."""
    devices = devices if devices is not None else jax.devices()
    if spec.num_devices > len(devices):
        raise ValueError(
            f"mesh needs {spec.num_devices} devices, have {len(devices)}"
        )
    devices = devices[: spec.num_devices]
    shape = tuple(spec.sizes()[a] for a in AXIS_ORDER)
    try:
        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except (ValueError, AssertionError):
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def batch_spec() -> P:
    """Sharding of the batch dimension: data-parallel over dp+fsdp."""
    return P((AXIS_DP, AXIS_FSDP))


def data_sharding(mesh: Mesh, *trailing: object) -> NamedSharding:
    """NamedSharding for (batch, seq, ...) arrays: batch over dp/fsdp, seq
    over sp."""
    return NamedSharding(mesh, P((AXIS_DP, AXIS_FSDP), AXIS_SP, *trailing))


def constrain(x, spec: P):
    """with_sharding_constraint that is a no-op outside jit/mesh contexts."""
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (ValueError, RuntimeError):
        return x
