"""Multi-host / multislice workload initialization and mesh construction.

The daemon side of multi-host scheduling lives in plugin/plugin.py (Allocate
injects TPU_WORKER_ID / TPU_WORKER_HOSTNAMES / TPU_PROCESS_BOUNDS /
MEGASCALE_*); this module is the matching WORKLOAD side: a pod entrypoint
calls :func:`initialize` before any other JAX API, then builds a global mesh
with :func:`make_global_mesh`, and every pjit'd step function works unchanged
— XLA routes intra-slice collectives over ICI and inter-slice traffic over
DCN (the scaling-book recipe; the reference has no analogue — its only
cross-process channel was kubelet gRPC, SURVEY §2 "distributed communication
backend: absent").

Design notes:
- ``jax.distributed.initialize`` wants (coordinator, num_processes,
  process_id); all three derive from the envs the plugin injected, so the
  common case is a zero-argument call.
- The DCN axis must be OUTERMOST: ``mesh_utils.create_hybrid_device_mesh``
  places slow (DCN) axes first, matching parallel/mesh.py's AXIS_ORDER where
  dp/pp lead — gradient all-reduces tolerate DCN latency, per-layer tp/sp
  collectives do not.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from k8s_gpu_device_plugin_tpu.parallel.mesh import AXIS_ORDER, MeshSpec, make_mesh

DEFAULT_COORDINATOR_PORT = 8476


@dataclass(frozen=True)
class WorkerEnv:
    """The multi-host identity a pod reads from its plugin-injected envs."""

    worker_id: int
    hostnames: tuple[str, ...]
    num_slices: int = 1
    slice_id: int = 0
    coordinator: str = ""    # MEGASCALE_COORDINATOR_ADDRESS (multislice only)

    @property
    def num_workers(self) -> int:
        return max(len(self.hostnames), 1) * self.num_slices

    @property
    def process_id(self) -> int:
        """Global process rank: slices are ranked outer, workers inner."""
        return self.slice_id * max(len(self.hostnames), 1) + self.worker_id

    @property
    def coordinator_host(self) -> str:
        """Host every process must agree on: for multislice that is the
        MEGASCALE coordinator (slice 0 / worker 0 of the JOB — hostnames[0]
        is only slice-local and would split the job into per-slice groups);
        single slice, the rank-0 worker."""
        if self.num_slices > 1 and self.coordinator:
            return self.coordinator.rsplit(":", 1)[0]
        return self.hostnames[0] if self.hostnames else "localhost"


def worker_env() -> WorkerEnv | None:
    """Parse the plugin's env contract; None on single-process pods.

    A pod is distributed if it has peers on its own slice
    (TPU_WORKER_HOSTNAMES) OR peers on other slices (MEGASCALE_NUM_SLICES>1)
    — gating on hostnames alone would silently skip jax.distributed init for
    a multislice job of single-host slices.
    """
    hostnames = tuple(
        h.strip()
        for h in os.environ.get("TPU_WORKER_HOSTNAMES", "").split(",")
        if h.strip()
    )
    if not hostnames and int(os.environ.get("MEGASCALE_NUM_SLICES", "1")) <= 1:
        return None
    return WorkerEnv(
        worker_id=int(os.environ.get("TPU_WORKER_ID", "0")),
        hostnames=hostnames,
        num_slices=int(os.environ.get("MEGASCALE_NUM_SLICES", "1")),
        slice_id=int(os.environ.get("MEGASCALE_SLICE_ID", "0")),
        coordinator=os.environ.get("MEGASCALE_COORDINATOR_ADDRESS", ""),
    )


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    port: int = DEFAULT_COORDINATOR_PORT,
    initialization_timeout: int = 300,
) -> WorkerEnv | None:
    """``jax.distributed.initialize`` from the plugin's Allocate envs.

    Call FIRST in a multi-host pod (before any jax.devices()/jit). On a
    single-process pod (no TPU_WORKER_HOSTNAMES) this is a no-op, so the
    same entrypoint works at every scale. ``initialization_timeout`` bounds
    the rendezvous wait — preflight checks want a short fuse so a wrong
    coordinator/rank/world-size fails in seconds, not minutes.
    """
    env = worker_env()
    if coordinator_address is None and (env is None or env.num_workers <= 1):
        # Nothing to rendezvous: no worker contract, or a 1-worker job
        # (e.g. single-host environments that still export
        # TPU_WORKER_HOSTNAMES=localhost). jax.distributed would only add a
        # failure mode here.
        return env
    if coordinator_address is None:
        coordinator_address = f"{env.coordinator_host}:{port}"
    if num_processes is None:
        num_processes = env.num_workers if env else 1
    if process_id is None:
        process_id = env.process_id if env else 0
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            initialization_timeout=initialization_timeout,
        )
    except RuntimeError as e:
        # idempotent re-entry: a second call in the same process is fine
        if "already initialized" not in str(e).lower():
            raise
    return env


def make_global_mesh(
    spec: MeshSpec,
    num_slices: int = 1,
    devices: list | None = None,
) -> Mesh:
    """Global mesh over every process's devices, DCN-aware.

    For one slice this is parallel/mesh.make_mesh over ``jax.devices()``
    (which, after :func:`initialize`, spans hosts). For multislice, the
    leading dp axis is split over DCN: dp must be a multiple of
    ``num_slices`` and each slice keeps dp/num_slices of it locally.
    """
    devices = devices if devices is not None else jax.devices()
    if spec.num_devices != len(devices):
        raise ValueError(
            f"mesh spec needs {spec.num_devices} devices, have {len(devices)}"
        )
    sizes = spec.sizes()
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    if num_slices > 1:
        if spec.dp % num_slices != 0:
            raise ValueError(
                f"dp={spec.dp} must be a multiple of num_slices={num_slices}"
            )
        # (dcn dp) x (ici dp, pp, fsdp, ep, sp, tp)
        ici_shape = (spec.dp // num_slices,) + shape[1:]
        dcn_shape = (num_slices,) + tuple(1 for _ in shape[1:])
        has_slice_meta = all(
            getattr(d, "slice_index", None) is not None for d in devices
        )
        if has_slice_meta:
            # Real multislice hardware: any error here is a genuine
            # placement problem and must propagate, not be papered over.
            dev_array = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devices
            )
        else:
            # Test platforms (CPU) carry no slice metadata; a row-major
            # reshape keeps the outer-dp-over-DCN axis semantics.
            dev_array = np.asarray(devices).reshape(shape)
        return Mesh(dev_array, AXIS_ORDER)
    return make_mesh(spec, devices)


def process_local_batch_size(global_batch: int) -> int:
    """Per-process batch share for data loading (global arrays are formed
    with jax.make_array_from_process_local_data)."""
    n = jax.process_count()
    if global_batch % n != 0:
        raise ValueError(f"global batch {global_batch} not divisible by {n} processes")
    return global_batch // n
