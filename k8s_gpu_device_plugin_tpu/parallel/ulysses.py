"""Ulysses-style sequence parallelism: all-to-all head/sequence resharding.

The second long-context strategy (DeepSpeed-Ulysses construction): instead of
rotating K/V blocks (ring_attention.py), re-shard with two all-to-alls —
(B, S/sp, H, D) -> (B, S, H/sp, D) — run *full-sequence* attention on each
device's head subset, and shard back. One pair of all-to-alls per attention
call (cheap on ICI) versus sp ppermute rounds for ring; the trade is HBM:
Ulysses materializes full-length K/V per device, so ring wins at extreme
sequence lengths while Ulysses wins when heads >> sp and S fits.

Requires Hq and Hkv divisible by sp.
"""

from __future__ import annotations

import functools

import jax
from jax.sharding import Mesh, PartitionSpec as P

from k8s_gpu_device_plugin_tpu.parallel.mesh import AXIS_DP, AXIS_FSDP, AXIS_SP

try:
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = True,
    axis: str = AXIS_SP,
    batch_axes: tuple[str, ...] = (AXIS_DP, AXIS_FSDP),
    head_axis: str | None = "tp",
    scale: float | None = None,
) -> jax.Array:
    """Attention over sequence-sharded (B, S, H, D) via all-to-all resharding."""
    sp = mesh.shape[axis]
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    # heads are already sharded over head_axis before the all-to-all splits
    # the LOCAL head dim by sp, so divisibility is on the per-shard count
    tp = mesh.shape[head_axis] if head_axis else 1
    for name, t in (("q", q), ("k", k), ("v", v)):
        if t.shape[2] % tp != 0 or (t.shape[2] // tp) % sp != 0:
            raise ValueError(
                f"ulysses needs {name} heads ({t.shape[2]}) divisible by "
                f"{head_axis or 'tp'}({tp}) x sp({sp})"
            )
    spec = P(batch_axes, axis, head_axis, None)

    local = functools.partial(_ulysses_local, causal=causal, axis=axis, scale=scale)
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)


def _ulysses_local(q, k, v, *, causal, axis, scale):
    # (b, s_local, h, d) -> (b, s_full, h_local, d): gather seq, scatter heads
    def seq_to_heads(x):
        return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

    # the dispatcher, not mha_reference: on TPU the per-shard full-sequence
    # attention is exactly the long-S case the Pallas flash kernel exists
    # for (the reference materializes (B, H, S, S) f32 scores per shard)
    from k8s_gpu_device_plugin_tpu.ops.attention import attention

    out = attention(
        seq_to_heads(q), seq_to_heads(k), seq_to_heads(v), causal=causal, scale=scale
    )
    return heads_to_seq(out)
