"""Ulysses-style sequence parallelism: all-to-all head/sequence resharding.

The second long-context strategy (DeepSpeed-Ulysses construction): instead of
rotating K/V blocks (ring_attention.py), re-shard with two all-to-alls —
(B, S/sp, H, D) -> (B, S, H/sp, D) — run *full-sequence* attention on each
device's head subset, and shard back. One pair of all-to-alls per attention
call (cheap on ICI) versus sp ppermute rounds for ring; the trade is HBM:
Ulysses materializes full-length K/V per device, so ring wins at extreme
sequence lengths while Ulysses wins when heads >> sp and S fits.

Requires Hq and Hkv divisible by sp.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from k8s_gpu_device_plugin_tpu.parallel.mesh import AXIS_DP, AXIS_FSDP, AXIS_SP

try:
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore


def _attn_full(q, k, v, causal, scale):
    """Plain f32 softmax attention over full sequences (b, s, h, d)."""
    n_rep = q.shape[2] // k.shape[2]
    if n_rep > 1:
        k = jnp.repeat(k, n_rep, axis=2)
        v = jnp.repeat(v, n_rep, axis=2)
    scores = (
        jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
        * scale
    )
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        mask = jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 0) >= (
            jax.lax.broadcasted_iota(jnp.int32, (s_q, s_k), 1)
        )
        scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    causal: bool = True,
    axis: str = AXIS_SP,
    batch_axes: tuple[str, ...] = (AXIS_DP, AXIS_FSDP),
    head_axis: str | None = "tp",
    scale: float | None = None,
) -> jax.Array:
    """Attention over sequence-sharded (B, S, H, D) via all-to-all resharding."""
    sp = mesh.shape[axis]
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    for name, t in (("q", q), ("k", k), ("v", v)):
        if t.shape[2] % sp != 0:
            raise ValueError(
                f"ulysses needs {name} heads ({t.shape[2]}) divisible by sp={sp}"
            )
    spec = P(batch_axes, axis, head_axis, None)

    local = functools.partial(_ulysses_local, causal=causal, axis=axis, scale=scale)
    return shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )(q, k, v)


def _ulysses_local(q, k, v, *, causal, axis, scale):
    # (b, s_local, h, d) -> (b, s_full, h_local, d): gather seq, scatter heads
    def seq_to_heads(x):
        return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

    out = _attn_full(
        seq_to_heads(q), seq_to_heads(k), seq_to_heads(v), causal, scale
    )
    return heads_to_seq(out)
