"""Sharding-plan shape checker: does a model fit a mesh, before hardware?

BASELINE #5 names Llama-3-70B on a multi-host v5p-32 slice. Nobody should
discover an OOM (or a DCN-routed tp collective) by burning a slice
reservation; this module proves the plan with zero devices:

- **Memory**: ``jax.eval_shape`` materializes the parameter and optimizer
  pytrees as shapes only; each leaf's per-chip bytes follow from its
  PartitionSpec (models/llama.py:param_specs — the REAL training specs,
  not a copy) divided by the mesh axes it shards over. Activation
  checkpoints are accounted per remat policy from the exact tensors the
  block checkpoint saves (save_dots_attn / save_dots / save_nothing,
  models/llama.py block remat), and the (B,S,V) logits transient rides on
  top when fused_ce is off.
- **Collective placement**: along any mesh axis, the devices at fixed
  other coordinates form a constant-stride run of the device list
  (row-major reshape, parallel/mesh.py:make_mesh). On a TPU slice the
  device list follows the torus traversal, so stride-1 axes are
  ICI-adjacent neighbors; the highest-traffic axis (tp: per-layer
  all-reduces) must sit innermost (stride 1) and dp (one gradient psum
  per step, DCN-tolerant) outermost. ``axis_strides`` exposes the strides
  so the plan test pins that ordering.

The reference has no analogue: its placement logic ends at NUMA-aware
device scoring inside one host (≙ gpuallocator best-effort policy); slice
-level fit/placement planning is a TPU-first addition.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import numpy as np

from k8s_gpu_device_plugin_tpu.models.llama import (
    LlamaConfig,
    init_params,
    param_specs,
)
from k8s_gpu_device_plugin_tpu.parallel.mesh import AXIS_ORDER, MeshSpec

GiB = 1024**3


def _hbm_gib() -> dict[str, int]:
    """Per-chip HBM budgets from the one authoritative generation table
    (device/topology.py:GENERATIONS) — no second hand-typed copy to
    drift."""
    from k8s_gpu_device_plugin_tpu.device.topology import GENERATIONS

    return {name: g.hbm_bytes // GiB for name, g in GENERATIONS.items()}


HBM_GIB = _hbm_gib()


def _leaf_shard_bytes(leaf, spec, sizes: dict[str, int]) -> float:
    """Per-chip bytes of one sharded leaf: total bytes over the product of
    the mesh axes its PartitionSpec names (axes of size 1 divide by 1)."""
    total = math.prod(leaf.shape) * leaf.dtype.itemsize
    div = 1
    for entry in spec:
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        for ax in axes:
            div *= sizes[ax]
    return total / div


def _tree_shard_bytes(tree, specs, sizes: dict[str, int]) -> float:
    leaves_and_specs = jax.tree.map(
        lambda leaf, spec: _leaf_shard_bytes(leaf, spec, sizes),
        tree,
        specs,
        is_leaf=lambda x: hasattr(x, "shape") and hasattr(x, "dtype"),
    )
    return float(sum(jax.tree.leaves(leaves_and_specs)))


@dataclass(frozen=True)
class MemoryPlan:
    """Per-chip HBM accounting for one (config, mesh, batch) plan; all
    fields in GiB."""

    params: float
    grads: float
    opt_state: float
    compute_cast: float      # bf16 working copy when master weights are f32
    activations: float       # remat-saved checkpoints live through backward
    logits_transient: float  # (B,S,V) f32 when fused_ce is off
    tokens_per_chip: int

    @property
    def total(self) -> float:
        return (self.params + self.grads + self.opt_state + self.compute_cast
                + self.activations + self.logits_transient)

    def fits(self, hbm_gib: float, headroom: float = 0.10) -> bool:
        """True if the plan leaves ``headroom`` of the budget free (XLA
        scratch, collective buffers, fragmentation)."""
        return self.total <= hbm_gib * (1.0 - headroom)


def _activation_bytes_per_token_layer(cfg: LlamaConfig, tp: int) -> float:
    """Bytes/token/layer the block checkpoint KEEPS through the backward.

    Mirrors models/llama.py's remat policies: the scan block always saves
    its input carry (B,S,d); the policies add the named projection/MLP dot
    outputs. tp shards the head/ff dims of q/k/v/attn_out/w1/w3; the
    d-dimension activations (wo out, w2 out, carry) are unsharded across
    tp (they are sharded over batch/seq axes, handled by tokens_per_chip).
    """
    d = cfg.d_model
    kv = cfg.n_kv_heads * cfg.head_dim
    itemsize = np.dtype(cfg.dtype).itemsize
    carry = d  # block input, always saved by jax.checkpoint
    if cfg.remat_policy == "save_nothing":
        sharded, unsharded = 0.0, carry
    else:
        # dots: q(d) + k(kv) + v(kv) + w1(d_ff) + w3(d_ff) sharded over tp;
        # wo out (d) + w2 out (d) unsharded
        sharded = d + 2 * kv + 2 * cfg.d_ff
        unsharded = carry + 2 * d
        if cfg.remat_policy == "save_dots_attn":
            sharded += d  # the named attention output (B,S,Hq*hd)
    return (sharded / tp + unsharded) * itemsize


def memory_plan(
    cfg: LlamaConfig,
    spec: MeshSpec,
    batch_size: int,
    seq_len: int,
) -> MemoryPlan:
    """Per-chip HBM plan for one full training step (params + AdamW state
    + grads + remat checkpoints + the logits transient). pp>1 divides the
    layer stacks across stages; microbatch pipelining keeps one
    microbatch's activations per stage in flight, which this first-order
    model approximates by the per-chip token share."""
    if not cfg.remat:
        raise ValueError(
            "memory_plan models the remat-checkpoint policies only; with "
            "cfg.remat=False every block intermediate lives through the "
            "backward (several times the save_dots_attn estimate) and a "
            "'fits' verdict here would be meaningless"
        )
    sizes = spec.sizes()
    specs = param_specs(cfg, pp=spec.pp)

    def init_fn(key):
        params = init_params(key, cfg)
        if spec.pp > 1:
            from k8s_gpu_device_plugin_tpu.parallel.pipeline import (
                stack_for_stages,
            )

            params = {**params, "layers": stack_for_stages(
                params["layers"], spec.pp
            )}
        return params

    abstract = jax.eval_shape(init_fn, jax.random.key(0))
    params_b = _tree_shard_bytes(abstract, specs, sizes)

    # AdamW: two moments shaped/sharded like the params, plus scalars.
    opt_b = 2.0 * params_b
    # grads: cotangents of the PARAMS, so they carry p_dtype — a master-
    # weight run (f32 params, bf16 compute) produces f32 grads (the
    # astype in cast_params_for_compute upcasts the cotangent)
    grads_b = params_b
    # ...and additionally keeps a compute-dtype working copy of the LAYER
    # STACKS through the step — cast_params_for_compute casts only
    # params["layers"]; embed/lm_head/norms stay in p_dtype
    itemsize_c = np.dtype(cfg.dtype).itemsize
    itemsize_p = np.dtype(cfg.p_dtype).itemsize
    cast_b = 0.0
    if cfg.p_dtype != cfg.dtype:
        layers_b = _tree_shard_bytes(
            abstract["layers"], specs["layers"], sizes
        )
        cast_b = layers_b * itemsize_c / itemsize_p

    # batch/seq sharding (models/train.py:batch_shardings): batch over
    # (dp, fsdp), seq over sp
    tokens_per_chip = math.ceil(
        batch_size * seq_len / (spec.dp * spec.fsdp * spec.sp)
    )
    per_tok_layer = _activation_bytes_per_token_layer(cfg, spec.tp)
    layers_resident = cfg.n_layers / spec.pp
    act_b = per_tok_layer * layers_resident * tokens_per_chip

    logits_b = 0.0
    if not (cfg.fused_ce and spec.tp == 1):
        # f32 logits, vocab sharded over tp (lm_head P(fsdp, tp)). The
        # fused-CE path only actually runs with the vocab axis unsharded
        # (train.py:loss_fn falls back to unfused at tp>1), so fused_ce
        # removes this row only when the mesh allows it to engage.
        logits_b = tokens_per_chip * cfg.vocab_size * 4 / spec.tp

    return MemoryPlan(
        params=params_b / GiB,
        grads=grads_b / GiB,
        opt_state=opt_b / GiB,
        compute_cast=cast_b / GiB,
        activations=act_b / GiB,
        logits_transient=logits_b / GiB,
        tokens_per_chip=tokens_per_chip,
    )


def axis_strides(spec: MeshSpec) -> dict[str, int]:
    """LOGICAL device-list stride along each mesh axis (size>1 only).

    Models the nesting contract make_mesh requests of both its paths:
    AXIS_ORDER puts dp outermost and tp innermost, so in the row-major
    arrangement axis a advances by the product of the inner axes' sizes
    (stride 1 = adjacent device-list entries). This is exact for
    make_mesh's reshape fallback (virtual/CPU meshes) and is the
    requested shape handed to mesh_utils.create_device_mesh, which then
    optimizes PHYSICAL placement for that ordering; for a mesh built on
    real hardware, read the as-built arrangement with
    :func:`mesh_axis_strides` instead of trusting this model.
    """
    sizes = spec.sizes()
    shape = [sizes[a] for a in AXIS_ORDER]
    arr = np.arange(spec.num_devices).reshape(shape)
    return _array_strides(arr)


def mesh_axis_strides(mesh) -> dict[str, tuple[int, ...]]:
    """Device-ID strides of an ACTUALLY BUILT Mesh's device array, per
    axis — the as-built counterpart of :func:`axis_strides` for plans
    being validated against a live mesh (create_device_mesh may permute
    devices for physical topology, so strides need not be constant;
    every distinct step is reported)."""
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    out: dict[str, tuple[int, ...]] = {}
    for i, a in enumerate(mesh.axis_names):
        if ids.shape[i] == 1:
            continue
        diffs = np.diff(ids, axis=i)
        out[a] = tuple(int(v) for v in np.unique(diffs))
    return out


def _array_strides(arr: np.ndarray) -> dict[str, int]:
    out: dict[str, int] = {}
    for i, a in enumerate(AXIS_ORDER):
        if arr.shape[i] == 1:
            continue
        first = np.take(arr, 0, axis=i)
        second = np.take(arr, 1, axis=i)
        strides = np.unique(second - first)
        assert strides.size == 1  # row-major reshape: constant by design
        out[a] = int(strides[0])
    return out


def _main(argv=None) -> int:
    """CLI: ``python -m k8s_gpu_device_plugin_tpu.parallel.plan --preset
    llama3_70b --fsdp 8 --tp 4 --batch 8 --seq 8192 --hbm v5p`` prints the
    per-chip plan and exits 1 when it does not fit (CI-able gate for a
    planned run)."""
    import argparse
    import json
    import os

    # a plan check never needs an accelerator — force CPU before any
    # array exists (module imports build no arrays; the first one is
    # eval_shape's concrete key argument), or a pinned wedged TPU
    # backend hangs the CLI
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    from jax._src import xla_bridge as _xb

    if _xb.backends_are_initialized():
        from jax.extend.backend import clear_backends

        clear_backends()

    parser = argparse.ArgumentParser(description=_main.__doc__)
    parser.add_argument("--preset", default="llama3_70b")
    parser.add_argument("--dp", type=int, default=1)
    parser.add_argument("--fsdp", type=int, default=1)
    parser.add_argument("--tp", type=int, default=1)
    parser.add_argument("--sp", type=int, default=1)
    parser.add_argument("--ep", type=int, default=1)
    parser.add_argument("--pp", type=int, default=1)
    parser.add_argument("--batch", type=int, default=8)
    parser.add_argument("--seq", type=int, default=8192)
    parser.add_argument("--rematPolicy", default=None,
                        choices=[None, "save_dots_attn", "save_dots",
                                 "save_nothing"])
    parser.add_argument("--fusedCE", action="store_true")
    parser.add_argument("--masterWeights", action="store_true")
    parser.add_argument("--hbm", default="v5p",
                        help=f"chip generation ({sorted(HBM_GIB)}) or GiB")
    parser.add_argument("--headroom", type=float, default=0.10)
    args = parser.parse_args(argv)

    from dataclasses import replace

    cfg = getattr(LlamaConfig, args.preset)()
    overrides = {}
    if args.rematPolicy:
        overrides["remat_policy"] = args.rematPolicy
    if args.fusedCE:
        overrides["fused_ce"] = True
    if args.masterWeights:
        import jax.numpy as jnp

        overrides["param_dtype"] = jnp.float32
    if overrides:
        cfg = replace(cfg, **overrides)
    spec = MeshSpec(dp=args.dp, fsdp=args.fsdp, tp=args.tp, sp=args.sp,
                    ep=args.ep, pp=args.pp)
    hbm = HBM_GIB[args.hbm] if args.hbm in HBM_GIB else float(args.hbm)
    plan = memory_plan(cfg, spec, args.batch, args.seq)
    fits = plan.fits(hbm, headroom=args.headroom)
    print(json.dumps({
        "preset": args.preset,
        "mesh": {k: v for k, v in spec.sizes().items() if v > 1},
        "devices": spec.num_devices,
        "batch": args.batch,
        "seq": args.seq,
        "remat_policy": cfg.remat_policy,
        "per_chip_gib": {
            "params": round(plan.params, 2),
            "grads": round(plan.grads, 2),
            "opt_state": round(plan.opt_state, 2),
            "compute_cast": round(plan.compute_cast, 2),
            "activations": round(plan.activations, 2),
            "logits_transient": round(plan.logits_transient, 2),
            "total": round(plan.total, 2),
        },
        "hbm_gib": hbm,
        "headroom": args.headroom,
        "fits": fits,
        "axis_strides": axis_strides(spec),
    }, indent=1))
    return 0 if fits else 1


if __name__ == "__main__":
    import sys

    sys.exit(_main())
