"""Multi-host TRAIN-STEP preflight: rendezvous + sharded steps across
processes.

``rendezvous_check`` proves the Allocate env contract can form a world and
psum; this goes the rest of the way: each worker initializes
``jax.distributed`` from the plugin-injected envs (TPU_WORKER_ID /
TPU_WORKER_HOSTNAMES / MEGASCALE_*, plugin/plugin.py:_container_allocate),
builds ONE GLOBAL MESH spanning every process's devices, and jits the
framework's real training step over it — dp crossing the process boundary
(gradient psum over the inter-host link), tp/sp inside each process. Two
steps run; every rank must report the identical global loss or the exit
code is nonzero.

This is the preflight a multi-host training job actually needs: the
rendezvous can succeed while the SHARDED step still deadlocks or diverges
(wrong mesh axis order, a collective crossing the wrong link, per-process
batch skew). The reference has no analogue — its cross-process story ends
at injecting NVIDIA_VISIBLE_DEVICES per container; here the worker side of
the contract is exercised end to end.

Usage (one process per worker, wearing the Allocate envs):
    python -m k8s_gpu_device_plugin_tpu.parallel.multihost_step \
        [--port N] [--steps K] [--batch B] [--seq S]

Prints ONE JSON line {rank, nprocs, ndev, mesh, losses, ok}; exit 0 iff
the distributed steps ran and produced finite losses.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def run_step_check(
    port: int | None = None,
    init_timeout: int = 60,
    steps: int = 2,
    batch_size: int = 4,
    seq_len: int = 32,
) -> dict:
    """Initialize from envs, run ``steps`` sharded train steps, report."""
    import jax

    # Same platform/collectives recipe as rendezvous_check: re-assert the
    # handed-down platform (a sitecustomize may pin another) and pick the
    # in-tree CPU collectives implementation for cross-process psums.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    from k8s_gpu_device_plugin_tpu.obs.trace import get_tracer
    from k8s_gpu_device_plugin_tpu.parallel import multihost

    tr = get_tracer()
    with tr.span("rendezvous", component="trainer"):
        env = multihost.initialize(
            port=port or multihost.DEFAULT_COORDINATOR_PORT,
            initialization_timeout=init_timeout,
        )
    if env is None or env.num_workers <= 1:
        raise RuntimeError(
            "no multi-host env contract found (TPU_WORKER_HOSTNAMES / "
            "MEGASCALE_* unset) — this preflight needs >= 2 workers"
        )
    if jax.process_count() != env.num_workers:
        raise RuntimeError(
            f"world size mismatch: envs promise {env.num_workers}, "
            f"jax.distributed sees {jax.process_count()}"
        )

    import jax.numpy as jnp

    from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig
    from k8s_gpu_device_plugin_tpu.models.train import (
        init_train_state,
        make_optimizer,
        make_train_step,
        synthetic_batch,
    )
    from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec

    devices = jax.devices()  # global: spans every process after initialize
    ndev = len(devices)
    # dp is OUTERMOST in AXIS_ORDER and jax.devices() lists process 0's
    # devices first, so the row-major mesh reshape puts dp across the
    # process boundary: the gradient psum rides the inter-host link while
    # tp (and sp when it fits) stay process-local — the DCN-outer /
    # ICI-inner recipe of parallel/multihost.make_global_mesh.
    local = ndev // jax.process_count()
    spec = MeshSpec.for_devices(
        ndev,
        tp=2 if local % 2 == 0 else 1,
        sp=2 if local % 4 == 0 else 1,
    )
    mesh = multihost.make_global_mesh(spec, num_slices=max(env.num_slices, 1))

    cfg = LlamaConfig.tiny(n_layers=2, attn_impl="ring" if spec.sp > 1 else "xla")
    optimizer = make_optimizer(total_steps=max(steps, 2))
    with tr.span("init_state", component="trainer", ndev=ndev):
        state = init_train_state(jax.random.key(0), cfg, mesh, optimizer)
        # identical key on every process -> identical host batch, which
        # device_put may assert when shards live on non-addressable devices
        batch = synthetic_batch(
            jax.random.key(1), cfg, batch_size=batch_size, seq_len=seq_len,
            mesh=mesh,
        )
        train_step = make_train_step(cfg, mesh, optimizer)

    losses: list[float] = []
    grad_norms: list[float] = []
    for i in range(steps):
        # each sharded step includes the cross-process gradient psum: the
        # span IS the collective-inclusive step wall time for this rank
        with tr.span("sharded_step", component="trainer", step=i):
            state, metrics = train_step(state, batch)
            losses.append(float(metrics["loss"]))
            grad_norms.append(float(metrics["grad_norm"]))
    if not all(jnp.isfinite(jnp.asarray(losses))):
        raise RuntimeError(f"non-finite losses across steps: {losses}")

    return {
        "rank": jax.process_index(),
        "nprocs": jax.process_count(),
        "ndev": ndev,
        "mesh": {k: int(v) for k, v in mesh.shape.items()},
        "losses": [round(v, 6) for v in losses],
        "grad_norms": [round(v, 6) for v in grad_norms],
        "distributed": True,
        "ok": True,
    }


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--port", type=int, default=None)
    parser.add_argument("--init-timeout", type=int, default=60)
    parser.add_argument("--steps", type=int, default=2)
    parser.add_argument("--batch", type=int, default=4)
    parser.add_argument("--seq", type=int, default=32)
    args = parser.parse_args(argv)
    try:
        report = run_step_check(
            port=args.port, init_timeout=args.init_timeout,
            steps=args.steps, batch_size=args.batch, seq_len=args.seq,
        )
    except Exception as e:  # noqa: BLE001 - the contract is one JSON line
        print(json.dumps({"ok": False, "error": f"{type(e).__name__}: {e}"}))
        return 1
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
