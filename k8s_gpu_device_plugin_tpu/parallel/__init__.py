"""SPMD parallelism layer: mesh conventions, shardings, sequence parallelism.

The reference daemon contains no parallelism (SURVEY §2: no DP/TP/PP/SP/EP,
no NCCL/MPI) — its contribution to parallel jobs is handing out contiguous
ICI sub-slices. This package is the workload half the north star requires:
jax.sharding meshes whose collectives ride the ICI slices the plugin
allocates, ring attention + Ulysses all-to-all for long-context sequence
parallelism, and the sharding rules for the benchmark models.
"""

from k8s_gpu_device_plugin_tpu.parallel.mesh import (
    AXIS_DP,
    AXIS_EP,
    AXIS_FSDP,
    AXIS_PP,
    AXIS_SP,
    AXIS_TP,
    MeshSpec,
    batch_spec,
    make_mesh,
)
from k8s_gpu_device_plugin_tpu.parallel.pipeline import (
    pipeline_blocks,
    stack_for_stages,
    unstack_stages,
)
from k8s_gpu_device_plugin_tpu.parallel.ring_attention import ring_attention
from k8s_gpu_device_plugin_tpu.parallel.ulysses import ulysses_attention

__all__ = [
    "AXIS_DP",
    "AXIS_FSDP",
    "AXIS_TP",
    "AXIS_SP",
    "AXIS_EP",
    "AXIS_PP",
    "MeshSpec",
    "make_mesh",
    "batch_spec",
    "pipeline_blocks",
    "stack_for_stages",
    "unstack_stages",
    "ring_attention",
    "ulysses_attention",
]
