"""ctypes binding to the native data loader (native/dataload.cc).

``NativeMemmapSource`` is a drop-in for ``pipeline.MemmapSource``: the
deterministic window sampling (numpy RNG keyed by (seed, step)) stays in
Python — ONE recipe, so the two sources are bit-identical — while the
gather itself (page faults + uint16/32 -> int32 widening for B windows)
runs in the C++ worker pool. On a cold TB-scale corpus the Python
memmap loop faults pages serially on the main thread; the native gather
overlaps faults across threads and returns one contiguous int32 array.

Falls back loudly: constructing without the built library raises (run
``make -C k8s_gpu_device_plugin_tpu/native``), it never silently
degrades to the Python path — callers choose their source explicitly.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

_LIB_DIRS = (
    os.path.join(os.path.dirname(__file__), "..", "native", "build"),
    os.path.join(os.path.dirname(__file__), "..", "native"),
    "/usr/local/lib",
)


def _load_library() -> ctypes.CDLL | None:
    for d in _LIB_DIRS:
        path = os.path.join(d, "libdataload.so")
        if os.path.exists(path):
            try:
                lib = ctypes.CDLL(path)
            except OSError:
                continue
            lib.dataload_open.restype = ctypes.c_void_p
            lib.dataload_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
            lib.dataload_len.restype = ctypes.c_int64
            lib.dataload_len.argtypes = [ctypes.c_void_p]
            lib.dataload_gather.restype = ctypes.c_int32
            lib.dataload_gather.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int32,
                ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int32,
            ]
            lib.dataload_close.restype = None
            lib.dataload_close.argtypes = [ctypes.c_void_p]
            return lib
    return None


_DTYPE_CODES = {"uint16": 2, "uint32": 4}


class NativeMemmapSource:
    """pipeline.TokenSource over the C++ gather (see module docstring)."""

    def __init__(self, path: str, dtype: str = "uint16", seed: int = 0,
                 threads: int = 0) -> None:
        if dtype not in _DTYPE_CODES:
            raise ValueError(f"unsupported dtype {dtype!r} (uint16/uint32)")
        self._lib = _load_library()
        if self._lib is None:
            raise RuntimeError(
                "libdataload.so not built; run "
                "`make -C k8s_gpu_device_plugin_tpu/native`"
            )
        self._handle = self._lib.dataload_open(
            path.encode(), _DTYPE_CODES[dtype]
        )
        if not self._handle:
            raise FileNotFoundError(f"cannot open token file {path}")
        self.n_tokens = int(self._lib.dataload_len(self._handle))
        if self.n_tokens < 2:
            self.close()
            raise ValueError(f"token file {path} too small ({self.n_tokens})")
        self.seed = seed
        self.threads = threads

    def windows(self, step, rows, batch_rows, seq_len):
        n = self.n_tokens - (seq_len + 1)
        if n < 1:
            raise ValueError(
                f"corpus of {self.n_tokens} tokens shorter than seq "
                f"{seq_len}+1"
            )
        # SAME sampling recipe as pipeline.MemmapSource — bit-identical
        # batches, so swapping sources never changes a training run
        rng = np.random.default_rng((self.seed, step))
        starts = np.ascontiguousarray(
            rng.integers(0, n + 1, size=batch_rows)[rows], dtype=np.int64
        )
        out = np.empty((len(starts), seq_len + 1), dtype=np.int32)
        got = self._lib.dataload_gather(
            self._handle,
            starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(starts),
            seq_len + 1,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            self.threads,
        )
        if got != len(starts):
            raise RuntimeError(
                f"native gather failed ({got}/{len(starts)} rows)"
            )
        return out

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.dataload_close(self._handle)
            self._handle = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass


def native_available() -> bool:
    """True when libdataload.so is built and loadable — the factory
    (pipeline.make_token_source) gate for defaulting corpus reads onto
    the C++ gather."""
    return _load_library() is not None
