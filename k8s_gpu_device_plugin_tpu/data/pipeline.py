"""Token data pipeline with host→device prefetch.

The reference daemon has no data path at all; this feeds the benchmark
training workloads (BASELINE configs #4/#5). TPU-first requirements it
satisfies:

- **Static shapes**: every batch is exactly (batch, seq+1) int32 — no
  ragged tails (the last partial window of an epoch is dropped), so the
  jitted train step never recompiles.
- **Prefetch**: a background thread assembles and device-puts the next
  batches while the current step runs, overlapping host IO with TPU compute
  (the HBM-bandwidth rule: never let the MXU wait on the host).
- **Multi-process**: under jax.distributed each process materializes only
  its own rows and the global array is assembled with
  ``jax.make_array_from_process_local_data`` — no cross-host token traffic.
- **Deterministic + resumable**: batch content is a pure function of
  (seed, step), so ``state()``/``seek()`` give exact resume after a
  checkpoint restore with no iterator pickling.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Protocol

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from k8s_gpu_device_plugin_tpu.parallel.mesh import AXIS_DP, AXIS_FSDP, AXIS_SP
from jax.sharding import PartitionSpec as P


class TokenSource(Protocol):
    """Pure window server: (step, rows, seq_len) -> (rows, seq_len+1) int32.

    Implementations must be deterministic in ``step`` — resume correctness
    (and multi-process row disjointness) depends on it.
    """

    def windows(self, step: int, rows: slice, batch_rows: int, seq_len: int) -> np.ndarray: ...


class SyntheticSource:
    """Deterministic random tokens (benchmark default; zero IO)."""

    def __init__(self, vocab_size: int, seed: int = 0) -> None:
        self.vocab_size = vocab_size
        self.seed = seed

    def windows(self, step, rows, batch_rows, seq_len):
        rng = np.random.default_rng((self.seed, step))
        full = rng.integers(
            0, self.vocab_size, (batch_rows, seq_len + 1), dtype=np.int32
        )
        return full[rows]


class MemmapSource:
    """Flat binary token file (np.memmap) served as shuffled windows.

    The file is one continuous token stream (the common packed-corpus
    format, e.g. uint16/uint32 little-endian). Windows are drawn at
    pseudo-random offsets keyed by (seed, step) — deterministic, collision
    -tolerant sampling rather than an epoch shuffle table, which keeps
    startup O(1) for terabyte corpora.
    """

    def __init__(self, path: str, dtype: str = "uint16", seed: int = 0) -> None:
        self.tokens = np.memmap(path, dtype=np.dtype(dtype), mode="r")
        self.seed = seed
        if len(self.tokens) < 2:
            raise ValueError(f"token file {path} too small ({len(self.tokens)})")

    def windows(self, step, rows, batch_rows, seq_len):
        n = len(self.tokens) - (seq_len + 1)
        if n < 1:
            raise ValueError(
                f"corpus of {len(self.tokens)} tokens shorter than seq {seq_len}+1"
            )
        rng = np.random.default_rng((self.seed, step))
        starts = rng.integers(0, n + 1, size=batch_rows)[rows]
        return np.stack(
            [self.tokens[s : s + seq_len + 1] for s in starts]
        ).astype(np.int32)


class DataLoader:
    """Sharded, prefetching batch iterator.

    Yields ``{"inputs": (B,S), "targets": (B,S)}`` jax Arrays laid out
    batch-over-(dp,fsdp), sequence-over-sp on ``mesh`` — the shardings
    models/train.py expects. ``B`` is the GLOBAL batch; each process holds
    only its rows.
    """

    def __init__(
        self,
        source: TokenSource,
        batch_size: int,
        seq_len: int,
        mesh: Mesh,
        start_step: int = 0,
        prefetch: int = 2,
    ) -> None:
        n_proc = jax.process_count()
        if batch_size % n_proc != 0:
            raise ValueError(
                f"global batch {batch_size} not divisible by {n_proc} processes"
            )
        self.source = source
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.mesh = mesh
        self._step = start_step
        self._prefetch = max(prefetch, 0)
        per = batch_size // n_proc
        self._rows = slice(jax.process_index() * per, (jax.process_index() + 1) * per)
        self._sharding = NamedSharding(mesh, P((AXIS_DP, AXIS_FSDP), AXIS_SP))

    # --- resumability ---

    def state(self) -> dict:
        """Checkpointable iterator position (pair with models/checkpoint.py)."""
        return {"step": self._step}

    def seek(self, step: int) -> None:
        self._step = step

    # --- batch production ---

    def _make_batch(self, step: int) -> dict:
        local = self.source.windows(
            step, self._rows, self.batch_size, self.seq_len
        )
        inputs, targets = local[:, :-1], local[:, 1:]
        if jax.process_count() > 1:
            make = lambda x: jax.make_array_from_process_local_data(  # noqa: E731
                self._sharding, x
            )
        else:
            make = lambda x: jax.device_put(x, self._sharding)  # noqa: E731
        return {"inputs": make(inputs), "targets": make(targets)}

    def __iter__(self) -> Iterator[dict]:
        if self._prefetch == 0:
            while True:
                batch = self._make_batch(self._step)
                self._step += 1
                yield batch
        else:
            yield from self._prefetch_iter()

    def _prefetch_iter(self) -> Iterator[dict]:
        """Background producer thread, bounded queue (double buffering)."""
        q: queue.Queue = queue.Queue(maxsize=self._prefetch)
        stop = threading.Event()

        def produce(start: int) -> None:
            step = start
            try:
                while not stop.is_set():
                    q.put(
                        ("ok", step, self._make_batch(step)),
                    )
                    step += 1
            except Exception as e:  # noqa: BLE001 - surface on the consumer side
                q.put(("err", step, e))

        t = threading.Thread(
            target=produce, args=(self._step,), daemon=True, name="data-prefetch"
        )
        t.start()
        try:
            while True:
                kind, step, payload = q.get()
                if kind == "err":
                    raise payload
                self._step = step + 1
                yield payload
        finally:
            stop.set()
            # unblock a producer waiting on a full queue
            while not q.empty():
                try:
                    q.get_nowait()
                except queue.Empty:
                    break


def make_token_source(
    path: str,
    vocab_size: int,
    dtype: str = "uint16",
    seed: int = 0,
) -> tuple[TokenSource, str]:
    """The default source factory: ``(source, label)``.

    - no ``path``: deterministic synthetic tokens (benchmarks, smoke runs)
    - ``path`` + built ``libdataload.so``: the native C++ gather
      (data/native_loader.py) — the production default, threads overlap
      the page faults a cold memmap serializes
    - ``path`` without the library: the Python memmap source

    The two file-backed sources share one sampling recipe keyed by
    (seed, step), so which one served a run never changes its batches
    (bit-identity pinned in tests/test_data_trainer.py). The label is for
    run logs/artifacts: an IO-bound run should say which gather fed it.

    A probe window is vocab-checked up front: out-of-vocab corpus ids
    (wrong ``dtype``, a corpus tokenized for a bigger vocab) would
    otherwise train silently wrong — JAX's out-of-bounds gather CLAMPS,
    so the embedding lookup never errors. A spot check, not a full scan;
    it reliably catches dtype garbage and grossly mismatched vocabs.
    """
    if not path:
        return SyntheticSource(vocab_size, seed=seed), "synthetic"
    from k8s_gpu_device_plugin_tpu.data.native_loader import (
        NativeMemmapSource,
        native_available,
    )

    if native_available():
        source: TokenSource = NativeMemmapSource(path, dtype=dtype, seed=seed)
        label = "native-memmap"
    else:
        source, label = MemmapSource(path, dtype=dtype, seed=seed), "python-memmap"
    try:
        probe = source.windows(0, slice(0, 2), 2, 127)
        if int(probe.max()) >= vocab_size:
            raise ValueError(
                f"corpus {path} contains token id {int(probe.max())} >= "
                f"vocab_size {vocab_size} (wrong --dataDtype, or a corpus "
                "tokenized for a larger vocabulary) — the embedding gather "
                "would clamp it and train on garbage"
            )
    except BaseException:
        # don't leak the native handle/mmap on a refused corpus
        if hasattr(source, "close"):
            source.close()
        raise
    return source, label
