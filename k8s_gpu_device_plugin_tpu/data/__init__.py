from k8s_gpu_device_plugin_tpu.data.pipeline import (
    DataLoader,
    MemmapSource,
    SyntheticSource,
    TokenSource,
)

__all__ = ["DataLoader", "MemmapSource", "SyntheticSource", "TokenSource"]
