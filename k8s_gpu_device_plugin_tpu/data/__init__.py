from k8s_gpu_device_plugin_tpu.data.pipeline import (
    DataLoader,
    MemmapSource,
    SyntheticSource,
    TokenSource,
    make_token_source,
)

__all__ = [
    "DataLoader",
    "MemmapSource",
    "SyntheticSource",
    "TokenSource",
    "make_token_source",
]
