"""Engine supervisor: in-replica crash recovery for the serving engine.

Before this module, a single engine-thread exception was terminal: the
loop logged, set ``_dead``, and pushed a bare end-of-stream ``None`` to
every client queue — the replica stayed dead until a process restart,
and a truncated stream was indistinguishable from a clean finish
(serving/server.py's old dead path). PR 11's router routes *around*
dead replicas; this is the tier that recovers *inside* one.

Recovery reuses the machinery the stack already trusts:

- **Capture.** The crashed batcher's host-side ledgers are intact (the
  engine thread is their sole owner, and it is the thread running this
  code): queued submissions still sit in the engine's submit queue,
  and every live request is a ``_Request`` in ``pending`` /
  ``prefilling`` / ``running``. Committed-but-unpublished tokens are
  pushed to their streams first — device work lost in flight was never
  in ``req.out``, so nothing can double-emit.
- **Rebuild.** A fresh batcher from the engine's own construction
  recipe: new device state, new page pool, the SAME metrics /
  scheduler / attribution / MFU objects (their ledgers are
  engine-owned and survive). The prefix cache re-attaches as-is on the
  dense layout (entries are standalone rows); on the paged layout it
  is RESET — promoted entries hold page ids of the dead pool.
- **Resume.** Each surviving request rides the PR-7 preemption-resume
  fold: emitted tokens fold back into ``prompt`` as ``prefilled_out``,
  so the re-prefill recomputes their K/V and the finish chunk samples
  emission (and seeded draw) number ``prefilled_out`` — greedy and
  seeded streams through an induced mid-decode crash are pinned
  bit-identical to an uninterrupted run, and no token is ever
  re-emitted (tests/test_supervisor.py). Requests keep their rids
  (the new batcher's rid counter continues from the old one's), so
  the engine's rid->stream map needs no surgery and clients only see
  a latency blip.

Restarts are **budgeted**: ``max_restarts`` per rolling ``window_s``.
An exhausted budget degrades to the dead state — but streams then end
with a structured :class:`StreamError` frame on both HTTP surfaces
(native SSE error event / OpenAI ``server_error`` envelope), never a
silent clean EOS.

Thread model: every mutable ledger here is engine-thread-owned (the
``recover``/``on_crash`` callers run in the crashed loop's except
block); cross-thread readers — ``/v1/health``'s ``supervisor``
section — go through the :meth:`EngineSupervisor.stats` snapshot, the
same contract as ``kv_stats``/``sched_stats``.
"""

from __future__ import annotations

import time

from k8s_gpu_device_plugin_tpu.obs.trace import get_tracer
from k8s_gpu_device_plugin_tpu.utils.log import get_logger

log = get_logger()


class RollingBudget:
    """N events per rolling window — the ONE budget shape both recovery
    tiers share: the engine supervisor's restart budget (crashes inside
    one replica) and the router's fleet restart budget (replica deaths
    across the fleet, serving/router.py). ``max_events=0`` means the
    budget is always exhausted — the recovery-off switch at either tier.

    Single-writer like every ledger around it: the supervisor's lives on
    the engine thread, the router's on its event loop; neither is shared.
    """

    __slots__ = ("max_events", "window_s", "_times")

    def __init__(self, max_events: int, window_s: float):
        if max_events < 0:
            raise ValueError(
                f"max_events must be >= 0, got {max_events}"
            )
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self.max_events = int(max_events)
        self.window_s = float(window_s)
        self._times: list[float] = []

    def allow(self) -> bool:
        """True while the rolling budget has room (prunes the window)."""
        now = time.monotonic()
        self._times = [t for t in self._times if now - t < self.window_s]
        return len(self._times) < self.max_events

    def record(self) -> None:
        """Charge one event against the window."""
        self._times.append(time.monotonic())

    def used(self) -> int:
        now = time.monotonic()
        self._times = [t for t in self._times if now - t < self.window_s]
        return len(self._times)


class StreamError:
    """Terminal structured-error frame on a per-request stream queue.

    The stream protocol items are ``(token, logprob)`` tuples closed by
    ``None``; a stream that dies abnormally now carries one of these
    BEFORE the closing ``None``, so both HTTP planes can emit a real
    error (native SSE ``{"error": ...}`` event, OpenAI ``server_error``
    envelope, 503 on the non-streamed paths) instead of a silent
    truncation that reads exactly like a short completion.
    """

    __slots__ = ("code", "message")

    def __init__(self, code: str, message: str):
        self.code = code
        self.message = message

    def __repr__(self) -> str:  # readable in logs/test failures
        return f"StreamError(code={self.code!r}, message={self.message!r})"


class EngineSupervisor:
    """Restart policy + recovery mechanics for one InferenceEngine.

    ``max_restarts`` restarts are allowed per rolling ``window_s``
    seconds; ``max_restarts=0`` disables recovery outright (every crash
    degrades to the dead state — with the structured-error close, not
    the old silent one).
    """

    def __init__(self, max_restarts: int = 3, window_s: float = 300.0):
        self._budget = RollingBudget(max_restarts, window_s)  # owner: engine
        self.max_restarts = self._budget.max_events
        self.window_s = self._budget.window_s
        self._state = "ok"                     # owner: engine
        self._last_crash: dict | None = None   # owner: engine
        self._crashes_total = 0                # owner: engine
        self._restarts_total = 0               # owner: engine
        self._replayed_total = 0               # owner: engine
        self._resumed_total = 0                # owner: engine

    # --- policy (engine thread) ------------------------------------------

    def on_crash(self, exc: BaseException) -> None:
        """Record one engine-loop crash (restart or not)."""
        self._crashes_total += 1
        self._last_crash = {
            "t_wall": time.time(),
            "error": f"{type(exc).__name__}: {exc}",
        }
        tracer = get_tracer()
        if tracer.enabled:
            tracer.span(
                "engine_crash", component="serving_engine",
                error=f"{type(exc).__name__}: {exc}",
                crashes=self._crashes_total,
            ).end()

    def allow_restart(self) -> bool:
        """True while the rolling restart budget has room."""
        return self._budget.allow()

    def mark_dead(self) -> None:
        self._state = "dead"

    # --- recovery (engine thread, inside the crashed loop's except) ------

    @staticmethod
    def _live_requests(cb) -> list:
        return (
            list(cb.pending)
            + list(cb.prefilling.values())
            + list(cb.running.values())
        )

    @staticmethod
    def _fallback_publish(engine, old) -> None:
        """Defensive twin of ``engine._publish`` for when that raised
        against the torn batcher: push every live request's committed
        tokens, and CLOSE the streams of requests that retired between
        the last publish and the crash — those rids never reach the
        rebuilt batcher, so no later publish would ever end their
        streams (the handler would await forever). Per-request
        try/except: one bad entry must not strand the rest."""
        for req in EngineSupervisor._live_requests(old):
            try:
                engine._push(req.rid, req.out, req.out_logp)
            except Exception:  # noqa: BLE001
                log.exception("fallback push failed for rid=%s", req.rid)
        for rid, eid in list(engine._rid_to_eid.items()):
            req = old.done_requests.pop(rid, None)
            if req is None:
                continue
            try:
                engine._push(rid, req.out, req.out_logp)
            except Exception:  # noqa: BLE001
                log.exception("fallback push failed for rid=%s", rid)
            old.done.pop(rid, None)
            # mirror _publish's wrap-up record: a request that retired
            # REJECTED just before the crash must still surface as a
            # 429/rejected disposition, never as a clean zero-token
            # done (the silent-truncation shape this PR kills)
            info: dict = {"cached_tokens": req.cached_tokens}
            tl = getattr(req, "timeline", None)
            if tl is not None and getattr(tl, "record", None) is not None:
                info["timeline"] = tl.record
            if req.reject_reason is not None:
                info["reject_reason"] = req.reject_reason
                sched = getattr(old, "scheduler", None)
                info["retry_after"] = (
                    sched.retry_after_s() if sched is not None else 1
                )
            with engine._lock:
                stream = engine._streams.pop(eid, None)
                engine._published.pop(eid, None)
                engine._finished_info[eid] = info
            del engine._rid_to_eid[rid]
            if stream is not None:
                loop, q = stream
                loop.call_soon_threadsafe(q.put_nowait, None)

    def recover(self, engine) -> None:
        """Rebuild ``engine.cb`` in place and resume its work. Raises
        if the rebuild itself fails (the caller then degrades to the
        dead state)."""
        old = engine.cb
        # 1. deliver every committed token. A crash between the paired
        # out/out_logp appends can leave one list a token long; trim to
        # the committed pair so the publish below and the prompt fold
        # agree on what was emitted.
        for req in self._live_requests(old):
            n = min(len(req.out), len(req.out_logp))
            del req.out[n:]
            del req.out_logp[n:]
        try:
            # the normal publish also closes streams of requests that
            # retired between the last publish and the crash
            engine._publish()
        except Exception:  # noqa: BLE001 - torn batcher state
            log.exception("post-crash publish failed; pushing live "
                          "streams directly")
            self._fallback_publish(engine, old)
        survivors = sorted(self._live_requests(old), key=lambda r: r.rid)
        # 2. the prefix cache: paged entries hold page ids of the DEAD
        # pool — reset them (dense entries are standalone rows and
        # re-attach as-is; the batcher ctor would refuse stale paged
        # entries anyway, loudly)
        pc = getattr(old, "prefix_cache", None)
        if pc is not None and getattr(old, "pool", None) is not None:
            reset = getattr(pc, "reset", None)
            if reset is not None:
                reset()
        new = engine._make_batcher()
        # rids stay unique AND stable across the restart: survivors
        # keep theirs (the engine's rid->stream map needs no surgery)
        # and fresh admissions continue the old sequence
        new._next_rid = old._next_rid
        now = time.perf_counter()
        replayed = resumed = 0
        for req in survivors:
            was_admitted = req.slot >= 0
            # the preemption fold (_preempt_slot's exact recipe): the
            # resumed finish chunk samples emission — and seeded draw —
            # number prefilled_out, so the continued stream is
            # bit-identical and no token is re-emitted
            req.prompt = list(req.prompt) + [
                int(t) for t in req.out[req.prefilled_out:]
            ]
            req.prefilled_out = len(req.out)
            req.slot = -1
            req.matched = False
            req.prefix = None
            req._match_depth = None
            req._pinned_pages = None   # pins belonged to the dead pool
            req._new_pages = None
            req._draft_new_pages = None
            req.defer_counted = False
            if req.out:
                resumed += 1
            else:
                replayed += 1
            if was_admitted or req.out:
                # mid-stream survivor (decoding, prefilling, or parked
                # in pending by a preemption with tokens already out):
                # the flight recorder always retains these, and the
                # scheduler skips re-charging its (now output-inflated)
                # prompt
                req.restarts += 1
            if was_admitted:
                if req.timeline is not None:
                    # decode/prefill segment closes at the crash; a
                    # fresh queue_wait opens (the resumed admission
                    # closes it), keeping phase sums exact
                    req.timeline.advance("queue_wait", now)
            if req.decode_span is not None:
                req.decode_span.set(tokens=len(req.out)).end()
                req.decode_span = None
            new.pending.append(req)
        engine.cb = new
        self._budget.record()
        self._restarts_total += 1
        self._replayed_total += replayed
        self._resumed_total += resumed
        metrics = getattr(new, "metrics", None)
        if metrics is not None:
            on_restart = getattr(metrics, "on_engine_restart", None)
            if on_restart is not None:
                on_restart(replayed, resumed)
        tracer = get_tracer()
        if tracer.enabled:
            tracer.span(
                "engine_restart", component="serving_engine",
                restart=self._restarts_total, replayed=replayed,
                resumed=resumed,
            ).end()
        log.warning(
            "inference engine restarted after crash",
            extra={"fields": {
                "restarts_total": self._restarts_total,
                "replayed": replayed,
                "resumed": resumed,
                "last_crash": (self._last_crash or {}).get("error"),
            }},
        )

    # --- cross-thread snapshot -------------------------------------------

    def stats(self) -> dict:
        """The ``supervisor`` section of ``/v1/health`` (schema pinned
        in tests/test_health.py): plain copies under the same
        approximate-read contract as ``kv_stats``."""
        return {
            "state": self._state,
            "max_restarts": self.max_restarts,
            "window_s": self.window_s,
            "crashes_total": self._crashes_total,
            "restarts_total": self._restarts_total,
            "replayed_total": self._replayed_total,
            "resumed_total": self._resumed_total,
            "last_crash": (
                dict(self._last_crash) if self._last_crash else None
            ),
        }
