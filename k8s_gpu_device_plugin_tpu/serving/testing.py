"""In-process serving fleet (the integration seam for fleet tests/benches).

N real InferenceServers on ephemeral ports behind a real ReplicaRouter,
all on the caller's event loop — the harness tests/test_router.py, the
``make bench-router`` smoke and serve_bench's fleet A/B all drive. Lives
in the package (not tests/) for the same reason plugin/testing.py does:
the shipped CPU benches spin fleets too, and three hand-rolled copies of
the bring-up/teardown dance drifted apart the moment one grew a kwarg.

Usage::

    async with inprocess_fleet(params, cfg, n_replicas=2,
                               engine_kw=dict(n_slots=2, max_len=64),
                               router_kw=dict(policy="rr")) as fleet:
        await client.post(f"{fleet.base}/v1/generate", ...)
        await fleet.kill_replica(0)    # the crash path
        fleet.router.router_stats()

Per-replica state (a prefix cache, a scheduler — objects that must NOT
be shared between engines) comes from ``engine_factory(i)``.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import time

from k8s_gpu_device_plugin_tpu.serving.fleet import FleetRegistry
from k8s_gpu_device_plugin_tpu.serving.router import ReplicaRouter
from k8s_gpu_device_plugin_tpu.serving.server import (
    InferenceEngine,
    InferenceServer,
)


def per_replica_registry_factories(
    params, cfg, *, n_slots: int = 2, max_len: int = 64,
    chunked_prefill: int = 8,
):
    """``(engine_factory, server_factory)`` giving every replica its
    OWN prometheus ``CollectorRegistry`` (and the ServingMetrics bound
    to it): ``/fleet/metrics`` federation needs N independently
    scrapable replicas, and shared collector names would collide in one
    process. The one copy tests/test_fleet_obs.py and the
    ``make bench-fleet-obs`` smoke both drive their fleets through."""
    from k8s_gpu_device_plugin_tpu.metrics.serving_metrics import (
        ServingMetrics,
    )
    from prometheus_client import CollectorRegistry

    def engine_factory(i: int) -> InferenceEngine:
        return InferenceEngine(
            params, cfg, n_slots=n_slots, max_len=max_len,
            chunked_prefill=chunked_prefill,
            metrics=ServingMetrics(registry=CollectorRegistry()),
        )

    def server_factory(i: int, engine: InferenceEngine) -> InferenceServer:
        return InferenceServer(
            engine, host="127.0.0.1", port=0, replica_id=f"r{i}",
            registry=engine.cb.metrics._registry,
        )

    return engine_factory, server_factory


async def stream_generate(session, base: str, *, prompt, max_new: int,
                          logprobs: bool = True, seed=None) -> dict:
    """One streamed ``/v1/generate`` through ``base`` (a router or a
    replica), drained frame by frame the way the fleet tests/benches
    all do; returns ``{"tokens", "logprobs", "done", "error",
    "wall_s"}`` with the client-observed wall time (``error`` is the
    structured error frame's payload, or None)."""
    t0 = time.perf_counter()
    toks: list[int] = []
    logps: list[float] = []
    done = False
    error = None
    body = {"prompt": prompt, "max_new": max_new, "stream": True,
            "logprobs": logprobs}
    if seed is not None:
        body["seed"] = seed
    async with session.post(f"{base}/v1/generate", json=body) as r:
        assert r.status == 200, await r.text()
        async for line in r.content:
            text = line.decode().strip()
            if not text.startswith("data: "):
                continue
            evt = json.loads(text[len("data: "):])
            if "token" in evt:
                toks.append(int(evt["token"]))
                if "logprob" in evt:
                    logps.append(float(evt["logprob"]))
            if evt.get("done"):
                done = True
            if evt.get("error"):
                error = evt["error"]
    return {"tokens": toks, "logprobs": logps, "done": done,
            "error": error, "wall_s": time.perf_counter() - t0}


async def _wait_bound(obj, task) -> None:
    """Spin until ``obj.bound_port`` is set — or the serving task died,
    in which case re-raise ITS error instead of hanging forever."""
    while obj.bound_port is None:
        if task.done():
            exc = task.exception()
            raise exc if exc is not None else RuntimeError(
                "server task exited before binding a port"
            )
        await asyncio.sleep(0.01)


class InprocessFleet:
    """Handles for one running fleet (yielded by :func:`inprocess_fleet`)."""

    def __init__(self):
        self.servers: list[InferenceServer] = []
        self.stops: list[asyncio.Event] = []
        self.tasks: list[asyncio.Task] = []
        self.fleet: FleetRegistry | None = None
        self.router: ReplicaRouter | None = None
        self.base: str = ""          # the router's http://host:port

    def replica_base(self, i: int) -> str:
        """Direct (router-bypassing) address of replica ``i``."""
        return f"http://127.0.0.1:{self.servers[i].bound_port}"

    async def kill_replica(self, i: int) -> None:
        """Stop replica ``i`` abruptly (the crash path — no drain).

        Live connections are ABORTED first: a graceful aiohttp cleanup
        waits for in-flight handlers to finish, which is a drain, not a
        death — mid-stream relays must see the connection reset the way
        they would when the process vanishes (what the router's resume
        path recovers from)."""
        runner = getattr(self.servers[i], "_runner", None)
        server = getattr(runner, "server", None)
        if server is not None:
            for proto in list(getattr(server, "connections", ())):
                transport = getattr(proto, "transport", None)
                if transport is not None:
                    transport.abort()
        self.stops[i].set()
        await asyncio.wait_for(self.tasks[i], 30)


@contextlib.asynccontextmanager
async def inprocess_fleet(
    params,
    cfg,
    n_replicas: int = 2,
    engine_kw: dict | None = None,
    engine_factory=None,   # (i) -> InferenceEngine; overrides engine_kw
    router_kw: dict | None = None,
    server_kw: dict | None = None,   # extra InferenceServer kwargs
    server_factory=None,   # (i, engine) -> InferenceServer; overrides
    # server_kw. Keep host="127.0.0.1", port=0, replica_id=f"r{i}" (the
    # registry below keys on those) — the hook exists for per-replica
    # state the shared kwargs cannot express, e.g. one prometheus
    # CollectorRegistry PER replica so /fleet/metrics federation is
    # testable in one process without collector-name collisions
):
    ctx = InprocessFleet()
    rstop = asyncio.Event()
    rtask = None
    try:
        for i in range(n_replicas):
            if engine_factory is not None:
                engine = engine_factory(i)
            else:
                engine = InferenceEngine(params, cfg, **(engine_kw or {}))
            if server_factory is not None:
                server = server_factory(i, engine)
            else:
                server = InferenceServer(
                    engine, host="127.0.0.1", port=0, replica_id=f"r{i}",
                    **(server_kw or {}),
                )
            stop = asyncio.Event()
            task = asyncio.create_task(server.run(stop))
            ctx.stops.append(stop)
            ctx.tasks.append(task)
            await _wait_bound(server, task)
            ctx.servers.append(server)
        ctx.fleet = FleetRegistry.from_spec(",".join(
            f"r{i}={ctx.replica_base(i)}" for i in range(n_replicas)
        ))
        ctx.router = ReplicaRouter(
            ctx.fleet, host="127.0.0.1", port=0, **(router_kw or {})
        )
        rtask = asyncio.create_task(ctx.router.run(rstop))
        await _wait_bound(ctx.router, rtask)
        ctx.base = f"http://127.0.0.1:{ctx.router.bound_port}"
        yield ctx
    finally:
        if rtask is not None and not rtask.done():
            rstop.set()
            await asyncio.wait_for(rtask, 30)
        for stop in ctx.stops:
            stop.set()
        for task in ctx.tasks:
            if not task.done():
                await asyncio.wait_for(task, 30)
