"""In-pod inference service: the continuous batcher behind an HTTP API."""
