"""Prompt scoring for the serving pod: teacher-forced token logprobs.

The OpenAI completions contract eval harnesses rely on (lm-eval's
``loglikelihood``): ``echo=true, max_tokens=0, logprobs=N`` returns the
PROMPT's own per-token logprobs — one teacher-forced forward, no
sampling. The decode engine can't serve this (its prefill keeps only the
next-token logits); this is the training-path forward scored at every
position.

TPU shape discipline mirrors serving/embeddings.py: inputs pad to the
prompt buckets so the jitted forward compiles once per bucket, padding
is masked out, and every bucket is compiled at construction — BEFORE the
engine thread exists — so aiohttp executor threads only dispatch cached
executables (concurrent XLA:CPU compilation segfaults intermittently in
this jaxlib build; see tests/conftest.py).

Unsupported with weight-only quantized serving for the same reason as
embeddings: the quantized leaves are decode-path, the scoring forward is
the training-path matmul. The CLI gates this at startup.

No reference analogue: the reference is a device-plugin daemon; scoring
belongs to the workload stack this framework adds.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, forward
from k8s_gpu_device_plugin_tpu.serving.bucketed import BucketedForward


@partial(jax.jit, static_argnames=("cfg",))
def _score_one(params, tokens, length, cfg: LlamaConfig):
    """(P,) padded ids + real length -> (P,) f32 logprob of each token
    given its prefix; position 0 and padding positions read 0.0 (callers
    mask them — position 0 has no context to be scored under)."""
    logits = forward(params, tokens[None, :], cfg)  # (1, P, V) f32
    logprobs = jax.nn.log_softmax(logits[0], axis=-1)  # (P, V)
    # token t's score lives at the logits of its PREDECESSOR position
    scores = jnp.take_along_axis(
        logprobs[:-1], tokens[1:, None], axis=-1
    )[:, 0]  # (P-1,)
    scores = jnp.concatenate([jnp.zeros((1,), scores.dtype), scores])
    mask = jnp.arange(tokens.shape[0]) < length
    return jnp.where(mask, scores, 0.0)


class Scorer(BucketedForward):
    """Bucketed, thread-safe prompt scorer over the serving params
    (bucket/warmup/lock discipline shared with Embedder via
    serving/bucketed.py)."""

    def __init__(self, params, cfg: LlamaConfig,
                 buckets: tuple[int, ...] = (32, 64, 128, 256, 512, 1024),
                 warmup: bool = True):
        super().__init__(_score_one, params, cfg, buckets,
                         kind="scoring", warmup=warmup)

    def score(self, ids: list[int]) -> list[float | None]:
        """Per-token logprobs for ``ids``; index 0 is None (no context)."""
        out = np.asarray(self.dispatch(ids), np.float32)
        return [None] + [float(v) for v in out[1:len(ids)]]
