"""Prompt scoring for the serving pod: teacher-forced token logprobs.

The OpenAI completions contract eval harnesses rely on (lm-eval's
``loglikelihood``): ``echo=true, max_tokens=0, logprobs=N`` returns the
PROMPT's own per-token logprobs — one teacher-forced forward, no
sampling. The decode engine can't serve this (its prefill keeps only the
next-token logits); this is the training-path forward scored at every
position.

TPU shape discipline mirrors serving/embeddings.py: inputs pad to the
prompt buckets so the jitted forward compiles once per bucket, padding
is masked out, and every bucket is compiled at construction — BEFORE the
engine thread exists — so aiohttp executor threads only dispatch cached
executables (concurrent XLA:CPU compilation segfaults intermittently in
this jaxlib build; see tests/conftest.py).

Unsupported with weight-only quantized serving for the same reason as
embeddings: the quantized leaves are decode-path, the scoring forward is
the training-path matmul. The CLI gates this at startup.

No reference analogue: the reference is a device-plugin daemon; scoring
belongs to the workload stack this framework adds.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, forward
from k8s_gpu_device_plugin_tpu.serving.bucketed import BucketedForward


TOP_K = 5  # OpenAI caps completions logprobs at 5 alternatives


@partial(jax.jit, static_argnames=("cfg",))
def _score_one(params, tokens, length, cfg: LlamaConfig):
    """(P,) padded ids + real length -> per-token scoring triple.

    Returns (scores (P,), top_lps (P, TOP_K), top_ids (P, TOP_K)):
    token t's logprob given its prefix, plus the TOP_K most likely
    alternatives AT t's position (what the model would have preferred —
    the lm-eval ``is_greedy`` signal is top_ids[t, 0] == tokens[t]).
    Position 0 and padding read 0.0/0 (callers mask them — position 0
    has no context to be scored under). Always computing TOP_K keeps the
    compiled shape independent of the per-request logprobs value, so
    warmup's cache covers every request (single-compiler discipline)."""
    logits = forward(params, tokens[None, :], cfg)  # (1, P, V) f32
    logprobs = jax.nn.log_softmax(logits[0], axis=-1)  # (P, V)
    # token t's score lives at the logits of its PREDECESSOR position
    scores = jnp.take_along_axis(
        logprobs[:-1], tokens[1:, None], axis=-1
    )[:, 0]  # (P-1,)
    scores = jnp.concatenate([jnp.zeros((1,), scores.dtype), scores])
    top_lps, top_ids = jax.lax.top_k(logprobs[:-1], TOP_K)  # (P-1, K)
    pad_lp = jnp.zeros((1, TOP_K), top_lps.dtype)
    pad_id = jnp.zeros((1, TOP_K), top_ids.dtype)
    top_lps = jnp.concatenate([pad_lp, top_lps])
    top_ids = jnp.concatenate([pad_id, top_ids])
    mask = jnp.arange(tokens.shape[0]) < length
    return (
        jnp.where(mask, scores, 0.0),
        jnp.where(mask[:, None], top_lps, 0.0),
        jnp.where(mask[:, None], top_ids, 0),
    )


@partial(jax.jit, static_argnames=("cfg",), donate_argnames=("cache",))
def _score_chunk(params, tokens, targets, cache, length, cfg: LlamaConfig):
    """One chunk of the long-prompt path: run C tokens at absolute
    position ``length`` through the cached forward (full per-position
    logits), score ``targets`` (the chunk shifted by one — the last
    position's target is the NEXT chunk's first token), return
    (scores (C,), top_lps (C, K), top_ids (C, K), new cache). Entry i
    here scores the token at absolute position length + i + 1."""
    from k8s_gpu_device_plugin_tpu.models.generate import _forward_cached

    logits, cache = _forward_cached(params, tokens, cache, length, cfg)
    logprobs = jax.nn.log_softmax(logits[0].astype(jnp.float32), axis=-1)
    scores = jnp.take_along_axis(logprobs, targets[0][:, None], axis=-1)[:, 0]
    top_lps, top_ids = jax.lax.top_k(logprobs, TOP_K)
    return scores, top_lps, top_ids, cache


class Scorer(BucketedForward):
    """Bucketed, thread-safe prompt scorer over the serving params
    (bucket/warmup/lock discipline shared with Embedder via
    serving/bucketed.py).

    Prompts up to ``buckets[-1]`` take the single-forward path; longer
    ones (to ``max_len``) run the CHUNKED path — fixed-size chunks
    through the KV-cached forward, one compile total (static chunk and
    cache shapes), teacher-forced across chunk boundaries. Both compiled
    at construction, so executor threads never compile."""

    def __init__(self, params, cfg: LlamaConfig,
                 buckets: tuple[int, ...] = (32, 64, 128, 256, 512, 1024),
                 max_len: int = 4096, chunk: int = 512,
                 warmup: bool = True):
        # a cap below the largest bucket trims the buckets instead of
        # being silently raised — "longest scorable prompt" means it
        buckets = tuple(b for b in sorted(buckets) if b <= max_len)
        if not buckets:
            raise ValueError(
                f"max_len {max_len} is below the smallest scoring bucket"
            )
        self.max_len = max_len
        self.chunk = chunk
        super().__init__(_score_one, params, cfg, buckets,
                         kind="scoring", warmup=warmup)

    def warmup(self) -> None:
        super().warmup()
        if self.max_len > self.buckets[-1]:
            from k8s_gpu_device_plugin_tpu.models.generate import KVCache

            z = jnp.zeros((1, self.chunk), jnp.int32)
            cache = KVCache.init(self.cfg, 1, self._cache_len())
            jax.block_until_ready(_score_chunk(
                self.params, z, z, cache, jnp.int32(0), self.cfg
            ))

    def _cache_len(self) -> int:
        # one static cache shape -> one chunk compile, shared by every
        # long prompt regardless of its length
        return -(-self.max_len // self.chunk) * self.chunk

    def score(self, ids: list[int]) -> list[float | None]:
        """Per-token logprobs for ``ids``; index 0 is None (no context)."""
        return self.score_full(ids)[0]

    def score_full(
        self, ids: list[int]
    ) -> tuple[list[float | None], np.ndarray, np.ndarray]:
        """(per-token logprobs, top-K alternative logprobs (n, K),
        top-K alternative ids (n, K)); row 0 of the top arrays is
        meaningless (no context) — callers emit null there."""
        n = len(ids)
        if n > self.buckets[-1]:
            return self._score_long(ids)
        scores, top_lps, top_ids = self.dispatch(ids)
        lps = [None] + [
            float(v) for v in np.asarray(scores, np.float32)[1:n]
        ]
        return (
            lps,
            np.asarray(top_lps, np.float32)[:n],
            np.asarray(top_ids, np.int32)[:n],
        )

    def _score_long(self, ids: list[int]):
        from k8s_gpu_device_plugin_tpu.models.generate import KVCache

        n = len(ids)
        if n > self.max_len:
            raise ValueError(
                f"input of {n} tokens exceeds the {self.kind} cap "
                f"{self.max_len}"
            )
        C = self.chunk
        n_chunks = -(-n // C)
        padded = list(ids) + [0] * (n_chunks * C - n)
        # targets are the sequence shifted one left: entry i of chunk c
        # scores absolute position c*C + i + 1
        shifted = padded[1:] + [0]
        scores = np.zeros((n_chunks * C,), np.float32)
        top_lps = np.zeros((n_chunks * C, TOP_K), np.float32)
        top_ids = np.zeros((n_chunks * C, TOP_K), np.int32)
        with self._lock:
            cache = KVCache.init(self.cfg, 1, self._cache_len())
            for c in range(n_chunks):
                toks = jnp.asarray([padded[c * C:(c + 1) * C]], jnp.int32)
                tgts = jnp.asarray([shifted[c * C:(c + 1) * C]], jnp.int32)
                s, tl, ti, cache = _score_chunk(
                    self.params, toks, tgts, cache, jnp.int32(c * C),
                    self.cfg,
                )
                scores[c * C:(c + 1) * C] = np.asarray(s, np.float32)
                top_lps[c * C:(c + 1) * C] = np.asarray(tl, np.float32)
                top_ids[c * C:(c + 1) * C] = np.asarray(ti, np.int32)
        # entry i of `scores` holds the score OF token i+1; re-index to
        # the score_full convention (row i = token i; row 0 = no context)
        lps = [None] + [float(v) for v in scores[:n - 1]]
        out_lps = np.zeros((n, TOP_K), np.float32)
        out_ids = np.zeros((n, TOP_K), np.int32)
        out_lps[1:] = top_lps[:n - 1]
        out_ids[1:] = top_ids[:n - 1]
        return lps, out_lps, out_ids
