"""Prompt scoring for the serving pod: teacher-forced token logprobs.

The OpenAI completions contract eval harnesses rely on (lm-eval's
``loglikelihood``): ``echo=true, max_tokens=0, logprobs=N`` returns the
PROMPT's own per-token logprobs — one teacher-forced forward, no
sampling. The decode engine can't serve this (its prefill keeps only the
next-token logits); this is the training-path forward scored at every
position.

TPU shape discipline mirrors serving/embeddings.py: inputs pad to the
prompt buckets so the jitted forward compiles once per bucket, padding
is masked out, and every bucket is compiled at construction — BEFORE the
engine thread exists — so aiohttp executor threads only dispatch cached
executables (concurrent XLA:CPU compilation segfaults intermittently in
this jaxlib build; see tests/conftest.py).

Unsupported with weight-only quantized serving for the same reason as
embeddings: the quantized leaves are decode-path, the scoring forward is
the training-path matmul. The CLI gates this at startup.

No reference analogue: the reference is a device-plugin daemon; scoring
belongs to the workload stack this framework adds.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, forward
from k8s_gpu_device_plugin_tpu.serving.bucketed import BucketedForward


TOP_K = 5  # OpenAI caps completions logprobs at 5 alternatives


@partial(jax.jit, static_argnames=("cfg",))
def _score_one(params, tokens, length, cfg: LlamaConfig):
    """(P,) padded ids + real length -> per-token scoring triple.

    Returns (scores (P,), top_lps (P, TOP_K), top_ids (P, TOP_K)):
    token t's logprob given its prefix, plus the TOP_K most likely
    alternatives AT t's position (what the model would have preferred —
    the lm-eval ``is_greedy`` signal is top_ids[t, 0] == tokens[t]).
    Position 0 and padding read 0.0/0 (callers mask them — position 0
    has no context to be scored under). Always computing TOP_K keeps the
    compiled shape independent of the per-request logprobs value, so
    warmup's cache covers every request (single-compiler discipline)."""
    logits = forward(params, tokens[None, :], cfg)  # (1, P, V) f32
    logprobs = jax.nn.log_softmax(logits[0], axis=-1)  # (P, V)
    # token t's score lives at the logits of its PREDECESSOR position
    scores = jnp.take_along_axis(
        logprobs[:-1], tokens[1:, None], axis=-1
    )[:, 0]  # (P-1,)
    scores = jnp.concatenate([jnp.zeros((1,), scores.dtype), scores])
    top_lps, top_ids = jax.lax.top_k(logprobs[:-1], TOP_K)  # (P-1, K)
    pad_lp = jnp.zeros((1, TOP_K), top_lps.dtype)
    pad_id = jnp.zeros((1, TOP_K), top_ids.dtype)
    top_lps = jnp.concatenate([pad_lp, top_lps])
    top_ids = jnp.concatenate([pad_id, top_ids])
    mask = jnp.arange(tokens.shape[0]) < length
    return (
        jnp.where(mask, scores, 0.0),
        jnp.where(mask[:, None], top_lps, 0.0),
        jnp.where(mask[:, None], top_ids, 0),
    )


class Scorer(BucketedForward):
    """Bucketed, thread-safe prompt scorer over the serving params
    (bucket/warmup/lock discipline shared with Embedder via
    serving/bucketed.py)."""

    def __init__(self, params, cfg: LlamaConfig,
                 buckets: tuple[int, ...] = (32, 64, 128, 256, 512, 1024),
                 warmup: bool = True):
        super().__init__(_score_one, params, cfg, buckets,
                         kind="scoring", warmup=warmup)

    def score(self, ids: list[int]) -> list[float | None]:
        """Per-token logprobs for ``ids``; index 0 is None (no context)."""
        return self.score_full(ids)[0]

    def score_full(
        self, ids: list[int]
    ) -> tuple[list[float | None], np.ndarray, np.ndarray]:
        """(per-token logprobs, top-K alternative logprobs (n, K),
        top-K alternative ids (n, K)); row 0 of the top arrays is
        meaningless (no context) — callers emit null there."""
        scores, top_lps, top_ids = self.dispatch(ids)
        n = len(ids)
        lps = [None] + [
            float(v) for v in np.asarray(scores, np.float32)[1:n]
        ]
        return (
            lps,
            np.asarray(top_lps, np.float32)[:n],
            np.asarray(top_ids, np.int32)[:n],
        )
