"""OpenAI-compatible façade over the inference server.

``/v1/generate`` (serving/server.py) is the native API; these routes make
the same engine a drop-in backend for the large ecosystem of OpenAI
clients (SDKs, gateways, eval harnesses) — request/response translation
only, no second serving path:

- ``POST /v1/completions``: ``prompt`` is a string (tokenizer required)
  or a token-id list (works on a token-ids-only server). ``n``,
  ``stream``, ``stop`` (string or list of strings), ``max_tokens``
  (default 16, as OpenAI's legacy endpoint), ``temperature``/``top_p``,
  ``logprobs`` (any non-null value incl. 0 returns sampled-token
  logprobs — the raw-distribution values the engine records; no top-k
  alternatives).
- ``POST /v1/chat/completions``: ``messages`` rendered through the HF
  tokenizer's own chat template when it has one, else a minimal generic
  template. ``max_tokens`` absent = the slot's remaining budget
  (OpenAI's chat endpoint has no 16-token default). Streams emit
  OpenAI-style role and content deltas.
- ``GET /v1/models``: the single model this pod serves.

OpenAI semantics honored beyond the envelope: a matched stop sequence is
NEVER part of the returned text (the native API keeps it, like EOS) —
non-streamed responses trim the matched suffix, and streams hold back
the last ``max(stop)`` tokens (a suffix match can span exactly that
many) until they can no longer complete a stop match. Sampling:
``temperature``/``top_p`` present builds a
per-request Sampler (the absent knob gets OpenAI's 1.0 default); neither
present runs the server's default sampler, so a speculative engine
(shared sampler) still serves knob-less requests instead of 422ing all.

Streaming text deltas use a prefix-stable decode: each chunk is the
newly-stabilized suffix of ``decode(all tokens so far)``, so multi-token
characters never stream as mojibake (a bare per-token decode would).

No reference analogue: the reference is a device-plugin daemon
(/root/reference/README.md:1-6); the serving surface is part of the
workload stack this framework adds on top.
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid

from aiohttp import web

from k8s_gpu_device_plugin_tpu.models.sampling import Sampler
from k8s_gpu_device_plugin_tpu.serving.supervisor import StreamError
from k8s_gpu_device_plugin_tpu.serving.tokenizer import (
    encode_stop_strings,
    trim_stop_suffix,
)

MODEL_ID = "tpu-serving"  # the base model's id ("model" absent = base)


class _ModelNotFound(Exception):
    """Unknown "model" value: OpenAI answers these with a 404, distinct
    from the 400 invalid_request_error family."""

    def __init__(self, model: str) -> None:
        super().__init__(
            f"The model {model!r} does not exist or is not served here"
        )


class _TextDiffer:
    """Incremental token->text streaming without mojibake: emit only the
    newly-stabilized text (multi-token UTF-8 sequences and subword merges
    stay buffered until complete).

    Windowed decode (the standard streaming-detokenizer shape): only the
    tokens since the last stable emission are re-decoded per push, so a
    long stream costs O(window) per token, not O(all tokens so far)."""

    def __init__(self, tok) -> None:
        self._tok = tok
        self._ids: list[int] = []
        self._prefix = 0  # window start: ids before this are fully emitted
        self._read = 0    # ids[_prefix:_read] produced the last stable text

    def push(self, token: int) -> str:
        self._ids.append(int(token))
        stable = self._tok.decode(self._ids[self._prefix:self._read])
        full = self._tok.decode(self._ids[self._prefix:])
        # a trailing replacement char means a partial multi-byte sequence:
        # hold it back — the next token may complete it
        if full.endswith("�") or len(full) <= len(stable) \
                or not full.startswith(stable):
            return ""
        self._prefix = self._read
        self._read = len(self._ids)
        return full[len(stable):]

    def flush(self) -> str:
        stable = self._tok.decode(self._ids[self._prefix:self._read])
        full = self._tok.decode(self._ids[self._prefix:])
        if full.startswith(stable):
            return full[len(stable):]
        return ""  # non-monotonic decode: everything already emitted best-effort


def _render_chat(tokenizer, messages: list[dict]) -> list[int]:
    """Messages -> prompt ids. An HF tokenizer with a chat template uses
    it (the model was trained on that format); anything else gets a
    minimal role-tagged template with a final assistant header."""
    for m in messages:
        if not isinstance(m, dict) or not isinstance(m.get("role"), str) \
                or not isinstance(m.get("content"), str):
            raise ValueError(
                "each message needs string 'role' and 'content' fields"
            )
    hf = getattr(tokenizer, "_tok", None)
    if hf is not None and getattr(hf, "chat_template", None):
        return list(hf.apply_chat_template(
            messages, add_generation_prompt=True, tokenize=True,
        ))
    text = "".join(
        f"<|{m['role']}|>\n{m['content']}\n" for m in messages
    ) + "<|assistant|>\n"
    return tokenizer.encode(text)


class _OpenAIRoutes:
    """Handlers bound to an InferenceServer (engine + tokenizer)."""

    def __init__(self, server) -> None:
        self._server = server

    # --- request parsing -------------------------------------------------

    def _prompt_ids(self, body: dict) -> list[int]:
        prompt = body.get("prompt")
        if isinstance(prompt, str) and prompt:
            if self._server.tokenizer is None:
                raise ValueError(
                    "string prompts need a tokenizer on this server; "
                    "send a token-id list"
                )
            return self._server.tokenizer.encode(prompt)
        if (
            isinstance(prompt, list) and prompt
            and all(type(t) is int for t in prompt)
        ):
            return _check_token_ids(
                prompt, self._server.engine.cb.cfg.vocab_size
            )
        raise ValueError(
            "prompt must be a non-empty string or list of token ids "
            "(batched prompt lists are not supported)"
        )

    def _common(self, body: dict, allow_zero_max_tokens: bool = False) -> dict:
        """Fields shared by both endpoints, validated. ``max_new`` is None
        when the request omitted max_tokens — each endpoint applies its
        own default (16 for legacy completions, the slot budget for
        chat). ``allow_zero_max_tokens`` admits max_tokens=0 for the
        echo prompt-scoring path, which generates nothing."""
        n = int(body.get("n", 1))
        if not (1 <= n <= 8):
            raise ValueError("n must be in [1, 8]")
        stream = bool(body.get("stream", False))
        if stream and n > 1:
            raise ValueError("streaming supports n=1 only")
        max_new = body.get("max_tokens")
        if max_new is not None:
            max_new = int(max_new)
            floor = 0 if allow_zero_max_tokens else 1
            if max_new < floor:
                raise ValueError(f"max_tokens must be >= {floor}")

        stop = body.get("stop")
        stop_lists: list[list[int]] = []
        if stop is not None:
            if isinstance(stop, str):
                stop = [stop]
            if isinstance(stop, list) and len(stop) > 4:
                raise ValueError("stop supports at most 4 sequences")
            stop_lists = encode_stop_strings(
                self._server.tokenizer, stop, field="stop"
            )

        sampler = None
        if "temperature" in body or "top_p" in body:
            sampler = Sampler(
                temperature=float(body.get("temperature", 1.0)),
                top_p=float(body.get("top_p", 1.0)),
            )
        from k8s_gpu_device_plugin_tpu.serving.server import _parse_logit_bias

        logit_bias = _parse_logit_bias(body.get("logit_bias"))
        from k8s_gpu_device_plugin_tpu.models.batching import (
            ContinuousBatcher,
        )

        # validate BEFORE the per-choice (seed+i) % 2^31 derivation —
        # the modulo would wrap an invalid seed into range silently
        seed = ContinuousBatcher.validate_seed(body.get("seed"))
        # SLO extension fields (serving/scheduler.py; OpenAI SDKs pass
        # them via extra_body): validated by the batcher's shared rule,
        # defaulted at the engine edge when absent
        ContinuousBatcher.validate_sched(
            body.get("tenant"), body.get("priority"),
            body.get("deadline_ms"),
        )
        # "model" routes: the base model's id (or absent) -> base; a
        # loaded LoRA adapter's name -> that adapter. Anything else is
        # OpenAI's model_not_found.
        model = str(body.get("model") or MODEL_ID)
        adapter = -1
        if model != MODEL_ID:
            try:
                adapter = self._server.resolve_adapter(model)
            except ValueError:
                raise _ModelNotFound(model) from None
        return {
            "n": n, "stream": stream, "max_new": max_new,
            "stop": stop_lists, "sampler": sampler,
            "model": model, "adapter": adapter, "logit_bias": logit_bias,
            "seed": seed,
            "tenant": body.get("tenant"),
            "priority": body.get("priority"),
            "deadline_ms": body.get("deadline_ms"),
            # opt-in per-request latency attribution on the response
            # envelope (obs/attribution.py; SDKs pass it via extra_body,
            # like the SLO fields above) — non-streamed responses only
            "timeline": bool(body.get("timeline", False)),
        }

    def _budget(self, c: dict, prompt: list[int], default: int | None) -> None:
        """Resolve an absent max_tokens: the endpoint's fixed default, or
        (chat) the slot's remaining token budget for this prompt."""
        if c["max_new"] is not None:
            return
        if default is not None:
            c["max_new"] = default
            return
        max_len = getattr(self._server.engine.cb, "max_len", 0)
        c["max_new"] = max(1, max_len - len(prompt))

    # --- engine plumbing -------------------------------------------------

    def _submit(self, prompt: list[int], c: dict) -> list[tuple[int, asyncio.Queue]]:
        # n>1 with a seed derives a per-choice seed (seed+i): the whole
        # response stays reproducible while the n samples stay distinct —
        # the same seed for every choice would return n identical copies.
        # best_of > n samples the extras; _respond ranks and keeps n.
        subs = []
        try:
            for i in range(c.get("best_of") or c["n"]):
                subs.append(self._server.engine.submit(
                    prompt, c["max_new"], stop=c["stop"],
                    sampler=c["sampler"],
                    adapter=c["adapter"], logit_bias=c["logit_bias"],
                    seed=(
                        None if c["seed"] is None
                        else (c["seed"] + i) % 2**31
                    ),
                    tenant=c["tenant"], priority=c["priority"],
                    deadline_ms=c["deadline_ms"],
                ))
        except Exception:
            for eid, _ in subs:  # a partially submitted n>1 burst
                self._server.engine.cancel(eid)
            raise
        return subs

    @staticmethod
    def _finish_reason(n_out: int, max_new: int) -> str:
        # the engine retires on EOS/stop/cancel or budget; budget is the
        # only case that fills it exactly (a stop match is trimmed before
        # this is consulted, so a trimmed answer always reads 'stop')
        return "length" if n_out >= max_new else "stop"

    def _decode(self, ids: list[int]) -> str:
        if self._server.tokenizer is None:
            return ""
        return self._server.tokenizer.decode(ids)

    # --- endpoints -------------------------------------------------------

    async def embeddings(self, request: web.Request) -> web.Response:
        """OpenAI /v1/embeddings: input is a string, a list of strings,
        a token-id list, or a list of token-id lists. Embeddings serve
        the BASE model (adapter deltas aren't threaded through the
        hidden-state forward), so only the base model id routes."""
        embedder = getattr(self._server, "embedder", None)
        if embedder is None:
            return _oai_error(
                "embeddings are not enabled on this server", 400
            )
        try:
            body = await request.json()
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
            model = str(body.get("model") or MODEL_ID)
            if model != MODEL_ID:
                raise _ModelNotFound(model)
            raw = body.get("input")
            inputs = self._embedding_inputs(raw)
            if len(inputs) > 64:
                # one forward per item, sequential: an unbounded list
                # would monopolize the chip (the n<=8 analogue here)
                raise ValueError(
                    f"at most 64 inputs per request (got {len(inputs)})"
                )
            cap = embedder.buckets[-1]
            for i, ids in enumerate(inputs):
                # reject the whole request BEFORE burning forwards on
                # the items preceding an over-long one
                if len(ids) > cap:
                    raise ValueError(
                        f"input {i} has {len(ids)} tokens; the embedding "
                        f"bucket cap is {cap}"
                    )
        except _ModelNotFound as e:
            return _oai_error(str(e), 404, code="model_not_found")
        except (json.JSONDecodeError, TypeError, ValueError) as e:
            return _oai_error(str(e), 400)
        loop = asyncio.get_running_loop()
        vecs = [
            await loop.run_in_executor(None, embedder.embed, ids)
            for ids in inputs
        ]
        n_tokens = sum(len(i) for i in inputs)
        return web.json_response({
            "object": "list",
            "model": model,
            "data": [
                {"object": "embedding", "index": i,
                 "embedding": [float(x) for x in v]}
                for i, v in enumerate(vecs)
            ],
            "usage": {"prompt_tokens": n_tokens, "total_tokens": n_tokens},
        })

    def _embedding_inputs(self, raw) -> list[list[int]]:
        tok = self._server.tokenizer
        vocab = self._server.engine.cb.cfg.vocab_size

        def encode(s: str) -> list[int]:
            if tok is None:
                raise ValueError(
                    "string inputs need a tokenizer on this server; "
                    "send token-id lists"
                )
            return tok.encode(s)

        def _is_id(t) -> bool:
            # bool is an int subclass; True/False must not embed as 1/0
            return type(t) is int

        def check(ids: list[int]) -> list[int]:
            return _check_token_ids(ids, vocab)

        if isinstance(raw, str) and raw:
            return [encode(raw)]
        if isinstance(raw, list) and raw:
            if all(isinstance(x, str) and x for x in raw):
                return [encode(s) for s in raw]
            if all(_is_id(x) for x in raw):
                return [check(raw)]
            if all(
                isinstance(x, list) and x and all(_is_id(t) for t in x)
                for x in raw
            ):
                return [check(x) for x in raw]
        raise ValueError(
            "input must be a non-empty string, list of strings, token-id "
            "list, or list of token-id lists"
        )

    async def models(self, request: web.Request) -> web.Response:
        # tombstoned (unregistered) adapter slots render "" — dead
        # indices stay stable, but a dead name must not be listed
        ids = (MODEL_ID,) + tuple(
            n for n in self._server.adapter_names if n
        )
        return web.json_response({
            "object": "list",
            "data": [{
                "id": mid, "object": "model", "created": 0,
                "owned_by": "tpu-device-plugin",
            } for mid in ids],
        })

    async def completions(self, request: web.Request) -> web.StreamResponse:
        try:
            body = await request.json()
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
            echo = bool(body.get("echo", False))
            c = self._common(body, allow_zero_max_tokens=echo)
            prompt = self._prompt_ids(body)
            lp = body.get("logprobs")
            want_logprobs = lp is not None and lp is not False  # 0 counts
            # OpenAI completions contract on BOTH paths: 0 <= logprobs <= 5
            # (scoring.TOP_K compiles exactly 5 alternatives)
            if want_logprobs and not (0 <= int(lp) <= 5):
                raise ValueError("logprobs must be between 0 and 5")
            best_of = body.get("best_of")
            if best_of is not None:
                best_of = int(best_of)
                if not (c["n"] <= best_of <= 8):
                    raise ValueError(
                        "best_of must be >= n and <= 8 (each candidate "
                        "occupies a decode slot)"
                    )
                if c["stream"] and best_of > c["n"]:
                    raise ValueError("streaming requires best_of == n")
                if echo:
                    raise ValueError("echo does not support best_of")
                c["best_of"] = best_of
            if echo:
                # the lm-eval loglikelihood contract: echo back the prompt
                # with its own teacher-forced logprobs, generate nothing
                if getattr(self._server, "scorer", None) is None:
                    raise ValueError(
                        "echo requires prompt scoring; start the server "
                        "with --scoring"
                    )
                if c["max_new"] not in (None, 0):
                    raise ValueError(
                        "echo is supported only with max_tokens 0 "
                        "(prompt scoring)"
                    )
                if c["n"] != 1:
                    raise ValueError("echo supports n == 1 only")
                if c["stream"]:
                    raise ValueError("echo does not support streaming")
                if c["adapter"] != -1:
                    raise ValueError("echo scores the base model only")
                # the scorer's cap bounds EVERY echo request, with or
                # without logprobs — echo must not be the one API path
                # with no prompt-size validation at all (long prompts
                # past the bucket cap take the scorer's chunked path)
                cap = self._server.scorer.max_len
                if len(prompt) > cap:
                    raise ValueError(
                        f"prompt of {len(prompt)} tokens exceeds the "
                        f"scoring cap {cap}"
                    )
            else:
                self._budget(c, prompt, default=16)  # OpenAI legacy default
        except _ModelNotFound as e:
            return _oai_error(str(e), 404, code="model_not_found")
        except (json.JSONDecodeError, TypeError, ValueError) as e:
            return _oai_error(str(e), 400)
        if echo:
            top_n = int(lp) if want_logprobs else 0
            return await self._echo_score(prompt, want_logprobs, top_n)
        return await self._respond(
            request, prompt, c, want_logprobs,
            object_name="text_completion", id_prefix="cmpl", chat=False,
        )

    async def _echo_score(
        self, prompt: list[int], want_logprobs: bool, top_n: int = 0
    ) -> web.Response:
        tok = self._server.tokenizer
        lp_payload = None
        if want_logprobs:
            loop = asyncio.get_running_loop()
            try:
                lps, top_lps, top_ids = await loop.run_in_executor(
                    None, self._server.scorer.score_full, prompt
                )
            except ValueError as e:  # bucket cap: a client-size mistake
                return _oai_error(str(e), 400)
            # per-token strings via the streaming detokenizer (_TextDiffer):
            # naive per-token or prefix-diff decode mangles multi-byte
            # characters spanning tokens (U+FFFD) and SentencePiece space
            # markers; with holdback, an incomplete token contributes ""
            # and the completing one carries the resolved characters, so
            # ''.join(tokens) always equals the returned text and offsets
            # stay monotone
            tokens, offsets = [], []
            if tok is not None:
                differ = _TextDiffer(tok)
                pos = 0
                for t in prompt:
                    piece = differ.push(t)
                    offsets.append(pos)
                    tokens.append(piece)
                    pos += len(piece)
                tail = differ.flush()
                if tail and tokens:
                    tokens[-1] += tail
            else:
                pos = 0
                for t in prompt:
                    tokens.append(str(t))
                    offsets.append(pos)
                    pos += len(str(t))
            top_payload = None
            if top_n > 0:
                def tstr(tid: int) -> str:
                    return tok.decode([tid]) if tok is not None else str(tid)

                # per-position top-N alternatives (the model's preference —
                # lm-eval's is_greedy compares entry 0 to the actual token);
                # index 0 is null like token_logprobs. The legacy dict
                # format keys by token STRING, so ids that decode
                # identically (e.g. several byte ids -> U+FFFD) merge;
                # iterating best-first with setdefault keeps the most
                # probable of any colliding pair.
                top_payload: list = [None]
                for i in range(1, len(prompt)):
                    entry: dict[str, float] = {}
                    for j in range(top_n):
                        entry.setdefault(
                            tstr(int(top_ids[i, j])), float(top_lps[i, j])
                        )
                    top_payload.append(entry)
            lp_payload = {
                "tokens": tokens,
                "token_logprobs": lps,  # index 0 is null: no context
                "top_logprobs": top_payload,
                "text_offset": offsets,
            }
        if tok is None:
            text = ""  # token-ids-only server, matching the generate path
        elif lp_payload is not None:
            text = "".join(lp_payload["tokens"])  # exact by construction
        else:
            text = tok.decode(prompt)
        return web.json_response({
            # unique like the generate path's rid-based ids — a timestamp
            # collides across concurrent echo requests
            "id": f"cmpl-echo-{uuid.uuid4().hex[:16]}",
            "object": "text_completion",
            "created": int(time.time()),
            "model": MODEL_ID,
            "choices": [{
                "index": 0,
                "text": text,
                "finish_reason": "length",
                "logprobs": lp_payload,
            }],
            "usage": {
                "prompt_tokens": len(prompt),
                "completion_tokens": 0,
                "total_tokens": len(prompt),
            },
        })

    async def chat_completions(self, request: web.Request) -> web.StreamResponse:
        try:
            body = await request.json()
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
            c = self._common(body)
            if self._server.tokenizer is None:
                raise ValueError(
                    "chat completions need a tokenizer on this server"
                )
            # chat-only: the newer field name wins over max_tokens when
            # both are sent (OpenAI deprecates max_tokens here); an
            # explicit null means absent, as OpenAI treats it
            mct = body.get("max_completion_tokens")
            if mct is not None:
                mct = int(mct)
                if mct < 1:
                    raise ValueError("max_completion_tokens must be >= 1")
                c["max_new"] = mct
            messages = body.get("messages")
            if not isinstance(messages, list) or not messages:
                raise ValueError("messages must be a non-empty list")
            prompt = _render_chat(self._server.tokenizer, messages)
            self._budget(c, prompt, default=None)  # chat: the slot budget
            want_logprobs = bool(body.get("logprobs", False))
        except _ModelNotFound as e:
            return _oai_error(str(e), 404, code="model_not_found")
        except (json.JSONDecodeError, TypeError, ValueError) as e:
            return _oai_error(str(e), 400)
        return await self._respond(
            request, prompt, c, want_logprobs,
            object_name="chat.completion", id_prefix="chatcmpl", chat=True,
        )

    async def _respond(
        self, request: web.Request, prompt: list[int], c: dict,
        want_logprobs: bool, object_name: str, id_prefix: str, chat: bool,
    ) -> web.StreamResponse:
        from k8s_gpu_device_plugin_tpu.serving.scheduler import (
            SchedulerOverloadError,
        )

        from k8s_gpu_device_plugin_tpu.models.batching import (
            RequestTooLargeError,
        )

        try:
            subs = self._submit(prompt, c)
        except RequestTooLargeError as e:
            # permanent refusal: the structured fields name the wall
            # (same body shape as the native surface's 422)
            return _oai_error(str(e), 422, code="request_too_large",
                              extra=e.body())
        except ValueError as e:  # capacity/bucket/sampler validation
            return _oai_error(str(e), 422)
        except SchedulerOverloadError as e:  # queue full: 429 + Retry-After
            sched = getattr(self._server.engine.cb, "scheduler", None)
            if sched is not None:
                sched.count_sync_rejection(self._server.engine.cb)
            return _oai_overloaded(str(e), e.reason, e.retry_after)
        except RuntimeError as e:  # engine dead
            return _oai_error(str(e), 503)
        rid = subs[0][0]
        oai_id = f"{id_prefix}-{rid}"
        created = int(time.time())

        if c["stream"]:
            return await self._stream(
                request, subs[0][1], oai_id, created, c, chat, rid,
                want_logprobs, object_name,
            )

        from k8s_gpu_device_plugin_tpu.serving.server import drain_queue

        try:
            drained = await asyncio.gather(*(drain_queue(q) for _, q in subs))
        except asyncio.CancelledError:
            for eid, _ in subs:
                self._server.engine.cancel(eid)
            raise
        err = next((e for _, _, e in drained if e is not None), None)
        if err is not None:
            # engine death / exhausted restart budget mid-request: a
            # retryable server_error, never a 200 with truncated text
            return _oai_error(err.message, 503, code=err.code)
        cands = []
        completion_tokens = 0  # usage counts EVERYTHING sampled (best_of too)
        for toks, lps, _err in drained:
            # OpenAI: the matched stop sequence is never in the output
            kept = trim_stop_suffix(toks, c["stop"])
            klps = lps[:len(kept)]
            completion_tokens += len(kept)
            finish = (
                "stop" if len(kept) < len(toks)
                else self._finish_reason(len(toks), c["max_new"])
            )
            cands.append((kept, klps, finish))
        if len(cands) > c["n"]:
            # best_of ranking: highest mean token logprob (OpenAI's
            # "highest log probability per token"), stable on ties. A
            # fully-stop-trimmed candidate has no tokens and no mean —
            # mean 0.0 would be the MAXIMUM (logprobs are <= 0), so empty
            # candidates rank last, not first.
            cands.sort(
                key=lambda t: (
                    -(sum(t[1]) / len(t[1])) if t[1] else float("inf")
                )
            )
            cands = cands[:c["n"]]
        choices = []
        for i, (kept, lps, finish) in enumerate(cands):
            text = self._decode(kept)
            choice: dict = {"index": i, "finish_reason": finish}
            if chat:
                choice["message"] = {"role": "assistant", "content": text}
                if want_logprobs:
                    choice["logprobs"] = {"content": [
                        {"token": self._decode([t]), "logprob": lp}
                        for t, lp in zip(kept, lps)
                    ]}
            else:
                choice["text"] = text
                if want_logprobs:
                    choice["logprobs"] = {
                        "tokens": [self._decode([t]) for t in kept],
                        "token_logprobs": lps,
                    }
            choices.append(choice)
        # prompt tokens served from the automatic prefix cache (OpenAI's
        # usage.prompt_tokens_details.cached_tokens field). n>1 submits
        # one engine request per choice over the same prompt and each
        # matches independently (the first may even seed the cache for
        # the rest mid-flight); usage is one envelope per API request, so
        # report the best reuse any choice achieved.
        infos = [self._server.engine.pop_request_info(eid) for eid, _ in subs]
        reject = next(
            (i["reject_reason"] for i in infos if i.get("reject_reason")),
            None,
        )
        if reject is not None and completion_tokens == 0:
            # rejected while queued (pool-pressure deferral past the
            # budget) before a single token: overload, not a completion
            return _oai_overloaded(
                "request rejected under overload before admission",
                reject,
                max((i.get("retry_after", 1) for i in infos), default=1),
            )
        envelope = {
            "id": oai_id,
            "object": object_name,
            "created": created,
            "model": c["model"],
            "choices": choices,
            "usage": {
                "prompt_tokens": len(prompt),
                "prompt_tokens_details": {
                    "cached_tokens": max(
                        (i.get("cached_tokens", 0) for i in infos),
                        default=0,
                    ),
                },
                "completion_tokens": completion_tokens,
                "total_tokens": len(prompt) + completion_tokens,
            },
        }
        if c.get("timeline"):
            # extension field (opt-in, like the SLO extras): the primary
            # choice's phase breakdown; null under --attributionOff
            envelope["timeline"] = infos[0].get("timeline")
        return web.json_response(envelope)

    async def _stream(
        self, request: web.Request, q: asyncio.Queue, oai_id: str,
        created: int, c: dict, chat: bool, rid: int, want_logprobs: bool,
        object_name: str,
    ) -> web.StreamResponse:
        chunk_object = "chat.completion.chunk" if chat else object_name
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream", "Cache-Control": "no-cache",
        })
        await resp.prepare(request)

        def chunk(text: str, lp: float | None, finish: str | None) -> bytes:
            choice: dict = {"index": 0, "finish_reason": finish}
            if chat:
                choice["delta"] = {"content": text} if finish is None else {}
                if lp is not None:
                    choice["logprobs"] = {"content": [
                        {"token": text, "logprob": lp}
                    ]}
            else:
                choice["text"] = text
                if lp is not None:
                    choice["logprobs"] = {
                        "tokens": [text], "token_logprobs": [lp],
                    }
            evt = {
                "id": oai_id, "object": chunk_object, "created": created,
                "model": c["model"], "choices": [choice],
            }
            return f"data: {json.dumps(evt)}\n\n".encode()

        differ = (
            _TextDiffer(self._server.tokenizer)
            if self._server.tokenizer is not None else None
        )
        # OpenAI never streams a stop sequence: hold back the last
        # max(stop) tokens — a suffix match can span exactly that many,
        # and anything older can no longer be part of one.
        hold = max((len(s) for s in c["stop"]), default=0)
        pending: list[tuple[int, float]] = []
        all_out: list[int] = []

        async def release(tok: int, lp: float) -> None:
            # token-ids-only server: text is always "" (matching the
            # non-streamed path — ids belong to the native /v1/generate
            # API); the stream still carries logprobs when asked
            text = differ.push(tok) if differ is not None else ""
            if text or want_logprobs:
                await resp.write(chunk(
                    text, lp if want_logprobs else None, None
                ))

        try:
            if chat:
                role_evt = {
                    "id": oai_id, "object": chunk_object,
                    "created": created, "model": c["model"],
                    "choices": [{"index": 0, "finish_reason": None,
                                 "delta": {"role": "assistant"}}],
                }
                await resp.write(
                    f"data: {json.dumps(role_evt)}\n\n".encode()
                )
            while True:
                item = await q.get()
                if isinstance(item, StreamError):
                    # abnormal close: the OpenAI stream-error envelope
                    # (the shape SDKs surface as a retryable
                    # server_error), then [DONE] — never a clean
                    # finish_reason over a truncated stream
                    err_evt = {"error": {
                        "message": item.message,
                        "type": "server_error",
                        "code": item.code,
                    }}
                    await resp.write(
                        f"data: {json.dumps(err_evt)}\n\n".encode()
                    )
                    await resp.write(b"data: [DONE]\n\n")
                    break
                if item is None:
                    if not all_out:
                        info = self._server.engine.pop_request_info(rid)
                        if info.get("reject_reason"):
                            # rejected while queued, zero tokens: the
                            # SSE stream is already 200, so the overload
                            # signal rides an error event (the OpenAI
                            # stream-error shape SDKs surface) before
                            # [DONE] — a bare finish_reason "stop" would
                            # read as a successful empty completion
                            err = {"error": {
                                "message": "request rejected under "
                                           "overload before admission",
                                "type": "rate_limit_error",
                                "code": info["reject_reason"],
                                "retry_after": info.get("retry_after", 1),
                            }}
                            await resp.write(
                                f"data: {json.dumps(err)}\n\n".encode()
                            )
                            await resp.write(b"data: [DONE]\n\n")
                            break
                    kept = trim_stop_suffix(all_out, c["stop"])
                    stopped = len(kept) < len(all_out)
                    # flush pending tokens that survive the trim
                    drop = len(all_out) - len(kept)
                    for tok, lp in pending[:len(pending) - drop]:
                        await release(tok, lp)
                    tail = differ.flush() if differ is not None else ""
                    if tail:
                        await resp.write(chunk(tail, None, None))
                    finish = (
                        "stop" if stopped
                        else self._finish_reason(len(all_out), c["max_new"])
                    )
                    await resp.write(chunk("", None, finish))
                    await resp.write(b"data: [DONE]\n\n")
                    break
                all_out.append(item[0])
                pending.append(item)
                while len(pending) > hold:
                    tok, lp = pending.pop(0)
                    await release(tok, lp)
        except (asyncio.CancelledError, ConnectionResetError):
            self._server.engine.cancel(rid)
            raise
        await resp.write_eof()
        return resp


def _check_token_ids(ids: list, vocab: int) -> list[int]:
    """The one token-id discipline for both prompt and embedding inputs:
    bools are int subclasses but must not decode as 1/0, and an
    out-of-vocab id would silently clamp in the embedding gather."""
    for t in ids:
        if type(t) is not int:
            raise ValueError("token ids must be plain ints")
        if not (0 <= t < vocab):
            raise ValueError(f"token id {t} outside vocab [0, {vocab})")
    return list(ids)


def _oai_overloaded(message: str, reason: str,
                    retry_after: int) -> web.Response:
    """Scheduler overload (queue full / deferral budget): HTTP 429 with
    a Retry-After header and OpenAI's retryable error envelope —
    ``rate_limit_error`` is the type SDK backoff logic keys on, and the
    ``code`` says WHICH valve fired. Deliberately not the generic
    ``invalid_request_error`` path: a retry CAN succeed here."""
    return web.json_response(
        {"error": {"message": message, "type": "rate_limit_error",
                   "code": reason, "retry_after": int(retry_after)}},
        status=429,
        headers={"Retry-After": str(int(retry_after))},
    )


def _oai_error(message: str, status: int, code: str | None = None,
               extra: "dict | None" = None) -> web.Response:
    """OpenAI error envelope (clients pattern-match on error.message).

    ``error.type`` keys SDK retry logic: 5xx (engine dead — a restart may
    fix it) must read as retryable ``server_error``. Everything 4xx stays
    ``invalid_request_error``: the only 422 path here is permanent request
    validation (prompt exceeding slot capacity, bucket overflow, unknown
    adapter), which a retry can never fix. ``extra`` merges structured
    fields into the error object (``request_too_large`` ships
    ``prompt_tokens``/``max_new``/``limit`` so clients can resize)."""
    err_type = "server_error" if status >= 500 else "invalid_request_error"
    err: dict = {"message": message, "type": err_type, "code": code}
    if extra:
        err.update(extra)
    return web.json_response({"error": err}, status=status)


def add_openai_routes(server) -> None:
    """Register the OpenAI-compatible routes on an InferenceServer."""
    api = _OpenAIRoutes(server)
    server.app.router.add_post("/v1/completions", api.completions)
    server.app.router.add_post("/v1/chat/completions", api.chat_completions)
    server.app.router.add_post("/v1/embeddings", api.embeddings)
    server.app.router.add_get("/v1/models", api.models)
