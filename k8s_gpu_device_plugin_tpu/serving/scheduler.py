"""SLO-aware request scheduling for the serving engine.

PRs 1-5 made the single-chip serving path fast; this module decides
WHICH work runs when there is more of it than the chip can hold. The
batcher keeps owning the pending list (``ContinuousBatcher.pending``)
and the slot machinery; a ``Scheduler`` plugs in behind a narrow seam
(duck-typed, like the prefix cache and the metrics object — the models/
layer never imports serving/):

- ``on_submit(req, cb)``     — admission-control gate (queue cap,
  token-bucket quota charge); may raise :class:`SchedulerOverloadError`,
  which the HTTP planes translate to 429 + Retry-After.
- ``plan(cb, now)``          — once per ``_admit`` pass: reorders
  ``cb.pending`` IN PLACE (the head is the next admission), returns
  ``(rejects, preempt_slot)`` — requests whose pool-pressure deferral
  outlived the budget, and at most one running slot to preempt for a
  higher class about to miss its deadline.
- ``on_admitted / on_retired / on_preempted`` — accounting: queue-wait,
  deadline misses and overruns, per-class goodput, WFQ virtual time.
- ``sched_stats()``          — snapshot for cross-thread readers
  (/v1/health), the same approximate-read contract as ``kv_stats``.

Two policies:

- :class:`Scheduler` (``fifo``, the default): arrival order, no
  reordering, no preemption — byte-for-byte the pre-scheduler admission
  (token/logprob streams are pinned bit-identical with the scheduler
  attached or absent). It still ACCOUNTS deadlines/goodput and enforces
  ``max_queue``/``defer_budget_ms`` so the fifo arm of an A/B reports
  the same SLO numbers the slo arm does.
- :class:`SloScheduler` (``slo``): strict priority classes (lower int =
  more urgent), weighted fair queuing across tenants within a class
  (virtual time charged per admitted token / tenant weight),
  earliest-deadline-first within a tenant-class, token-bucket quotas
  (an over-quota tenant's requests sort behind every in-quota class —
  demoted, not dropped), and pressure-triggered preemption: when the
  head of the queue carries a deadline it cannot meet by waiting for
  the earliest natural slot retirement, the longest-running strictly-
  lower-class decode is evicted (its pages free, it requeues, and the
  resume re-prefills only what the prefix cache cannot serve —
  ``ContinuousBatcher._preempt_slot`` owns the mechanics; streams are
  pinned bit-identical across a preempt/resume cycle).

Thread model: the policy ledgers are engine-thread state
(``# owner: engine``); the request thread touches only ``max_queue``
(immutable) via :meth:`check_capacity`'s atomic ``len()`` path;
/v1/health goes through the :meth:`sched_stats` snapshot. The one
exception is the ``rejections`` counter pair: sync queue-full raises
are only visible to the HTTP planes, so :meth:`count_sync_rejection`
writes it off-thread under ``_rej_lock``.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass

from k8s_gpu_device_plugin_tpu.obs.trace import get_tracer

#: the serving edge's defaults (applied in InferenceEngine.submit — a
#: request that names nothing lands here)
DEFAULT_TENANT = "default"
DEFAULT_PRIORITY = 1
#: priority classes are small ints, lower = more urgent; the bound keeps
#: metric label cardinality sane
MAX_PRIORITY = 9


class SchedulerOverloadError(RuntimeError):
    """The server cannot take this request NOW (queue full, or its
    pool-pressure deferral outlived the budget) — a transient condition,
    distinct from the permanent ValueError validation family. The HTTP
    planes translate it to 429 with a Retry-After hint."""

    def __init__(self, message: str, reason: str, retry_after: int):
        super().__init__(message)
        self.reason = reason   # queue_full | defer_budget | adapter_quota
        self.retry_after = int(retry_after)


@dataclass(frozen=True)
class TenantQuota:
    """Token-bucket + WFQ parameters for one tenant: ``rate`` tokens/s
    refill, ``burst`` bucket capacity (tokens), ``weight`` the WFQ
    share. ``rate=0`` means unmetered (weight still applies)."""

    rate: float = 0.0
    burst: float = 0.0
    weight: float = 1.0


def parse_tenant_quotas(spec: str) -> dict[str, TenantQuota]:
    """``--tenantQuota`` value -> {tenant: TenantQuota}.

    Syntax: ``name=rate[:burst=B][:weight=W],...`` — rate in tokens/s
    (prompt + budgeted output tokens charged at submit, refunded if the
    request is cancelled or rejected before ever taking a slot); burst
    defaults to 4x rate; weight defaults to 1."""
    out: dict[str, TenantQuota] = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        if "=" not in entry:
            raise ValueError(
                f"--tenantQuota entry {entry!r}: expected name=rate"
                "[:burst=B][:weight=W]"
            )
        name, rest = entry.split("=", 1)
        name = name.strip()
        if not name:
            raise ValueError(f"--tenantQuota entry {entry!r}: empty tenant")
        parts = rest.split(":")
        try:
            rate = float(parts[0])
        except ValueError:
            raise ValueError(
                f"--tenantQuota entry {entry!r}: rate must be a number"
            ) from None
        burst = weight = None
        for p in parts[1:]:
            if p.startswith("burst="):
                burst = float(p[len("burst="):])
            elif p.startswith("weight="):
                weight = float(p[len("weight="):])
            else:
                raise ValueError(
                    f"--tenantQuota entry {entry!r}: unknown option {p!r}"
                )
        if rate < 0 or (burst is not None and burst < 0):
            raise ValueError(
                f"--tenantQuota entry {entry!r}: rate/burst must be >= 0"
            )
        if weight is not None and weight <= 0:
            raise ValueError(
                f"--tenantQuota entry {entry!r}: weight must be > 0"
            )
        out[name] = TenantQuota(
            rate=rate,
            burst=burst if burst is not None else 4.0 * rate,
            weight=weight if weight is not None else 1.0,
        )
    return out


def parse_adapter_quotas(spec: str) -> dict[str, TenantQuota]:
    """``--adapterQuota`` value -> {adapter name: TenantQuota}.

    Syntax: ``name=rate[:burst=B],...`` — rate in tokens/s charged per
    request (prompt + budgeted output, the same cost model as tenant
    quotas), burst defaults to 4x rate. Unlike tenant quotas these are
    HARD limits enforced at submit under every policy (fifo included):
    an adapter is a model variant, not a payer — there is no fairness
    ledger to demote against, so over-quota is a 429, not a demotion.
    Weight is not accepted: adapters never join the WFQ ordering."""
    out: dict[str, TenantQuota] = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        if "=" not in entry:
            raise ValueError(
                f"--adapterQuota entry {entry!r}: expected "
                "name=rate[:burst=B]"
            )
        name, rest = entry.split("=", 1)
        name = name.strip()
        if not name:
            raise ValueError(
                f"--adapterQuota entry {entry!r}: empty adapter name"
            )
        parts = rest.split(":")
        try:
            rate = float(parts[0])
        except ValueError:
            raise ValueError(
                f"--adapterQuota entry {entry!r}: rate must be a number"
            ) from None
        burst = None
        for p in parts[1:]:
            if p.startswith("burst="):
                burst = float(p[len("burst="):])
            else:
                raise ValueError(
                    f"--adapterQuota entry {entry!r}: unknown option {p!r}"
                )
        if rate <= 0 or (burst is not None and burst < 0):
            raise ValueError(
                f"--adapterQuota entry {entry!r}: rate must be > 0 and "
                "burst >= 0 (omit the entry to leave an adapter unmetered)"
            )
        out[name] = TenantQuota(
            rate=rate,
            burst=burst if burst is not None else 4.0 * rate,
        )
    return out


class _AdapterState:
    """Per-adapter token bucket: the hard-reject ledger. Slimmer than
    ``_TenantState`` on purpose — adapters carry no WFQ identity, no
    deadlines, no goodput; just a bucket and the submit/reject tally."""

    __slots__ = ("quota", "level", "last_refill", "submitted", "rejected")

    def __init__(self, quota: TenantQuota, now: float):
        self.quota = quota
        self.level = quota.burst        # bucket starts full
        self.last_refill = now
        self.submitted = 0
        self.rejected = 0

    def refill(self, now: float) -> None:
        self.level = min(
            self.quota.burst,
            self.level + (now - self.last_refill) * self.quota.rate,
        )
        self.last_refill = now


class _TenantState:
    """Per-tenant ledger: token bucket, WFQ virtual time, tallies."""

    __slots__ = (
        "quota", "level", "last_refill", "vtime", "active", "submitted",
        "admitted", "retired", "preempted", "rejected", "deadline_misses",
        "goodput_tokens",
    )

    def __init__(self, quota: TenantQuota, now: float):
        self.quota = quota
        self.level = quota.burst        # bucket starts full
        self.last_refill = now
        self.vtime = 0.0
        self.active = 0                 # requests submitted, not retired
        self.submitted = 0
        self.admitted = 0
        self.retired = 0
        self.preempted = 0
        self.rejected = 0
        self.deadline_misses = 0
        self.goodput_tokens = 0

    def refill(self, now: float) -> None:
        if self.quota.rate > 0:
            self.level = min(
                self.quota.burst,
                self.level + (now - self.last_refill) * self.quota.rate,
            )
        self.last_refill = now

    def over_quota(self) -> bool:
        return self.quota.rate > 0 and self.level < 0


class Scheduler:
    """The ``fifo`` policy and the base of every other: arrival-order
    admission (``plan`` never reorders), no preemption — bit-identical
    to the pre-scheduler batcher — plus the accounting and overload
    valves every policy shares (queue cap, deferral budget, deadline /
    goodput / queue-wait bookkeeping).

    All mutable state is engine-thread-owned; cross-thread readers use
    :meth:`sched_stats` (snapshot) or :meth:`check_capacity` (atomic
    ``len()`` counts computed by the caller).
    """

    policy = "fifo"

    def __init__(
        self,
        max_queue: int = 0,
        defer_budget_ms: int = 0,
        quotas: "dict[str, TenantQuota] | None" = None,
        adapter_quotas: "dict[str, TenantQuota] | None" = None,
    ):
        if max_queue < 0:
            raise ValueError(f"max_queue must be >= 0, got {max_queue}")
        if defer_budget_ms < 0:
            raise ValueError(
                f"defer_budget_ms must be >= 0, got {defer_budget_ms}"
            )
        self.max_queue = int(max_queue)          # immutable after init
        self.defer_budget_s = defer_budget_ms / 1000.0  # immutable
        self.quotas = dict(quotas or {})         # immutable after init
        # hard per-adapter rate limits (parse_adapter_quotas); enforced
        # under EVERY policy — an adapter quota is capacity protection,
        # not fairness, so fifo enforces it too
        self.adapter_quotas = dict(adapter_quotas or {})  # immutable
        self._adapters: dict[str, _AdapterState] = {}  # owner: engine
        # rid -> (adapter name, cost) charged but not yet admitted
        # (refunded if the request dies while still queued)
        self._adapter_queued_cost: dict[int, tuple] = {}  # owner: engine
        self._tenants: dict[str, _TenantState] = {}  # owner: engine
        # rid -> quota tokens charged but not yet admitted (refunded if
        # the request dies while still queued)
        self._queued_cost: dict[int, float] = {}  # owner: engine
        # rid -> perf_counter of its FIRST pool-pressure deferral (the
        # defer-budget clock); cleared on admission/retirement
        self._defer_t0: dict[int, float] = {}  # owner: engine
        # EWMA of the inter-plan interval while busy (~ one decode step):
        # the wait estimator and the Retry-After hint
        self._ewma_step_s = 0.0  # owner: engine
        self._last_plan_t = 0.0  # owner: engine
        self._preempted_for: dict[int, int] = {}  # rid -> count; owner: engine
        self.preemptions = 0      # owner: engine
        # the ONE piece of mutable state written off the engine thread:
        # sync queue-full rejections are counted by the HTTP planes
        # (the raise happens on the request thread, so only they see
        # it), and dict-int += is not atomic — a lock keeps concurrent
        # 429 bursts from losing increments. defer_budget increments
        # ride the engine thread but share the dict, so they lock too.
        self._rej_lock = threading.Lock()
        self.rejections = {
            "queue_full": 0, "defer_budget": 0, "adapter_quota": 0,
        }
        self._tracer = get_tracer()

    # --- shared helpers ---------------------------------------------------

    def _tenant(self, name: str, now: float) -> _TenantState:
        ts = self._tenants.get(name)
        if ts is None:
            ts = self._tenants[name] = _TenantState(
                self.quotas.get(name, TenantQuota()), now
            )
        return ts

    def _refloor_vtime(self, ts: _TenantState) -> None:
        """A tenant whose backlog just (re)started — no live requests —
        must not replay virtual time banked while idle: that would let
        a returning tenant monopolize admission until the gap burned
        off. Standard WFQ: rejoin at the system virtual time (the
        minimum over tenants with live work)."""
        if ts.active:
            return
        floor = min(
            (t.vtime for t in self._tenants.values()
             if t is not ts and t.active > 0),
            default=None,
        )
        if floor is not None:
            ts.vtime = max(ts.vtime, floor)

    @staticmethod
    def request_cost(req) -> float:
        """Quota/WFQ charge for one request: the work it may occupy the
        chip with (prompt prefill + budgeted output)."""
        return float(len(req.prompt) + req.max_new)

    def retry_after_s(self) -> int:
        """Retry-After hint for overload responses: one average request
        drain if the step EWMA has data, else 1s."""
        if self._ewma_step_s > 0:
            return max(1, min(30, int(math.ceil(self._ewma_step_s * 64))))
        return 1

    # --- request-thread side ---------------------------------------------

    def check_capacity(self, queued_now: int) -> None:
        """Queue-cap gate for the REQUEST thread (the serving engine's
        submit handler): the caller computes ``queued_now`` from atomic
        ``len()`` reads; this method touches no engine-owned state."""
        if self.max_queue and queued_now >= self.max_queue:
            raise SchedulerOverloadError(
                f"request queue is full ({queued_now} waiting, cap "
                f"{self.max_queue}); retry later",
                reason="queue_full", retry_after=self.retry_after_s(),
            )

    # --- engine-thread seam (called by ContinuousBatcher) -----------------

    def _charge_adapter(self, req, cb, now: float) -> None:
        """Hard per-adapter token-bucket gate: raises 429 when the
        request's adapter is quota'd and its bucket cannot cover the
        cost. Runs BEFORE the tenant charge so a rejected request never
        touches the tenant ledger (nothing to refund)."""
        if not self.adapter_quotas or getattr(req, "adapter", -1) < 0:
            return
        names = getattr(cb, "adapter_names", ())
        name = names[req.adapter] if req.adapter < len(names) else ""
        quota = self.adapter_quotas.get(name) if name else None
        if quota is None:
            return
        st = self._adapters.get(name)
        if st is None:
            st = self._adapters[name] = _AdapterState(quota, now)
        st.refill(now)
        st.submitted += 1
        cost = self.request_cost(req)
        if st.level < cost:
            st.rejected += 1
            with self._rej_lock:
                self.rejections["adapter_quota"] += 1
            if cb.metrics is not None:
                count = getattr(cb.metrics, "on_sched_rejected", None)
                if count is not None:
                    count("adapter_quota")
            raise SchedulerOverloadError(
                f"adapter {name!r} is over its request-rate quota "
                f"({quota.rate:g} tokens/s); retry later",
                reason="adapter_quota", retry_after=self.retry_after_s(),
            )
        st.level -= cost
        self._adapter_queued_cost[req.rid] = (name, cost)

    def on_submit(self, req, cb) -> None:
        """Admission control + quota charge at enqueue time. Raising
        here leaves the batcher untouched (the request never queues)."""
        self.check_capacity(len(cb.pending))
        now = time.perf_counter()
        self._charge_adapter(req, cb, now)
        ts = self._tenant(req.tenant, now)
        ts.refill(now)
        self._refloor_vtime(ts)
        ts.submitted += 1
        ts.active += 1
        cost = self.request_cost(req)
        if ts.quota.rate > 0:
            # charge even into debt: over-quota demotes (slo) rather
            # than drops; the balance is refunded if the request is
            # cancelled or rejected before ever taking a slot
            ts.level -= cost
            self._queued_cost[req.rid] = cost

    def plan(self, cb, now: float) -> tuple[list, "int | None"]:
        """One admission pass: update the step EWMA, expire over-budget
        deferrals. FIFO never reorders and never preempts."""
        if self._last_plan_t:
            dt = now - self._last_plan_t
            # only count busy intervals (idle waits are not steps)
            if cb.running and 0 < dt < 1.0:
                self._ewma_step_s = (
                    0.9 * self._ewma_step_s + 0.1 * dt
                    if self._ewma_step_s else dt
                )
        self._last_plan_t = now
        return self._expired_deferrals(cb, now), None

    def _expired_deferrals(self, cb, now: float) -> list:
        """Pool-pressure deferrals older than the budget become
        rejections (the batcher retires them; the 429 surfaces through
        the request's stream info)."""
        if not self.defer_budget_s or not cb.pending:
            return []
        head = cb.pending[0]
        if not head.defer_counted or head.out:
            # a head with OUTPUT is a preempted request awaiting resume:
            # its tokens are already streaming to a client, so rejecting
            # it would 200 a silently truncated result — it keeps
            # waiting (pages free as slots retire; its class ordering
            # already puts it where the policy wants it)
            self._defer_t0.pop(head.rid, None)
            return []
        t0 = self._defer_t0.setdefault(head.rid, now)
        if now - t0 <= self.defer_budget_s:
            return []
        return [head]

    def on_admitted(self, req, cb, now: float) -> None:
        ts = self._tenant(req.tenant, now)
        ts.refill(now)
        self._queued_cost.pop(req.rid, None)  # charge becomes final
        self._adapter_queued_cost.pop(req.rid, None)  # ditto
        self._defer_t0.pop(req.rid, None)
        if req.preemptions or getattr(req, "restarts", 0):
            # a RESUMED request (preemption eviction, or an engine-crash
            # recovery resume — serving/supervisor.py): its first
            # admission already charged the full worst-case work and
            # observed the queue wait — re-charging the (now
            # output-inflated) prompt would demote the victims below
            # their fair share
            return
        ts.admitted += 1
        # WFQ virtual time advances by the admitted work / weight — the
        # fifo policy keeps the ledger too, so flipping --schedPolicy
        # changes ordering, not observability
        ts.vtime += self.request_cost(req) / ts.quota.weight
        wait = now - req.t_submit
        if cb.metrics is not None:
            observe = getattr(cb.metrics, "observe_queue_wait", None)
            if observe is not None:
                observe(wait)
        if self._tracer.enabled and req.span is not None:
            # the scheduling span COVERS the queue wait (t0 backdated),
            # carrying the SLO identity the admit span doesn't know
            self._tracer.span(
                "sched_queue", component="sched", parent=req.span,
                t0=req.t_submit, tenant=req.tenant, priority=req.priority,
                deadline_in_ms=(
                    round((req.deadline - now) * 1000.0)
                    if req.deadline is not None else None
                ),
            ).end()

    def on_retired(self, req, cb, reason: str, now: float) -> None:
        ts = self._tenant(req.tenant, now)
        ts.retired += 1
        ts.active = max(0, ts.active - 1)
        self._defer_t0.pop(req.rid, None)
        self._preempted_for.pop(req.rid, None)
        cost = self._queued_cost.pop(req.rid, None)
        if cost is not None:
            # died while still queued (cancel / defer-budget rejection):
            # the charged work never ran — give it back
            ts.refill(now)
            ts.level = min(ts.quota.burst, ts.level + cost)
        acharge = self._adapter_queued_cost.pop(req.rid, None)
        if acharge is not None:
            aname, acost = acharge
            ast = self._adapters.get(aname)
            if ast is not None:
                ast.refill(now)
                ast.level = min(ast.quota.burst, ast.level + acost)
        if reason == "rejected":
            ts.rejected += 1
            with self._rej_lock:
                self.rejections["defer_budget"] += 1
            if cb.metrics is not None:
                count = getattr(cb.metrics, "on_sched_rejected", None)
                if count is not None:
                    count("defer_budget")
            return
        if reason == "cancelled":
            return  # the client left: neither goodput nor a miss
        goodput = len(req.out)
        if req.deadline is not None and now > req.deadline:
            ts.deadline_misses += 1
            goodput = 0  # late tokens are not goodput
            if cb.metrics is not None:
                miss = getattr(cb.metrics, "on_deadline_miss", None)
                if miss is not None:
                    miss(req.tenant, now - req.deadline)
        ts.goodput_tokens += goodput
        if cb.metrics is not None and goodput:
            good = getattr(cb.metrics, "on_goodput", None)
            if good is not None:
                good(req.tenant, str(req.priority), goodput)

    def on_preempted(self, req, cb, now: float) -> None:
        ts = self._tenant(req.tenant, now)
        ts.preempted += 1
        self.preemptions += 1
        if cb.metrics is not None:
            count = getattr(cb.metrics, "on_preemption", None)
            if count is not None:
                count()

    def count_sync_rejection(self, cb) -> None:
        """A submit-time queue-full raise never reaches the batcher;
        the HTTP plane (or bench driver) reports it here so the
        rejection still lands in stats/metrics. Runs OFF the engine
        thread — the one sanctioned write, under ``_rej_lock``
        (prometheus counters are internally locked already)."""
        with self._rej_lock:
            self.rejections["queue_full"] += 1
        if cb is not None and cb.metrics is not None:
            count = getattr(cb.metrics, "on_sched_rejected", None)
            if count is not None:
                count("queue_full")

    # --- cross-thread snapshot --------------------------------------------

    def sched_stats(self) -> dict:
        """Queue + per-tenant view for /v1/health: plain numbers copied
        under the same approximate-read contract as ``kv_stats`` (the
        GIL keeps each read atomic; list() snapshots before iterating)."""
        tenants = {}
        for name, ts in list(self._tenants.items()):
            tenants[name] = {
                "submitted": ts.submitted,
                "admitted": ts.admitted,
                "retired": ts.retired,
                "preempted": ts.preempted,
                "rejected": ts.rejected,
                "deadline_misses": ts.deadline_misses,
                "goodput_tokens": ts.goodput_tokens,
                "quota_rate": ts.quota.rate,
                "quota_level": round(ts.level, 1),
                "weight": ts.quota.weight,
            }
        adapters = {}
        for name, ast in list(self._adapters.items()):
            adapters[name] = {
                "submitted": ast.submitted,
                "rejected": ast.rejected,
                "quota_rate": ast.quota.rate,
                "quota_level": round(ast.level, 1),
            }
        with self._rej_lock:
            rejections = dict(self.rejections)
        return {
            "policy": self.policy,
            "max_queue": self.max_queue,
            "defer_budget_ms": int(self.defer_budget_s * 1000),
            "preemptions": self.preemptions,
            "rejections": rejections,
            "step_ewma_ms": round(self._ewma_step_s * 1000.0, 3),
            "tenants": tenants,
            "adapters": adapters,
        }


class SloScheduler(Scheduler):
    """The ``slo`` policy: (over-quota, priority class, tenant WFQ
    virtual time, deadline, arrival) ordering plus pressure-triggered
    preemption. See the module docstring for the exact rules."""

    policy = "slo"

    def __init__(
        self,
        max_queue: int = 0,
        defer_budget_ms: int = 0,
        quotas: "dict[str, TenantQuota] | None" = None,
        preempt: bool = True,
        adapter_quotas: "dict[str, TenantQuota] | None" = None,
    ):
        super().__init__(max_queue=max_queue, defer_budget_ms=defer_budget_ms,
                         quotas=quotas, adapter_quotas=adapter_quotas)
        self.preempt_enabled = bool(preempt)  # immutable after init

    def plan(self, cb, now: float) -> tuple[list, "int | None"]:
        rejects, _ = super().plan(cb, now)
        if len(cb.pending) > 1:
            for ts in self._tenants.values():
                ts.refill(now)
            inf = float("inf")

            def key(req):
                ts = self._tenants.get(req.tenant)
                over = 1 if ts is not None and ts.over_quota() else 0
                vt = ts.vtime if ts is not None else 0.0
                return (
                    over, req.priority, vt,
                    req.deadline if req.deadline is not None else inf,
                    req.rid,
                )

            cb.pending.sort(key=key)
        return rejects, self._preempt_slot(cb, now, rejects)

    def _preempt_slot(self, cb, now: float, rejects) -> "int | None":
        """At most one victim per pass: the longest-running strictly-
        lower-class decode, evicted only when the queue head carries a
        deadline it cannot meet by waiting for the earliest natural
        retirement (estimated from remaining budgets x the step EWMA)."""
        if not self.preempt_enabled or not cb.pending or not cb.running:
            return None
        if not cb.chunk or not getattr(cb, "supports_preemption", False):
            return None  # resume rides the chunked-prefill scheduler
        head = cb.pending[0]
        if any(head is r for r in rejects) or head.deadline is None:
            return None
        ts = self._tenants.get(head.tenant)
        if ts is not None and ts.over_quota():
            return None  # an over-quota tenant never evicts anyone
        free = cb.n_slots - len(cb.running) - len(cb.prefilling)
        if free > 0 and not head.defer_counted:
            return None  # a slot is open and the pool can take it
        if self._preempted_for.get(head.rid, 0) >= cb.n_slots:
            return None  # this head already claimed every slot once
        remaining = min(
            (req.max_new - len(req.out) for req in cb.running.values()),
            default=0,
        )
        wait = remaining * self._ewma_step_s
        if head.deadline - now > wait:
            return None  # waiting still meets the deadline
        victims = [
            (slot, req) for slot, req in cb.running.items()
            if req.priority > head.priority
        ]
        if not victims:
            return None
        ledger = getattr(cb, "_slot_pages", None)
        if (head.defer_counted and ledger
                and getattr(cb, "window", 0) > 0):
            # The head waits on PAGES, not a slot, and out-of-window
            # recycling has broken the "longest decode = most KV"
            # proxy: a windowed row's footprint plateaus at O(window)
            # no matter how long it has run. Rank victims by the pages
            # their eviction actually returns (live ledger entries;
            # recycled slots are already 0), tie-broken toward the
            # least wasted decode work.
            def relief(sr):
                ids = ledger.get(sr[0], ())
                return (sum(1 for p in ids if p), -len(sr[1].out))

            slot = max(victims, key=relief)[0]
        else:
            slot = max(victims, key=lambda sr: len(sr[1].out))[0]
        self._preempted_for[head.rid] = \
            self._preempted_for.get(head.rid, 0) + 1
        return slot


def make_scheduler(
    policy: str,
    max_queue: int = 0,
    defer_budget_ms: int = 0,
    tenant_quota: str = "",
    preempt: bool = True,
    adapter_quota: str = "",
) -> Scheduler:
    """``--schedPolicy`` & friends -> a Scheduler (the server edge's one
    construction site; bench and tests may build policies directly)."""
    quotas = parse_tenant_quotas(tenant_quota)
    # adapter quotas are hard limits, not ordering — every policy
    # enforces them (unlike --tenantQuota, which fifo refuses)
    aquotas = parse_adapter_quotas(adapter_quota)
    if policy == "fifo":
        if quotas:
            raise ValueError(
                "--tenantQuota requires --schedPolicy slo (the fifo "
                "policy never consults quotas; silently accepting them "
                "would look like enforcement)"
            )
        return Scheduler(max_queue=max_queue,
                         defer_budget_ms=defer_budget_ms,
                         adapter_quotas=aquotas)
    if policy == "slo":
        return SloScheduler(max_queue=max_queue,
                            defer_budget_ms=defer_budget_ms,
                            quotas=quotas, preempt=preempt,
                            adapter_quotas=aquotas)
    raise ValueError(f"unknown scheduling policy {policy!r} "
                     "(expected 'fifo' or 'slo')")
