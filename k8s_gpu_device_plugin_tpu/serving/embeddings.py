"""Embeddings for the serving pod: mean-pooled final hidden states.

Rounds out the OpenAI surface (/v1/embeddings) with the model the pod
already serves: the final RMS-normed hidden states
(models/llama.py forward_with_aux(return_hidden=True) — the same seam
fused-CE training uses), mean-pooled over the REAL tokens and
L2-normalized (the conventional decoder-LM embedding recipe; unit norm
makes downstream cosine similarity a plain dot product).

TPU shape discipline: inputs pad to the serving prompt buckets so the
jitted forward compiles once per bucket, not once per length; the pool
masks padding out of the mean. Single-row dispatches keep latency flat
and shapes static.

Unsupported with weight-only quantized serving: the quantized leaves are
decode-path ({"q","s"} consumed by qmatmul); the hidden-state forward is
the training-path matmul. The CLI gates this at startup.

No reference analogue: the reference is a device-plugin daemon
(/root/reference/README.md:1-6); serving belongs to the workload stack
this framework adds.
"""

from __future__ import annotations

import threading
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, forward_with_aux


@partial(jax.jit, static_argnames=("cfg",))
def _embed_one(params, tokens, length, cfg: LlamaConfig):
    """(P,) padded ids + real length -> (D,) unit-norm mean-pooled
    embedding (padding masked out of the mean)."""
    hidden, _ = forward_with_aux(
        params, tokens[None, :], cfg, mesh=None, return_hidden=True
    )  # (1, P, D)
    mask = (jnp.arange(tokens.shape[0]) < length)[None, :, None]
    summed = jnp.sum(jnp.where(mask, hidden.astype(jnp.float32), 0.0), axis=1)
    mean = summed / jnp.maximum(length, 1)
    return (mean / jnp.linalg.norm(mean, axis=-1, keepdims=True))[0]


class Embedder:
    """Bucketed, thread-safe embedding pool over the serving params.

    ``embed`` is called from aiohttp executor threads; the lock
    serializes embedding dispatches against each other (they share the
    chip with the decode loop at the XLA queue level, which is safe)."""

    def __init__(self, params, cfg: LlamaConfig,
                 buckets: tuple[int, ...] = (32, 64, 128, 256, 512, 1024),
                 warmup: bool = True):
        self.params = params
        self.cfg = cfg
        self.buckets = tuple(sorted(buckets))
        self.dim = cfg.d_model
        self._lock = threading.Lock()
        if warmup:
            self.warmup()

    def warmup(self) -> None:
        """Compile every bucket's forward NOW, on the constructing thread.

        ``embed`` runs on aiohttp executor threads while the engine thread
        compiles decode steps; a first-request-per-bucket compile would
        race those (concurrent XLA:CPU compilation segfaults intermittently
        in this jaxlib build — see tests/conftest.py). After warmup every
        embed() dispatch is a cache hit, so the executor threads never
        compile. The server constructs the Embedder BEFORE the engine
        starts its thread, making startup single-compiler."""
        for b in self.buckets:
            _embed_one(
                self.params, jnp.zeros((b,), jnp.int32), jnp.int32(1),
                self.cfg,
            ).block_until_ready()

    def embed(self, ids: list[int]) -> np.ndarray:
        if not ids:
            raise ValueError("empty input")
        # the serving prefill's own smallest-fitting-bucket rule — one
        # implementation, so the two bucket policies can never diverge
        from k8s_gpu_device_plugin_tpu.models.batching import _bucket

        try:
            b = _bucket(len(ids), self.buckets)
        except ValueError:
            raise ValueError(
                f"input of {len(ids)} tokens exceeds the embedding "
                f"bucket cap {self.buckets[-1]}"
            ) from None
        padded = jnp.asarray(ids + [0] * (b - len(ids)), jnp.int32)
        with self._lock:
            out = _embed_one(self.params, padded, jnp.int32(len(ids)),
                             self.cfg)
            return np.asarray(out, np.float32)
