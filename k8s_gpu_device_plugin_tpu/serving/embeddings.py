"""Embeddings for the serving pod: mean-pooled final hidden states.

Rounds out the OpenAI surface (/v1/embeddings) with the model the pod
already serves: the final RMS-normed hidden states
(models/llama.py forward_with_aux(return_hidden=True) — the same seam
fused-CE training uses), mean-pooled over the REAL tokens and
L2-normalized (the conventional decoder-LM embedding recipe; unit norm
makes downstream cosine similarity a plain dot product).

TPU shape discipline: inputs pad to the serving prompt buckets so the
jitted forward compiles once per bucket, not once per length; the pool
masks padding out of the mean. Single-row dispatches keep latency flat
and shapes static.

Unsupported with weight-only quantized serving: the quantized leaves are
decode-path ({"q","s"} consumed by qmatmul); the hidden-state forward is
the training-path matmul. The CLI gates this at startup.

No reference analogue: the reference is a device-plugin daemon
(/root/reference/README.md:1-6); serving belongs to the workload stack
this framework adds.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, forward_with_aux
from k8s_gpu_device_plugin_tpu.serving.bucketed import BucketedForward


@partial(jax.jit, static_argnames=("cfg",))
def _embed_one(params, tokens, length, cfg: LlamaConfig):
    """(P,) padded ids + real length -> (D,) unit-norm mean-pooled
    embedding (padding masked out of the mean)."""
    hidden, _ = forward_with_aux(
        params, tokens[None, :], cfg, mesh=None, return_hidden=True
    )  # (1, P, D)
    mask = (jnp.arange(tokens.shape[0]) < length)[None, :, None]
    summed = jnp.sum(jnp.where(mask, hidden.astype(jnp.float32), 0.0), axis=1)
    mean = summed / jnp.maximum(length, 1)
    return (mean / jnp.linalg.norm(mean, axis=-1, keepdims=True))[0]


class Embedder(BucketedForward):
    """Bucketed, thread-safe embedding pool over the serving params.

    ``embed`` is called from aiohttp executor threads; the shared
    bucket/warmup/lock discipline (serving/bucketed.py) serializes
    dispatches and pre-compiles every bucket BEFORE the engine thread
    exists, so executor threads never compile (the XLA:CPU concurrent-
    compile segfault; see tests/conftest.py)."""

    def __init__(self, params, cfg: LlamaConfig,
                 buckets: tuple[int, ...] = (32, 64, 128, 256, 512, 1024),
                 warmup: bool = True):
        super().__init__(_embed_one, params, cfg, buckets,
                         kind="embedding", warmup=warmup)
        self.dim = cfg.d_model

    def embed(self, ids: list[int]) -> np.ndarray:
        return np.asarray(self.dispatch(ids), np.float32)
