"""Seeded fault-injection plane: failures as a first-class, rehearsed event.

The TPU pod-scaling methodology (arXiv:1909.09756, arXiv:2011.03641)
treats worker failure as something you exercise continuously, not an
outage you meet for the first time in production. This module is the
injection half of that stance: a registry of NAMED fault points
installed at the serving stack's existing seams, armed by a spec string
(``--faults`` on the server/router CLIs, or the ``TPU_SERVING_FAULTS``
environment variable) and DISARMED by default.

Fault points (the seams they live at):

==================  ====================================================
``pool.alloc``      paged-KV page reservation (ContinuousBatcher
                    ``_reserve_pages``): a fired fault reads as
                    transient pool pressure — the admission defers
                    head-of-line exactly like a real exhausted free
                    list, and retries next step
``prefill.dispatch``  the chunked-prefill dispatch
                    (``_prefill_one_chunk``): raises on the engine
                    thread — an engine crash mid-prefill
``decode.apply``    the decode readback/apply seam
                    (``_apply_decode_result``): raises on the engine
                    thread — the canonical mid-decode engine crash
``prefix.promote``  prefix-cache promotion (``_maybe_promote_prefix``):
                    raises on the engine thread after a finished prefill
``adapter.upload``  the adapter-residency admission gate
                    (``_admit_adapter``): a fired fault reads as an
                    adapter HBM upload still in flight — the admission
                    defers head-of-line exactly like a real residency
                    miss, and retries next step
``health.handler``  the replica's ``GET /v1/health``: answers 500 — a
                    live socket over a lying health surface (what the
                    router's poller must survive)
``router.connect``  the router's dispatch, BEFORE the backend request:
                    reads as a connection failure — exercises ring
                    failover
``router.midstream``  the router's SSE relay, mid-stream: reads as the
                    backend dying under a live relay. On a journaled
                    native stream this rehearses the cross-replica
                    RESUME path (the continuation splices from the
                    next ring candidate); on non-resumable streams the
                    relay aborts — the truncation-is-visible case
==================  ====================================================

Schedules (per point, all deterministic):

- ``nth=N``: fire on the Nth hit (once; raise ``times`` to repeat on
  every later hit up to that many fires).
- ``p=0.3:seed=7``: fire each hit with probability p, drawn from a
  ``random.Random`` seeded by ``(seed, point name)`` — the sequence is
  identical run to run, which is what makes a chaos bench comparable.
  Unlimited fires unless ``times`` caps it.
- ``delay_ms=D``: when the schedule fires, SLEEP instead of raising —
  latency injection (at a router seam this stalls the event loop,
  which is exactly the wedge it simulates).

Spec grammar: comma-separated entries, colon-separated fields::

    decode.apply:nth=40,pool.alloc:p=0.25:seed=3:times=6

Hot-path contract: a DISARMED point is ``None`` — consumers hold the
resolved point and guard with ``is not None`` (the PR-9 attribution
pattern), so the disarmed cost is one pointer compare per seam
(microbenched in ``make bench-chaos`` as ``fault_guard_ns``).
Consumers in ``models/`` never import this module: the plane is
duck-typed (``point()``/``error``), keeping the batcher's
no-serving-imports layering.
"""

from __future__ import annotations

import random
import time
import zlib


class FaultError(RuntimeError):
    """An injected failure. Raised ONLY by armed fault points, so a
    test or chaos harness can always tell induced breakage from real
    bugs (a real crash never carries this type)."""

    def __init__(self, point: str):
        super().__init__(f"injected fault at {point!r}")
        self.point = point


#: every seam a spec may name — a typo'd point would otherwise arm
#: nothing and silently pass the chaos it was meant to cause
KNOWN_POINTS = (
    "pool.alloc",
    "prefill.dispatch",
    "decode.apply",
    "prefix.promote",
    "adapter.upload",
    "health.handler",
    "router.connect",
    "router.midstream",
)


class FaultPoint:
    """One armed fault point: a name plus a deterministic schedule.

    ``fire()`` is the whole consumer API: it advances the schedule and
    either returns (not due), sleeps (``delay_ms`` latency injection),
    or raises :class:`FaultError`. Counters (``hits``/``fired``) are
    owned by whichever thread runs the seam — single-threaded per
    point, like the state around every seam it installs into.
    """

    __slots__ = ("name", "nth", "p", "times", "delay_ms", "hits", "fired",
                 "_rng")

    def __init__(self, name: str, *, nth: int = 0, p: float = 0.0,
                 seed: int = 0, times: int = 0, delay_ms: float = 0.0):
        if name not in KNOWN_POINTS:
            raise ValueError(
                f"unknown fault point {name!r}; known: {list(KNOWN_POINTS)}"
            )
        if (nth > 0) == (p > 0.0):
            raise ValueError(
                f"fault point {name!r} needs exactly one schedule: "
                "nth=N or p=P"
            )
        if not (0.0 <= p <= 1.0):
            raise ValueError(f"fault point {name!r}: p must be in [0, 1]")
        if delay_ms < 0:
            raise ValueError(f"fault point {name!r}: delay_ms must be >= 0")
        if times < 0:
            raise ValueError(f"fault point {name!r}: times must be >= 0")
        self.name = name
        self.nth = int(nth)
        self.p = float(p)
        # nth defaults to a single fire (the induced-crash idiom); p
        # defaults to unlimited (the background-flakiness idiom)
        self.times = int(times) if times else (1 if nth else 0)
        self.delay_ms = float(delay_ms)
        self.hits = 0
        self.fired = 0
        # seeded per (seed, name): two points under one seed draw
        # independent, reproducible sequences
        self._rng = random.Random(
            (int(seed) << 32) ^ zlib.crc32(name.encode())
        )

    def fire(self) -> None:
        """Advance the schedule; raise/sleep when due, else return."""
        self.hits += 1
        if self.times and self.fired >= self.times:
            return
        if self.nth:
            due = self.hits >= self.nth
        else:
            due = self._rng.random() < self.p
        if not due:
            return
        self.fired += 1
        if self.delay_ms:
            time.sleep(self.delay_ms / 1000.0)
            return
        raise FaultError(self.name)

    def stats(self) -> dict:
        return {
            "hits": self.hits,
            "fired": self.fired,
            "schedule": (
                {"nth": self.nth} if self.nth else {"p": self.p}
            ),
            "times": self.times,
            "delay_ms": self.delay_ms,
        }


class FaultPlane:
    """The armed-point registry one process carries (server or router).

    ``point(name)`` returns the armed :class:`FaultPoint` or ``None`` —
    consumers cache the result and guard with ``is not None``.
    ``error`` hands consumers the exception TYPE without an import
    (the batcher catches injected pool-alloc failures through it while
    keeping models/ serving-free).
    """

    #: duck-typed exception handle for no-import consumers
    error = FaultError

    def __init__(self):
        self._points: dict[str, FaultPoint] = {}

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlane | None":
        """Parse a ``--faults`` spec; empty/whitespace -> ``None`` (the
        fully disarmed plane — consumers then hold no plane at all)."""
        spec = (spec or "").strip()
        if not spec:
            return None
        plane = cls()
        for entry in spec.split(","):
            entry = entry.strip()
            if not entry:
                continue
            name, _, rest = entry.partition(":")
            name = name.strip()
            kw: dict = {}
            for fld in rest.split(":") if rest else ():
                if "=" not in fld:
                    raise ValueError(
                        f"fault spec field {fld!r} in {entry!r}: "
                        "expected key=value"
                    )
                k, v = fld.split("=", 1)
                k = k.strip()
                try:
                    if k in ("nth", "seed", "times"):
                        kw[k] = int(v)
                    elif k == "p":
                        kw[k] = float(v)
                    elif k == "delay_ms":
                        kw[k] = float(v)
                    else:
                        raise ValueError
                except ValueError:
                    raise ValueError(
                        f"fault spec field {fld!r} in {entry!r}: known "
                        "keys are nth/p/seed/times/delay_ms"
                    ) from None
            if "nth" not in kw and "p" not in kw:
                kw["nth"] = 1  # no schedule named: fire on first hit
            if name in plane._points:
                raise ValueError(f"fault point {name!r} armed twice")
            plane._points[name] = FaultPoint(name, **kw)
        return plane

    @classmethod
    def from_cli(cls, spec_arg: str) -> "FaultPlane | None":
        """The one CLI/env arming path (server AND router ``_main``):
        the ``--faults`` value, falling back to ``TPU_SERVING_FAULTS``;
        spec errors become the clean usage exit, not a traceback."""
        import os

        try:
            return cls.from_spec(
                spec_arg or os.environ.get("TPU_SERVING_FAULTS", "")
            )
        except ValueError as e:
            raise SystemExit(str(e)) from None

    def arm(self, name: str, **kw) -> FaultPoint:
        """Programmatic arming (tests/benches); same rules as the spec."""
        if name in self._points:
            raise ValueError(f"fault point {name!r} armed twice")
        pt = FaultPoint(name, **kw)
        self._points[name] = pt
        return pt

    def point(self, name: str) -> "FaultPoint | None":
        if name not in KNOWN_POINTS:
            # resolving a typo'd name would silently disarm the seam
            raise ValueError(
                f"unknown fault point {name!r}; known: {list(KNOWN_POINTS)}"
            )
        return self._points.get(name)

    def stats(self) -> dict:
        return {name: pt.stats() for name, pt in self._points.items()}
