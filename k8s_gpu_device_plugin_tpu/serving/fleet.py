"""Fleet replica registry: the bookkeeping half of the replica router.

PRs 1-10 built a single-replica serving stack; serving/router.py makes
N of those replicas act like one service. This module is the router's
state — deliberately free of HTTP so the routing policy is testable as
plain objects:

- :class:`Replica`: one backend engine's registry entry — its base URL,
  router-side in-flight count, drain flag, liveness bookkeeping (the
  health poller and the proxy's connection failures both feed it), the
  last ``/v1/health`` payload, and the 429 ``Retry-After`` cooldown.
- :class:`FleetRegistry`: the replica set plus the aggregate
  ``GET /fleet/health`` snapshot.
- :class:`HashRing`: a consistent-hash ring over replica ids (virtual
  nodes, stable byte hashing — NOT Python's salted ``hash()``), so the
  same affinity key maps to the same replica across router restarts.
- :func:`affinity_key`: the routing key — the request's
  **bucket-aligned token-prefix path**, truncated at the largest
  ``prompt_buckets`` boundary the prompt covers. These are exactly the
  boundaries serving/prefix_cache.py promotes at, so two prompts that
  can share a cached prefix hash to the same ring point and land where
  that cache lives; bytes past the last boundary cannot be cached and
  must not split the key.

Thread model: everything here is event-loop state owned by the router's
asyncio task (single-threaded, like the rest of the router) — no locks,
no cross-thread readers.
"""

from __future__ import annotations

import bisect
import email.utils
import hashlib
import json
import time
from urllib.parse import urlparse

from k8s_gpu_device_plugin_tpu.serving.supervisor import RollingBudget


def parse_retry_after(raw, *, default: float = 1.0,
                      max_s: float = 3600.0) -> float:
    """``Retry-After`` header value -> seconds to wait.

    RFC 9110 allows BOTH shapes — delta-seconds (``"30"``) and an
    HTTP-date (``"Tue, 04 Aug 2026 17:00:00 GMT"``); a proxy in front
    of a replica may well rewrite one into the other. Garbage (or a
    date in the past) falls back to ``default`` instead of raising —
    a malformed header from an overloaded backend must slow the client
    down, not crash it. The result is clamped to [0, ``max_s``]: a
    backend asking for a year must not wedge a retry loop."""
    if raw is None:
        return float(default)
    s = str(raw).strip()
    if not s:
        return float(default)
    import math

    try:
        secs = float(s)
    except ValueError:
        import datetime

        try:
            when = email.utils.parsedate_to_datetime(s)
        except (TypeError, ValueError):
            return float(default)
        if when is None:
            return float(default)
        if when.tzinfo is None:
            # RFC 5322 dates without a zone are rare but parseable;
            # treat them as UTC like every HTTP implementation does
            when = when.replace(tzinfo=datetime.timezone.utc)
        secs = (
            when - datetime.datetime.now(datetime.timezone.utc)
        ).total_seconds()
        if secs < 0:
            return float(default)  # already elapsed: retry now-ish
    if not math.isfinite(secs) or secs < 0:
        # NaN/inf are garbage too: NaN slips through < comparisons and
        # min(), then poisons whatever arithmetic consumes the wait
        return float(default)
    return min(float(secs), float(max_s))


def _digest(data: bytes) -> int:
    """Stable 64-bit hash (blake2b): Python's ``hash()`` is salted per
    process, which would re-deal every tenant's cache home on restart."""
    return int.from_bytes(
        hashlib.blake2b(data, digest_size=8).digest(), "big"
    )


def affinity_key(source, buckets: tuple[int, ...]) -> bytes | None:
    """Request -> routing key bytes (None = no affinity; balance only).

    ``source`` is whatever prefix-bearing field the surface carries:
    a token-id list (native ``prompt`` / OpenAI id-list prompts), a
    string (text prompts — byte length stands in for token length), or
    any JSON-serializable structure (chat ``messages``). The key is the
    prefix up to the largest ``buckets`` boundary the sequence reaches —
    the prefix cache's promotion ladder — so requests sharing a
    cacheable prefix share a key, and divergence past the last boundary
    (uncacheable) does not scatter them."""
    if source is None:
        return None
    if isinstance(source, (list, tuple)) and source and all(
        isinstance(t, int) and not isinstance(t, bool) for t in source
    ):
        n = len(source)
        cut = max((b for b in buckets if b <= n), default=n)
        return ",".join(str(t) for t in source[:cut]).encode()
    if isinstance(source, str):
        if not source:
            return None
        raw = source.encode()
        cut = max((b for b in buckets if b <= len(raw)), default=len(raw))
        return raw[:cut]
    try:
        raw = json.dumps(source, sort_keys=True).encode()
    except (TypeError, ValueError):
        return None
    cut = max((b for b in buckets if b <= len(raw)), default=len(raw))
    return raw[:cut]


def poll_phase(rid: str, interval_s: float) -> float:
    """Deterministic per-replica health-poll phase offset in
    ``[0, interval_s)``. An N-replica fleet polled on one shared timer
    fires N probes in the same instant every ``--healthIntervalS`` tick
    — a thundering herd the replicas all pay together. Hashing the
    replica id (stable blake2b, like the ring) spreads the probes
    across the interval identically on every router restart, so
    dashboards comparing probe timestamps across restarts stay
    comparable."""
    if interval_s <= 0:
        return 0.0
    return (_digest(f"poll#{rid}".encode()) % 9973) / 9973.0 * interval_s


class FleetRestartBudget:
    """The fleet tier's twin of the engine supervisor's restart budget
    (one :class:`~...serving.supervisor.RollingBudget` underneath):
    ``max_restarts`` replica-death recoveries per rolling ``window_s``.

    The unit is a replica DEATH, not a stream: one dead replica with N
    in-flight streams charges ONE budget event — every stream of that
    death resumes (or none does). ``charge(rep)`` keys on the replica's
    death epoch (bumped on revival), so concurrent streams dying from
    the same death share the charge, while a flapping replica burns one
    unit per death. ``max_restarts=0`` disables cross-replica resume —
    streams then end with the structured error frame, the same
    degrade-loudly stance as the supervisor's budget-0 mode."""

    def __init__(self, max_restarts: int = 3, window_s: float = 300.0):
        self._budget = RollingBudget(max_restarts, window_s)
        self.max_restarts = self._budget.max_events
        self.window_s = self._budget.window_s
        self._charged: set[tuple[str, int]] = set()
        self.charged_total = 0

    def charge(self, rep: Replica) -> bool:
        """True iff resuming streams of this replica death is within
        budget (charging it on first sight of the (replica, epoch))."""
        key = (rep.rid, rep.epoch)
        if key in self._charged:
            return True
        if not self._budget.allow():
            return False
        self._budget.record()
        # one live epoch per replica: drop the stale keys so the set
        # stays bounded by fleet size
        self._charged = {k for k in self._charged if k[0] != rep.rid}
        self._charged.add(key)
        self.charged_total += 1
        return True

    def stats(self) -> dict:
        return {
            "max_restarts": self.max_restarts,
            "window_s": self.window_s,
            "window_used": self._budget.used(),
            "charged_total": self.charged_total,
        }


class HashRing:
    """Consistent hashing with virtual nodes. ``candidates(key)`` walks
    the ring from the key's point and yields each distinct replica id
    once — index 0 is the key's HOME (where its cache lives); the rest
    are the failover/spill order, stable under membership changes in
    the usual consistent-hashing way (adding a replica moves ~1/N of
    the keyspace, not all of it)."""

    def __init__(self, ids: list[str], vnodes: int = 64):
        self._points: list[int] = []
        self._owner: dict[int, str] = {}
        self.ids = list(ids)
        for rid in ids:
            for v in range(vnodes):
                p = _digest(f"{rid}#{v}".encode())
                # a full 64-bit collision across ids is ~impossible;
                # last-writer-wins keeps construction deterministic
                self._owner[p] = rid
                self._points.append(p)
        self._points.sort()

    def candidates(self, key: bytes) -> list[str]:
        if not self._points:
            return []
        h = _digest(key)
        i = bisect.bisect_right(self._points, h)
        seen: list[str] = []
        for j in range(len(self._points)):
            rid = self._owner[self._points[(i + j) % len(self._points)]]
            if rid not in seen:
                seen.append(rid)
                if len(seen) == len(self.ids):
                    break
        return seen


class Replica:
    """One backend's registry entry (event-loop state, router-owned)."""

    __slots__ = (
        "rid", "url", "draining", "alive", "consecutive_failures",
        "health", "health_t", "inflight", "relayed", "cooldown_until",
        "reported_id", "spare", "epoch", "role",
    )

    def __init__(self, rid: str, url: str):
        self.rid = rid
        self.url = url.rstrip("/")
        self.draining = False
        self.alive = True          # optimistic until dead_after failures
        self.consecutive_failures = 0
        self.health: dict | None = None   # last /v1/health payload
        self.health_t = 0.0
        self.inflight = 0          # router-side: requests being relayed
        self.relayed = 0           # completed relays (any outcome)
        self.cooldown_until = 0.0  # honor a 429's Retry-After
        self.reported_id: str | None = None  # replica_id from /v1/health
        # warm-spare membership: a spare is registered and health-polled
        # but NOT on the ring and never routed — it waits to be promoted
        # when an active replica dies (a demoted ex-active that revives
        # becomes a spare: its ring slot is taken)
        self.spare = False
        # death-generation counter: bumps every time a dead replica
        # revives. The fleet restart budget charges ONE unit per
        # (replica, epoch) — one replica death with N in-flight streams
        # is one fleet event, not N
        self.epoch = 0
        # disaggregated prefill/decode specialization (--roles):
        # "prefill" replicas take long-prompt prefill legs, "decode"
        # replicas take short prompts and transferred continuations,
        # "any" (the default) serves both — an unroled fleet routes
        # byte-identically to before roles existed
        self.role = "any"

    def routable(self, now: float) -> bool:
        return (
            self.alive and not self.draining and not self.spare
            and now >= self.cooldown_until
        )


def _id_from_url(url: str) -> str:
    p = urlparse(url if "//" in url else f"http://{url}")
    host = p.hostname or url
    return f"{host}:{p.port}" if p.port else host


class FleetRegistry:
    """The replica set + liveness bookkeeping + the aggregate snapshot."""

    def __init__(self, replicas: list[Replica], dead_after: int = 3):
        if not replicas:
            raise ValueError("a fleet needs at least one replica")
        ids = [r.rid for r in replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids: {ids}")
        # the health poller and proxy-failure paths mutate the replica
        # map's entries; handlers read it ONLY through the registry's
        # own snapshot methods (ReplicaRouter.fleet_stats is the one
        # health accessor) — the same ownership discipline the engine-
        # side *_stats() snapshots follow, graftlint-pinned
        self._replicas: dict[str, Replica] = {  # owner: engine
            r.rid: r for r in replicas
        }
        self.dead_after = int(dead_after)

    @classmethod
    def from_spec(cls, spec: str, dead_after: int = 3) -> "FleetRegistry":
        """``--replicas`` value -> registry. Entries are
        ``id=http://host:port`` or bare URLs (id defaults to the URL's
        host:port — matching the replica's own ``--replicaId`` default
        when replicas are addressed by hostname; fleets addressed by
        IP/service DNS should name ids explicitly on both sides. The
        health-reported id lands in ``reported_id`` either way, so a
        mismatch shows on /fleet/health instead of hiding)."""
        reps = []
        for entry in (spec or "").split(","):
            entry = entry.strip()
            if not entry:
                continue
            if "=" in entry and not entry.split("=", 1)[0].startswith("http"):
                rid, url = entry.split("=", 1)
                rid = rid.strip()
            else:
                url, rid = entry, _id_from_url(entry)
            url = url.strip()
            if not rid or not url:
                raise ValueError(f"--replicas entry {entry!r}: "
                                 "expected [id=]http://host:port")
            if "://" not in url:
                # a scheme-less 'host:port' would raise InvalidURL on
                # every request — a silently permanently-dead replica
                url = f"http://{url}"
            reps.append(Replica(rid, url))
        return cls(reps, dead_after=dead_after)

    # --- roles (disaggregated prefill/decode) ----------------------------

    REPLICA_ROLES = ("prefill", "decode", "any")

    def assign_roles(self, spec: str) -> None:
        """Apply a ``--roles`` spec: whitespace/semicolon-separated
        ``role=id,id`` groups, e.g. ``prefill=r0 decode=r1,r2``.
        Unlisted replicas keep role ``"any"`` (they serve both sides).
        Unknown roles and unknown replica ids are refused — a typo must
        not silently leave a fleet colocated."""
        for group in (spec or "").replace(";", " ").split():
            role, _, ids = group.partition("=")
            role = role.strip()
            if role not in ("prefill", "decode"):
                raise ValueError(
                    f"--roles group {group!r}: unknown role {role!r} "
                    "(expected prefill=... or decode=...; unlisted "
                    "replicas default to 'any')"
                )
            for rid in (r.strip() for r in ids.split(",")):
                if not rid:
                    continue
                rep = self._replicas.get(rid)
                if rep is None:
                    raise ValueError(
                        f"--roles names unknown replica {rid!r}; "
                        f"registered: {self.ids()}"
                    )
                rep.role = role

    def roles_configured(self) -> bool:
        """True when any replica is specialized — the gate every
        disaggregation code path sits behind (an unroled fleet must
        behave byte-identically to a build without roles)."""
        return any(r.role != "any" for r in self._replicas.values())

    def role_capable(self, role: str) -> "list[Replica]":
        """Replicas that can serve ``role`` work: exact matches plus
        the unspecialized ``"any"`` generalists."""
        return [
            r for r in self._replicas.values()
            if r.role == role or r.role == "any"
        ]

    def removal_empties_role(self, rep: Replica) -> "str | None":
        """Would taking ``rep`` out of service leave a configured role
        unservable? Returns the actionable refusal message (for the
        drain/promote surfaces), or None when the swap is safe. A
        specialized replica is covered by its exact peers and by
        ``"any"`` generalists; an ``"any"`` replica may itself be the
        last cover for BOTH specialized roles."""
        if not self.roles_configured():
            return None
        covered = ("prefill", "decode") if rep.role == "any" \
            else (rep.role,)
        for role in covered:
            if not any(
                r is not rep and not r.spare and r.alive and not r.draining
                for r in self.role_capable(role)
            ):
                return (
                    f"replica {rep.rid!r} (role {rep.role!r}) is the "
                    f"last in-service cover for the {role!r} role; "
                    "undrain or add a replica with that role (or "
                    "'any') first"
                )
        return None

    def get(self, rid: str) -> Replica | None:
        return self._replicas.get(rid)

    def all(self) -> list[Replica]:
        return list(self._replicas.values())

    def ids(self) -> list[str]:
        return list(self._replicas)

    # --- warm spares ------------------------------------------------------

    def mark_spares(self, n: int) -> None:
        """Flag the LAST ``n`` registered replicas as warm spares
        (registered, health-polled, unrouted until promoted). The tail
        convention matches how an operator writes ``--replicas``: the
        serving set first, the standbys after."""
        reps = list(self._replicas.values())
        if not (0 <= n < len(reps)):
            raise ValueError(
                f"warm_spares must leave at least one active replica: "
                f"got {n} spares over {len(reps)} replicas"
            )
        for rep in reps[len(reps) - n:]:
            rep.spare = True

    def active(self) -> list[Replica]:
        """The ring membership: every non-spare replica (dead ones
        included — the ring is identity, liveness is routing)."""
        return [r for r in self._replicas.values() if not r.spare]

    def spares(self) -> list[Replica]:
        return [r for r in self._replicas.values() if r.spare]

    def promote_spare(self, dead: Replica) -> Replica | None:
        """Swap a dead active replica for a live warm spare: the spare
        joins the ring membership (the caller rebuilds the ring —
        affinity keys remap in the usual consistent-hashing way), the
        dead one becomes a spare so a later revival re-enters the pool
        as a standby instead of double-claiming a ring slot. Returns
        the promoted replica, or None when no live spare is idle.

        Role-aware (disaggregated fleets): the spare must be able to
        cover the dead replica's role — its exact role or ``"any"`` —
        and an ``"any"`` spare ADOPTS the dead replica's specialization
        so the swap never leaves a role empty; a spare specialized the
        other way is skipped (refusing the role-emptying swap)."""
        spare = next(
            (r for r in self.spares()
             if r.alive and not r.draining
             and r.role in ("any", dead.role)),
            None,
        )
        if spare is None:
            return None
        if spare.role == "any" and dead.role != "any":
            spare.role = dead.role
        spare.spare = False
        dead.spare = True
        return spare

    # --- liveness (fed by the health poller AND proxy failures) ---------

    def note_success(self, rep: Replica, health: dict | None = None) -> None:
        if not rep.alive or rep.consecutive_failures:
            # recovery from ANY observed failure — full death or a flap
            # that never reached dead_after — closes that death epoch:
            # the next failure is a NEW fleet event for the restart
            # budget (streams dying from one crash see no success in
            # between, so they still share one charge)
            rep.epoch += 1
        rep.consecutive_failures = 0
        rep.alive = True
        if health is not None:
            rep.health = health
            rep.health_t = time.monotonic()
            rep.reported_id = health.get("replica_id", rep.reported_id)

    def note_failure(self, rep: Replica) -> None:
        rep.consecutive_failures += 1
        if rep.consecutive_failures >= self.dead_after:
            rep.alive = False

    # --- views -----------------------------------------------------------

    def any_draining(self) -> bool:
        return any(r.draining for r in self._replicas.values())

    def snapshot(self) -> dict:
        """The ``GET /fleet/health`` aggregate: per-replica state plus
        fleet-level tallies (plain copies; everything is loop-owned)."""
        now = time.monotonic()
        reps = {}
        for r in self._replicas.values():
            h = r.health or {}
            reps[r.rid] = {
                "url": r.url,
                "alive": r.alive,
                "spare": r.spare,
                "role": r.role,
                "draining": r.draining,
                "inflight": r.inflight,
                "relayed": r.relayed,
                "consecutive_failures": r.consecutive_failures,
                "cooldown_s": round(max(0.0, r.cooldown_until - now), 3),
                "reported_id": r.reported_id,
                "health_age_s": (
                    round(now - r.health_t, 3) if r.health_t else None
                ),
                # the balancing-relevant slice of the replica's own
                # health (queue depth, slot occupancy, kv pool pressure,
                # scheduler rejections) — dashboards get the digest
                # without a second scrape fan-out
                "queued": h.get("queued"),
                "active": h.get("active"),
                "prefilling": h.get("prefilling"),
                "uptime_s": h.get("uptime_s"),
                "kv": h.get("kv"),
                "sched_rejections": (h.get("sched") or {}).get("rejections"),
            }
        live = [r for r in self._replicas.values() if r.alive]
        # per-role membership + in-flight (disaggregated fleets): "any"
        # rolls up separately so dashboards can tell generalist slack
        # from specialized capacity; an unroled fleet reads all-"any"
        roles: dict[str, dict] = {}
        for r in self._replicas.values():
            agg = roles.setdefault(
                r.role, {"replicas": 0, "live": 0, "inflight": 0}
            )
            agg["replicas"] += 1
            agg["live"] += 1 if r.alive else 0
            agg["inflight"] += r.inflight
        return {
            "replicas": reps,
            "total": len(self._replicas),
            "live": len(live),
            "spares": len(self.spares()),
            "roles": roles,
            "draining": sum(
                1 for r in self._replicas.values() if r.draining
            ),
            "inflight": sum(r.inflight for r in self._replicas.values()),
        }
