"""Tokenizer seam for the inference server: text in/out.

The serving API is token-ids at its core (the engine never sees text);
this seam makes the service deployable to clients that speak text. Any
object with ``encode(str) -> list[int]`` and ``decode(list[int]) -> str``
plugs in:

- :class:`HFTokenizer` wraps a HuggingFace tokenizer loaded from a LOCAL
  directory (a serving pod must not download tokenizers at startup; this
  environment has no egress either). Optional dependency — imported only
  when used.
- :class:`ByteTokenizer` is the dependency-free fallback: UTF-8 bytes as
  ids. Exact round-trip for any text, works with any model whose vocab
  is >= 256 — the smoke/load-testing companion to the random-weights
  server mode.

No reference analogue: the reference is a device-plugin daemon
(/root/reference/README.md:1-6); tokenization belongs to the serving
workload surface this framework adds on top.
"""

from __future__ import annotations

from typing import Protocol, runtime_checkable


@runtime_checkable
class TokenizerSeam(Protocol):
    def encode(self, text: str) -> list[int]: ...

    def decode(self, ids: list[int]) -> str: ...


class ByteTokenizer:
    """UTF-8 bytes as token ids (vocab 256). Lossless round-trip for ids
    the tokenizer produced itself; ids >= 256 (a model sampling outside
    the byte range — random-weights smoke mode does this constantly)
    decode as U+FFFD REPLACEMENT CHARACTER, one per id, rather than being
    silently clamped onto a real byte."""

    vocab_size = 256
    eos_id: int | None = None

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    # byte-level: no special tokens, so stop-string encoding is identical
    encode_plain = encode

    def decode(self, ids: list[int]) -> str:
        out: list[str] = []
        run: list[int] = []  # contiguous valid bytes, decoded together
        for i in ids:
            if 0 <= int(i) < 256:
                run.append(int(i))
                continue
            out.append(bytes(run).decode("utf-8", errors="replace"))
            run = []
            out.append("�")
        out.append(bytes(run).decode("utf-8", errors="replace"))
        return "".join(out)


class HFTokenizer:
    """HuggingFace tokenizer from a local path (transformers is baked in;
    the path must already contain tokenizer files — no hub download)."""

    def __init__(self, path: str) -> None:
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.eos_id: int | None = self._tok.eos_token_id

    def encode(self, text: str) -> list[int]:
        return list(self._tok.encode(text, add_special_tokens=True))

    def encode_plain(self, text: str) -> list[int]:
        """No special tokens: for stop strings, which must match a run of
        GENERATED output — a prepended BOS would make the stop sequence
        unmatchable and silently never fire."""
        return list(self._tok.encode(text, add_special_tokens=False))

    def decode(self, ids: list[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)


def load_tokenizer(spec: str) -> TokenizerSeam | None:
    """CLI knob: "" -> None (token-id API only), "byte" -> ByteTokenizer,
    anything else -> local HF tokenizer directory."""
    if not spec:
        return None
    if spec == "byte":
        return ByteTokenizer()
    return HFTokenizer(spec)


def encode_stop_strings(tokenizer, strings, field: str = "stop") -> list:
    """Stop strings -> token-id lists, shared by the native and OpenAI
    handlers so the encoding semantics (no special tokens; loud failure
    when an entry normalizes away) can never drift between them.

    Caveat carried from the native API: standalone encoding can differ
    from in-context BPE merges — exact for byte-level tokenizers,
    best-effort across subword merge boundaries.
    """
    if tokenizer is None:
        raise ValueError(f"{field} requires a tokenizer on this server")
    if not isinstance(strings, list) or not all(
        isinstance(s, str) and s for s in strings
    ):
        raise ValueError(f"{field} must be a list of non-empty strings")
    enc = getattr(tokenizer, "encode_plain", tokenizer.encode)
    out: list[list[int]] = []
    for s in strings:
        ids = enc(s)
        if not ids:
            # silently dropping it would leave the client believing the
            # stop is armed
            raise ValueError(f"{field} entry {s!r} encodes to no tokens")
        out.append(list(ids))
    return out


def trim_stop_suffix(tokens: list, stop: list) -> list:
    """Drop a matched stop sequence from the end of ``tokens`` (OpenAI
    semantics: returned text never includes the stop sequence; the native
    API keeps it, like EOS).

    The SHORTEST matching suffix wins, not the client's list order: the
    engine halts on the first suffix that completes, so with
    stop=["ab", "b"] and output "...a b" the engine fired on "b" — a
    client-order trim would also drop the legitimately generated "a"."""
    best: int | None = None
    for st in stop:
        if len(st) <= len(tokens) and list(tokens[-len(st):]) == list(st):
            if best is None or len(st) < best:
                best = len(st)
    return list(tokens[:-best]) if best is not None else list(tokens)
