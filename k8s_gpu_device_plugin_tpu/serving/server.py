"""Inference HTTP server: the continuous batcher as a deployable service.

The control-plane daemon (server/server.py) hands pods their chips; this
is what runs INSIDE such a pod to serve a model — the serving analogue
of the trainer CLI. One background thread drives the ContinuousBatcher
step loop (device work never blocks the event loop); asyncio handlers
submit requests and read per-request token queues bridged with
``loop.call_soon_threadsafe``.

API (JSON over HTTP, SSE for streaming):

- ``POST /v1/generate``  {"prompt": [ids...], "max_new": N,
  "stream": false, "n": 1, "stop": [[ids...], ...], "logprobs": false,
  "temperature": t, "top_k": k, "top_p": p, "repetition_penalty": r}
  — the four sampling knobs are per-request (any present builds a full
  Sampler; absent knobs default to greedy/off, not to the server's
  default sampler); unsupported with --draftPreset (speculative
  batching shares one sampler: 422).
  -> {"id", "tokens"} (plus "completions" when n > 1: independent
  samples decoded in parallel slots; plus "logprobs" — and
  "completions_logprobs" with n > 1 — when requested: raw-distribution
  log-probabilities aligned with the tokens) — or with
  ``"stream": true`` (n=1 only), a ``text/event-stream`` of
  ``data: {"token": t}`` lines (each also carrying "logprob" when
  requested), closing with ``data: {"done": true}``. Stop sequences
  retire a request when its output ends with any of them (tokens kept,
  like EOS).
  With a tokenizer configured (serving/tokenizer.py; CLI --tokenizer):
  ``"text"`` (a string) may replace ``"prompt"``; responses gain
  ``"text"`` (and ``"completions_text"`` with n > 1); the stream's
  closing event carries the full decoded ``"text"``; ``"stop_text"``
  (list of strings) adds encoded stop sequences (exact for byte-level
  tokenizers, best-effort across subword merge boundaries).
- ``GET /v1/health``     {"slots", "active", "prefilling", "queued"}
- ``GET /metrics``       Prometheus text (ServingMetrics +
  whatever else lives on the registry)
- ``POST /v1/completions``, ``POST /v1/chat/completions``,
  ``GET /v1/models`` — OpenAI-compatible façade over the same engine
  (serving/openai_api.py): existing OpenAI SDKs/clients point at this
  server unchanged.

Multi-LoRA: with ``--loraAdapters name=ckptdir,...`` the server stacks
the adapters (models/lora_serving.py) and every request picks one —
``"adapter": "name"`` here, or the OpenAI ``"model"`` field (the base
model's id or an adapter name; ``/v1/models`` lists all).

Automatic prefix caching (serving/prefix_cache.py; on by default):
prompts sharing a cached prefix — system prompts, multi-turn chat
histories — skip its re-prefill; the cache is a radix index over token
ids, LRU-evicted under ``--prefixCacheMB`` of HBM, promotion gated by
``--prefixCacheMinHits``, disabled by ``--prefixCacheOff``. Responses
report the reuse (``cached_tokens`` natively, OpenAI
``usage.prompt_tokens_details``), ``/v1/health`` carries live cache
stats, and token/logprob streams are bit-identical cache on or off.

Crash recovery (serving/supervisor.py; on by default): an engine-thread
exception no longer kills the replica — within ``--restartBudget`` per
rolling ``--restartWindowS`` the batcher is rebuilt in place, queued
requests replay in admission order and in-flight streams resume
bit-identically through the preemption fold; past the budget the
replica degrades to dead with a STRUCTURED error frame on every stream
(native SSE ``{"error": ...}`` event / OpenAI ``server_error``
envelope / 503 bodies), never a silent clean EOS. ``/v1/health``
carries a ``supervisor`` section, and ``--faults`` arms the seeded
fault-injection plane (serving/faults.py) that rehearses all of this.

Design notes: the engine thread is the batcher's sole owner, and
handlers never wait on device work — submissions ride a small locked
queue the engine drains between steps. The batcher's decode loop is
pipelined by default (``pipeline_depth=1``): each step dispatches the
next device step BEFORE reading the previous one back, so the host-side
token publishing this engine does per step overlaps the chip's compute
(``--pipelineDepth 0`` restores the synchronous loop; ``--traceSteps``
adds per-step decode_dispatch/decode_readback spans under ``--tracing``
to see the overlap). Shutdown drains nothing — serving pods are
stateless, kubelet restarts re-register via the plugin, matching the
daemon's stateless stance (SURVEY §5 checkpoint row).
"""

from __future__ import annotations

import asyncio
import json
import socket
import threading
import time

from aiohttp import web

from k8s_gpu_device_plugin_tpu.models.batching import (
    ContinuousBatcher,
    RequestTooLargeError,
)
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig
from k8s_gpu_device_plugin_tpu.models.sampling import Sampler
from k8s_gpu_device_plugin_tpu.serving.faults import FaultError
from k8s_gpu_device_plugin_tpu.serving.scheduler import (
    SchedulerOverloadError,
)
from k8s_gpu_device_plugin_tpu.serving.supervisor import (
    EngineSupervisor,
    StreamError,
)
from k8s_gpu_device_plugin_tpu.obs.trace import (
    TRACEPARENT_HEADER,
    attach,
    current_context,
    format_traceparent,
    get_tracer,
    parse_traceparent,
)
from k8s_gpu_device_plugin_tpu.utils.log import get_logger

log = get_logger()


class InferenceEngine:
    """Background thread around a ContinuousBatcher with per-request
    token streams. Thread-safe submit; asyncio-friendly consumption."""

    def __init__(
        self,
        params,
        cfg: LlamaConfig,
        n_slots: int = 8,
        max_len: int = 2048,
        sampler: Sampler | None = None,
        eos_id: int | None = None,
        chunked_prefill: int = 256,
        prompt_buckets: "tuple[int, ...] | None" = None,  # None = default
        metrics=None,
        batcher: ContinuousBatcher | None = None,
        adapters=None,  # lora_serving.AdapterSet | AdapterStore
        adapter_cache_mb: int = 0,  # >0 = gathered multi-LoRA with an
        # LRU HBM residency budget (lora_serving.AdapterStore); 0 with
        # an AdapterSet keeps unlimited residency (all adapters resident)
        pipeline_depth: int = 1,
        trace_steps: bool = False,
        prefix_cache=None,  # serving.prefix_cache.PrefixCache (or None)
        kv_layout: str | None = None,   # None = cfg.kv_layout
        kv_page_size: int | None = None,
        kv_pages: int = 0,
        prefill_reserve_chunks: int = 2,  # windowed admission tranche
        scheduler=None,  # serving.scheduler.Scheduler (None = plain FIFO)
        default_priority: int = 1,
        default_deadline_ms: int = 0,
        tp: int | None = None,  # None = take cfg.tp (1 = single chip)
        attribution=None,  # obs.attribution.RequestAttributor (or None)
        mfu=None,  # metrics.roofline.MfuAccumulator (or None)
        supervisor: "EngineSupervisor | None" = None,  # None = default budget
        faults=None,  # serving.faults.FaultPlane (or None = disarmed)
        devices=None,  # device.allocation.AllocatedDevices (or None)
    ):
        # ``batcher`` injects a pre-built engine (e.g. a
        # SpeculativeBatcher); the scheduling/stream logic is identical
        if batcher is not None and adapters is not None:
            raise ValueError(
                "pass adapters to the injected batcher's own constructor; "
                "silently ignoring them here would 404 every adapter request"
            )
        if batcher is not None and adapter_cache_mb:
            raise ValueError(
                "pass adapter_cache_mb to the injected batcher's own "
                "constructor; silently ignoring it here would hold every "
                "adapter resident while reporting an LRU budget"
            )
        if batcher is not None and prefix_cache is not None:
            raise ValueError(
                "pass the prefix cache to the injected batcher's own "
                "constructor; silently ignoring it here would serve every "
                "request cold"
            )
        if batcher is not None and (kv_layout is not None
                                    or kv_page_size is not None or kv_pages):
            raise ValueError(
                "pass the KV layout to the injected batcher's own "
                "constructor; silently ignoring it here would serve the "
                "dense layout while reporting paged flags"
            )
        if batcher is not None and prefill_reserve_chunks != 2:
            raise ValueError(
                "pass prefill_reserve_chunks to the injected batcher's "
                "own constructor; silently ignoring it here would "
                "reserve a different admission tranche than requested"
            )
        if batcher is not None and prompt_buckets is not None:
            raise ValueError(
                "pass prompt_buckets to the injected batcher's own "
                "constructor; silently ignoring them here would hash "
                "router affinity keys at boundaries the engine never "
                "promotes at"
            )
        if batcher is not None and scheduler is not None:
            raise ValueError(
                "pass the scheduler to the injected batcher's own "
                "constructor; silently ignoring it here would admit FIFO "
                "while reporting the requested policy"
            )
        if batcher is not None and tp not in (None, 1):
            raise ValueError(
                "pass tp to the injected batcher's own constructor; "
                "silently ignoring it here would serve single-chip "
                "while reporting a sharded mesh"
            )
        if batcher is not None and (attribution is not None
                                    or mfu is not None):
            raise ValueError(
                "pass attribution/mfu to the injected batcher's own "
                "constructor; silently ignoring them here would serve "
                "no timelines while reporting the layer enabled"
            )
        if batcher is not None and faults is not None:
            raise ValueError(
                "pass the fault plane to the injected batcher's own "
                "constructor; silently ignoring it here would leave "
                "every armed engine-side fault point disarmed"
            )
        if batcher is not None and devices is not None:
            raise ValueError(
                "pass devices to the injected batcher's own constructor; "
                "silently ignoring them here would attribute every "
                "request to no silicon while reporting chips allocated"
            )
        if batcher is not None and supervisor is not None:
            raise ValueError(
                "crash recovery requires the engine-built batcher: an "
                "injected one carries no rebuild recipe (and the "
                "speculative engine has no resume path for its draft "
                "cache)"
            )
        # request-edge SLO defaults: a request that names no tenant /
        # priority / deadline gets these (the "defaulted at the server
        # edge" contract — the batcher itself never invents a deadline)
        self._default_priority = int(default_priority)
        self._default_deadline_ms = int(default_deadline_ms)
        buckets_kw = (
            {} if prompt_buckets is None
            else {"prompt_buckets": tuple(prompt_buckets)}
        )
        if batcher is not None:
            self.cb = batcher
            self._make_batcher = None
            self.supervisor: "EngineSupervisor | None" = None
        else:
            # the construction recipe is CAPTURED so the supervisor can
            # rebuild a fresh batcher (new device state, new pools) after
            # an engine-thread crash — same metrics/scheduler/attribution
            # objects, whose ledgers live through the restart
            def make_batcher() -> ContinuousBatcher:
                return ContinuousBatcher(
                    params, cfg, n_slots=n_slots, max_len=max_len,
                    sampler=sampler, eos_id=eos_id,
                    chunked_prefill=min(chunked_prefill, max_len),
                    metrics=metrics, adapters=adapters,
                    adapter_cache_mb=adapter_cache_mb, **buckets_kw,
                    pipeline_depth=pipeline_depth, trace_steps=trace_steps,
                    prefix_cache=prefix_cache,
                    kv_layout=kv_layout, kv_page_size=kv_page_size,
                    kv_pages=kv_pages,
                    prefill_reserve_chunks=prefill_reserve_chunks,
                    scheduler=scheduler, tp=tp,
                    attribution=attribution, mfu=mfu, faults=faults,
                    devices=devices,
                )

            self.cb = make_batcher()
            self._make_batcher = make_batcher
            # crash recovery is ON by default (the default rolling
            # budget); EngineSupervisor(max_restarts=0) degrades every
            # crash to the dead state — with structured error frames,
            # never the old silent clean-EOS close
            self.supervisor = (
                supervisor if supervisor is not None else EngineSupervisor()
            )
        # The engine thread is the ONLY toucher of self.cb — a device
        # step can take long, and a shared lock would let a submit
        # handler block the event loop behind it. Submissions go through
        # a small locked queue the engine drains between steps; request-
        # side validation reuses the batcher's own rules pre-admission.
        self._lock = threading.Lock()       # guards _subq/_streams maps
        self._work = threading.Event()
        self._stop = threading.Event()
        self._dead = threading.Event()
        self._subq: list[
            tuple[int, list[int], int, tuple, "Sampler | None", int, tuple,
                  int | None, object, str, int, "int | None", tuple]
        ] = []  # (eid, prompt, max_new, stop, sampler, adapter, bias,
        #          seed, trace_parent, tenant, priority, deadline_ms,
        #          (resume_out, resume_logp))
        self._cancelq: list[int] = []  # eids to cancel, drained per step
        # KV-export ops (disaggregated prefill/decode): (eid, loop, fut)
        # drained per step; the engine thread snapshots pages + emitted
        # tokens and retires the request in one indivisible pass
        self._exportq: list[tuple[int, object, object]] = []
        self._streams: dict[int, tuple[asyncio.AbstractEventLoop, asyncio.Queue]] = {}
        self._published: dict[int, int] = {}   # eid -> tokens already pushed
        self._rid_to_eid: dict[int, int] = {}
        # eid -> per-request wrap-up facts (cached_tokens today), recorded
        # when the request retires and popped by the HTTP handler for the
        # response envelope; capped so streams that never pop (client
        # gone) age out instead of leaking
        self._finished_info: dict[int, dict] = {}
        self._next_eid = 0
        self._thread = threading.Thread(
            target=self._loop, name="inference-engine", daemon=True
        )
        self._thread.start()

    # --- request side (event loop thread) ---

    def submit(  # graftlint: cross-thread
        self, prompt: list[int], max_new: int,
        stop: list[list[int]] | None = None,
        sampler: Sampler | None = None,
        adapter: int = -1,
        logit_bias=None,
        seed: int | None = None,
        tenant: str | None = None,
        priority: int | None = None,
        deadline_ms: int | None = None,
        resume_out: list[int] | None = None,
        resume_logp: list[float] | None = None,
        kv_pages=None,
    ) -> tuple[int, asyncio.Queue]:
        """Register a request; returns (eid, queue of tokens then None).

        Validates EVERYTHING the batcher would (capacity, bucket fit in
        bucketed mode, adapter range) so admission on the engine thread
        can never raise — an admission error there would otherwise kill
        the loop and hang every stream. Scheduling identity defaults at
        THIS edge: tenant "default", the server's --defaultDeadlineMs,
        priority 1. Raises SchedulerOverloadError (-> HTTP 429) when the
        scheduler's queue cap is already full.

        ``resume_out``/``resume_logp`` resume a stream another
        incarnation (a dead replica) already partially served: the
        emitted tokens fold into the prompt through the preemption fold
        and — because they were already DELIVERED to the client by
        whoever relayed the dead stream — the published cursor starts
        past them, so this stream carries only the continuation (zero
        re-emitted tokens).

        ``kv_pages`` (a wire blob from another replica's
        ``/v1/kv/export``) upgrades the resume to a KV-page install:
        the folded prompt admits onto the transferred pages and only
        the finish chunk runs. Pool pressure at THIS edge raises
        SchedulerOverloadError (-> 429 kv_pool_pressure) instead of
        deferring: the caller is a router holding a live stream, and
        its re-prefill fallback beats queueing a blob behind a full
        pool (the engine-thread reservation still defers if a burst
        races past this approximate check)."""
        if self._dead.is_set():
            raise RuntimeError("inference engine is dead (see logs)")
        resume_out, resume_logp = self.cb.validate_resume(
            resume_out, resume_logp, max_new
        )
        # the batcher's own rule, over the folded prompt + what is LEFT
        # of the budget (the fold's row total is the original worst case)
        self.cb.validate(len(prompt) + len(resume_out),
                         max_new - len(resume_out))
        kv_wire = None
        if kv_pages is not None:
            kv_wire = self.cb.validate_kv_pages(
                kv_pages, len(prompt), len(resume_out)
            )
            need, free = self.cb.kv_install_headroom(
                len(prompt) + len(resume_out),
                max_new - len(resume_out),
            )
            if need > free:  # approximate cross-thread read
                raise SchedulerOverloadError(
                    f"KV transfer needs {need} pages, "
                    f"{free} free: install would defer "
                    "behind pool pressure — re-prefill elsewhere or "
                    "retry",
                    reason="kv_pool_pressure", retry_after=1,
                )
        self.cb.validate_adapter(adapter)
        logit_bias = self.cb.validate_bias(logit_bias)
        if priority is None:
            priority = self._default_priority
        if deadline_ms is None and self._default_deadline_ms:
            deadline_ms = self._default_deadline_ms
        tenant, priority, deadline_ms = self.cb.validate_sched(
            tenant, priority, deadline_ms
        )
        sched = getattr(self.cb, "scheduler", None)
        if sched is not None:
            # queue-cap gate on the REQUEST thread so overload answers
            # 429 immediately instead of queueing doomed work; atomic
            # len() reads only (the engine thread owns the queues). The
            # engine-thread check in cb.submit stays authoritative — a
            # race past this one is caught there and closes the stream.
            with self._lock:
                queued_local = len(self._subq)
            sched.check_capacity(len(self.cb.pending) + queued_local)
        if sampler is not None and not getattr(
            self.cb, "per_request_sampler", False
        ):
            raise ValueError(
                "per-request sampling is not supported by this engine "
                "(speculative batching shares one sampler)"
            )
        if logit_bias and not getattr(self.cb, "per_request_bias", False):
            raise ValueError(
                "logit_bias is not supported by this engine "
                "(speculative batching threads no bias planes)"
            )
        seed = self.cb.validate_seed(seed)
        if seed is not None and not getattr(
            self.cb, "per_request_seed", False
        ):
            raise ValueError(
                "per-request seeds are not supported by this engine"
            )
        # Thread-hop propagation: the batcher admits on the engine thread,
        # where THIS task's contextvars are invisible — capture the active
        # span here (the HTTP middleware's) and re-attach it around
        # cb.submit so the request's span tree parents under it.
        trace_parent = current_context() if get_tracer().enabled else None
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()
        with self._lock:
            # Re-check death while holding the lock: the dead-path stream
            # flush also runs under it after setting _dead, so either the
            # flush already ran (we see _dead and bail before anyone waits
            # on q) or it runs after us and sees this stream.
            if self._dead.is_set():
                raise RuntimeError("inference engine is dead (see logs)")
            eid = self._next_eid
            self._next_eid += 1
            self._subq.append(
                (eid, list(prompt), max_new, tuple(stop or ()), sampler,
                 adapter, logit_bias, seed, trace_parent,
                 tenant, priority, deadline_ms,
                 (resume_out, resume_logp, kv_wire))
            )
            self._streams[eid] = (loop, q)
            # the published cursor starts past the resumed tokens: they
            # were delivered by the dead incarnation's relay — pushing
            # them again would duplicate what the client already has
            self._published[eid] = len(resume_out)
        self._work.set()
        return eid, q

    def cancel(self, eid: int) -> None:  # graftlint: cross-thread
        """Thread-safe: queue a cancellation; the engine thread applies it
        between steps (a disconnected client must free its slot instead of
        decoding to the token budget). Unknown/finished eids are no-ops."""
        with self._lock:
            self._cancelq.append(eid)
        self._work.set()

    def pop_request_info(self, eid: int) -> dict:  # graftlint: cross-thread
        """Per-request wrap-up facts recorded at retirement (empty dict
        for unknown/aged-out eids). Pop-once: the handler that owns the
        stream consumes it."""
        with self._lock:
            return self._finished_info.pop(eid, {})

    def stats(self) -> dict:  # graftlint: cross-thread
        # approximate cross-thread reads (GIL-consistent lengths)
        with self._lock:
            queued_local = len(self._subq)
        out = {
            "slots": self.cb.n_slots,
            "active": len(self.cb.running),
            "prefilling": len(self.cb.prefilling),
            "queued": len(self.cb.pending) + queued_local,
            "alive": not self._dead.is_set(),
        }
        pc = getattr(self.cb, "prefix_cache", None)
        if pc is not None:
            out["prefix_cache"] = pc.stats.as_dict()
        kv_stats = getattr(self.cb, "kv_stats", None)
        if kv_stats is not None:
            # KV residency (both layouts; paged adds pool occupancy +
            # fragmentation; speculative batchers fold the draft cache
            # in) — mirrored by the OpenAI façade's health
            out["kv"] = kv_stats()
        attn_stats = getattr(self.cb, "attn_backend_stats", None)
        if attn_stats is not None:
            # which attention backend each serving mode routes through
            # (pallas kernel vs xla gather) and the gate that decided
            # it — the static startup plan, so no cross-thread hazard
            out["decode_attn"] = attn_stats()
        spec_stats = getattr(self.cb, "spec_stats", None)
        if spec_stats is not None:
            # speculative acceptance (rounds, drafted/accepted tokens,
            # acceptance rate) — the production view of gamma's health
            out["spec"] = spec_stats()
        adapter_stats = getattr(self.cb, "adapter_stats", None)
        if adapter_stats is not None and getattr(self.cb, "n_adapters", 0):
            # multi-LoRA residency view (registered vs HBM-resident,
            # gathers, deferrals, upload p99) — snapshot-built by the
            # batcher/store, same contract as kv_stats
            out["adapters"] = adapter_stats()
        sched = getattr(self.cb, "scheduler", None)
        if sched is not None:
            # queue + per-tenant SLO view (policy, quota levels,
            # preemptions, deadline misses, goodput) — snapshotted by
            # the scheduler, same contract as kv_stats
            out["sched"] = sched.sched_stats()
        mfu_stats = getattr(self.cb, "mfu_stats", None)
        if mfu_stats is not None:
            # live MFU/roofline view (metrics/roofline.py): generation
            # peaks, windowed mfu/bandwidth %, per-tenant goodput-per-
            # TFLOP — snapshot-built, same contract as kv_stats
            mfu = mfu_stats()
            if mfu is not None:
                out["mfu"] = mfu
        attr_stats = getattr(self.cb, "attribution_stats", None)
        if attr_stats is not None:
            attr = attr_stats()
            if attr is not None:
                # counts only on health; the timelines themselves live
                # on /debug/requests and /debug/slow
                out["attribution"] = attr
        if self.supervisor is not None:
            # crash-recovery view (state, restart budget, replay/resume
            # tallies, last crash) — the supervisor's own snapshot
            # method, same thread contract as kv_stats/sched_stats
            out["supervisor"] = self.supervisor.stats()
        devices = getattr(self.cb, "devices", None)
        if devices is not None:
            # the physical chips under this engine (device/allocation.py):
            # allocation id + chip indices, frozen at startup — the
            # request->chip attribution join key on /v1/health
            out["devices"] = devices.as_dict()
        return out

    def shutdown(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self._work.set()
        self._thread.join(timeout)

    # --- engine side (worker thread) ---

    def _admit_submissions(self) -> None:
        with self._lock:
            batch, self._subq = self._subq, []
        for (eid, prompt, max_new, stop, sampler, adapter, bias, seed,
             trace_parent, tenant, priority, deadline_ms, resume) in batch:
            try:
                with attach(trace_parent):
                    rid = self.cb.submit(
                        prompt, max_new=max_new,
                        stop=[list(st) for st in stop],
                        sampler=sampler, adapter=adapter, logit_bias=bias,
                        seed=seed, tenant=tenant, priority=priority,
                        deadline_ms=deadline_ms,
                        resume_out=resume[0], resume_logp=resume[1],
                        kv_pages=resume[2],
                    )
            except SchedulerOverloadError as e:
                # the request-thread capacity gate raced a burst: close
                # this stream with the rejection recorded so its handler
                # answers 429 (an uncaught raise here would kill the
                # engine loop and hang every stream)
                sched = getattr(self.cb, "scheduler", None)
                if sched is not None:
                    sched.count_sync_rejection(self.cb)
                with self._lock:
                    stream = self._streams.pop(eid, None)
                    self._published.pop(eid, None)
                    self._finished_info[eid] = {
                        "reject_reason": e.reason,
                        "retry_after": e.retry_after,
                    }
                if stream is not None:
                    loop, q = stream
                    loop.call_soon_threadsafe(q.put_nowait, None)
                continue
            except Exception as e:  # noqa: BLE001 - one bad admission
                # must kill neither the engine loop nor the other
                # streams: close THIS stream with a structured error
                # frame (the request-thread validation makes this path
                # unreachable for well-formed requests, so anything
                # here is a real defect worth the loud log)
                log.exception("admission failed for eid=%s", eid)
                with self._lock:
                    stream = self._streams.pop(eid, None)
                    self._published.pop(eid, None)
                if stream is not None:
                    loop, q = stream
                    loop.call_soon_threadsafe(
                        q.put_nowait,
                        StreamError("submit_failed",
                                    f"admission failed: {e}"),
                    )
                    loop.call_soon_threadsafe(q.put_nowait, None)
                continue
            self._rid_to_eid[rid] = eid

    def _apply_cancellations(self) -> None:
        """Runs after admission: a cancel targeting an eid still in the
        submit queue is removed there; an admitted one goes through
        ``cb.cancel`` and the normal done-request publish (which closes
        its stream). Never-admitted streams are closed here."""
        with self._lock:
            cancels, self._cancelq = self._cancelq, []
        if not cancels:
            return
        for eid in cancels:
            with self._lock:
                before = len(self._subq)
                self._subq = [s for s in self._subq if s[0] != eid]
                dropped = len(self._subq) < before
                stream = self._streams.pop(eid, None) if dropped else None
                if dropped:
                    self._published.pop(eid, None)
            if dropped:
                if stream is not None:
                    loop, q = stream
                    loop.call_soon_threadsafe(q.put_nowait, None)
                continue
            rid = next(
                (r for r, e in self._rid_to_eid.items() if e == eid), None
            )
            if rid is not None and self.cb.cancel(rid):
                # flush now: the batcher may have just gone idle, in which
                # case the step-loop publish would never run again
                self._publish()

    async def export_kv(self, eid: int, timeout: float = 30.0) -> dict:
        """Snapshot a running request's KV pages and retire it, in one
        engine-thread pass (disaggregated prefill/decode: the router
        calls this on the prefill replica, then resubmits the result to
        a decode replica as ``resume_out``+``kv_pages``). Atomicity
        matters: export, cancel, and the final publish happen
        back-to-back on the engine thread, so the stream cannot emit a
        token AFTER the snapshot was taken — the returned ``resume_out``
        is exactly the tokens the stream delivered (or will deliver
        before its end-of-stream), never a prefix of them.

        Raises KeyError (unknown/finished eid), ValueError (not yet
        admitted or still prefilling — the caller should wait for the
        first token), RuntimeError (dense layout / dead engine), or
        asyncio.TimeoutError."""
        if self._dead.is_set():
            raise RuntimeError("inference engine is dead (see logs)")
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        with self._lock:
            self._exportq.append((eid, loop, fut))
        self._work.set()
        return await asyncio.wait_for(fut, timeout)

    def _apply_exports(self) -> None:
        """Engine thread: drain queued KV-export ops. Each op snapshots
        the request's pages + emitted tokens (flushing any in-flight
        pipelined decode first, inside export_kv_pages), cancels the
        request, and publishes — so the source stream closes having
        delivered a PREFIX of the returned ``resume_out`` (the flush can
        surface tokens the relay never read; the router synthesizes
        those frames from the export result, never from the stream)."""
        with self._lock:
            ops, self._exportq = self._exportq, []
        for eid, loop, fut in ops:
            try:
                rid = next(
                    (r for r, e in self._rid_to_eid.items() if e == eid),
                    None,
                )
                if rid is None:
                    with self._lock:
                        queued = any(s[0] == eid for s in self._subq)
                    if queued:
                        raise ValueError(
                            f"request {eid} has not been admitted yet: "
                            "wait for its first token before exporting"
                        )
                    raise KeyError(
                        f"unknown or finished request {eid}"
                    )
                blob, out, lps = self.cb.export_kv_pages(rid)
                self.cb.cancel(rid)
                self._publish()
                res = {
                    "kv_pages": blob,
                    "resume_out": out,
                    "resume_logprobs": lps,
                }
            except Exception as e:  # noqa: BLE001 - surfaced to caller
                err = e
                loop.call_soon_threadsafe(
                    lambda f=fut, x=err: f.done() or f.set_exception(x)
                )
                continue
            loop.call_soon_threadsafe(
                lambda f=fut, r=res: f.done() or f.set_result(r)
            )

    def _publish(self) -> None:
        """Push newly generated (token, logprob) pairs to their queues."""
        live = (
            list(self.cb.running.values())
            + list(self.cb.prefilling.values())
            + list(self.cb.pending)
        )
        for req in live:
            self._push(req.rid, req.out, req.out_logp)
        for rid, eid in list(self._rid_to_eid.items()):
            req = self.cb.done_requests.pop(rid, None)
            if req is not None:
                self._push(rid, req.out, req.out_logp)
                # pop done too: a long-running server must not retain
                # every request's token list forever
                self.cb.done.pop(rid, None)
                info = {"cached_tokens": req.cached_tokens}
                tl = getattr(req, "timeline", None)
                if tl is not None and tl.record is not None:
                    # the finalized attribution record (a plain dict,
                    # built at retirement on the engine thread): the
                    # HTTP handler exports it when the request opted in
                    info["timeline"] = tl.record
                if req.reject_reason is not None:
                    # scheduler rejection (pool-pressure deferral past
                    # the budget): the handler turns this into a 429
                    info["reject_reason"] = req.reject_reason
                    sched = getattr(self.cb, "scheduler", None)
                    info["retry_after"] = (
                        sched.retry_after_s() if sched is not None else 1
                    )
                with self._lock:
                    loop, q = self._streams.pop(eid)
                    self._published.pop(eid)
                    self._finished_info[eid] = info
                    while len(self._finished_info) > 4096:  # unpopped: aged out
                        self._finished_info.pop(
                            next(iter(self._finished_info))
                        )
                del self._rid_to_eid[rid]
                loop.call_soon_threadsafe(q.put_nowait, None)  # end-of-stream

    def _push(self, rid: int, out: list[int], logp: list[float]) -> None:
        eid = self._rid_to_eid.get(rid)
        if eid is None:
            return
        with self._lock:
            stream = self._streams.get(eid)
            seen = self._published.get(eid, 0)
        if stream is None:
            return
        loop, q = stream
        for tok, lp in zip(out[seen:], logp[seen:]):
            loop.call_soon_threadsafe(q.put_nowait, (int(tok), float(lp)))
        with self._lock:
            self._published[eid] = len(out)

    def _loop(self) -> None:
        """Crash boundary around the step loop: an engine-thread
        exception recovers IN PLACE through the supervisor (fresh
        batcher, queued work replayed in order, in-flight requests
        resumed bit-identically via the preemption fold) while the
        restart budget lasts; past it — or without a rebuild recipe —
        the engine degrades to the dead state, closing every stream
        with a structured error frame instead of a silent clean EOS."""
        while True:
            try:
                self._loop_inner()
                return  # clean shutdown (_stop set)
            except Exception as exc:  # noqa: BLE001 - the crash boundary
                log.exception("inference engine loop died")
                if self._stop.is_set():
                    # a crash racing shutdown(): the clean-exit path —
                    # rebuilding a whole batcher just to observe _stop
                    # would hold the joining thread through compiles
                    return
                sup = self.supervisor
                if sup is not None:
                    sup.on_crash(exc)
                if sup is None or self._make_batcher is None \
                        or not sup.allow_restart():
                    detail = (
                        " (restart budget exhausted)"
                        if sup is not None and sup.max_restarts else ""
                    )
                    self._die(
                        "engine_dead",
                        f"inference engine died{detail}: "
                        f"{type(exc).__name__}: {exc}",
                    )
                    return
                try:
                    sup.recover(self)
                except Exception:  # noqa: BLE001 - rebuild failed
                    log.exception(
                        "engine recovery failed; degrading to dead"
                    )
                    self._die(
                        "engine_dead",
                        "inference engine recovery failed (see logs)",
                    )
                    return

    def _loop_inner(self) -> None:
        was_busy = False
        while not self._stop.is_set():
            self._admit_submissions()
            self._apply_cancellations()
            self._apply_exports()
            busy = bool(
                self.cb.pending or self.cb.running or self.cb.prefilling
            )
            if busy:
                self.cb.step()
                self._publish()
            else:
                if was_busy:
                    # busy->idle transition: throughput gauge reads 0
                    # while idle, not the last busy window's value.
                    # getattr: metrics is duck-typed to the batcher
                    # hooks only; on_idle is optional.
                    on_idle = getattr(
                        getattr(self.cb, "metrics", None), "on_idle", None
                    )
                    if on_idle is not None:
                        on_idle()
                    # same busy->idle zeroing for the MFU window
                    mfu = getattr(self.cb, "mfu", None)
                    if mfu is not None:
                        mfu.on_idle()
                self._work.wait(timeout=0.05)
                self._work.clear()
            was_busy = busy

    def _die(self, code: str, message: str) -> None:
        """Degrade to the dead state: every open stream gets a
        structured :class:`StreamError` frame and then end-of-stream —
        a truncated stream must never read as a short completion
        (both HTTP surfaces translate the frame; pinned in tests)."""
        if self.supervisor is not None:
            self.supervisor.mark_dead()
        self._dead.set()
        with self._lock:
            streams, self._streams = self._streams, {}
            self._published.clear()
        err = StreamError(code, message)
        for loop, q in streams.values():
            loop.call_soon_threadsafe(q.put_nowait, err)
            loop.call_soon_threadsafe(q.put_nowait, None)


def _overload_response(message: str, reason: str,
                       retry_after: int) -> web.Response:
    """HTTP 429 for scheduler overload (queue full, deferral budget):
    a structured body + a Retry-After header, NOT the generic 4xx/5xx
    error path — clients must be able to tell 'back off and retry'
    from 'this request can never succeed'."""
    return web.json_response(
        {"error": message, "code": "overloaded", "reason": reason,
         "retry_after": int(retry_after)},
        status=429,
        headers={"Retry-After": str(int(retry_after))},
    )


def _parse_logit_bias(raw) -> dict | None:
    """JSON logit_bias ({"token_id": bias} — keys are strings on the
    wire, OpenAI-style) -> {int: float}; value bounds are the batcher's
    validate_bias rule. Shared by the native and OpenAI handlers."""
    if raw is None:
        return None
    if not isinstance(raw, dict):
        raise ValueError("logit_bias must be an object of token_id: bias")
    try:
        return {int(k): float(v) for k, v in raw.items()}
    except (TypeError, ValueError):
        raise ValueError(
            "logit_bias keys must be integer token ids and values numbers"
        ) from None


async def drain_queue(
    queue: asyncio.Queue,
) -> "tuple[list[int], list[float], StreamError | None]":
    """Collect one request's full (tokens, logprobs, error) off its
    stream queue (None = end-of-stream; a StreamError frame before it
    marks an abnormal close — engine death, exhausted restart budget).
    Shared by the native and OpenAI handlers, which turn a non-None
    error into a real error response instead of a silent truncation."""
    toks: list[int] = []
    lps: list[float] = []
    err: "StreamError | None" = None
    while True:
        item = await queue.get()
        if item is None:
            return toks, lps, err
        if isinstance(item, StreamError):
            err = item
            continue
        toks.append(item[0])
        lps.append(item[1])


class InferenceServer:
    """aiohttp app over an InferenceEngine (port 0 = ephemeral)."""

    def __init__(self, engine: InferenceEngine, host: str = "0.0.0.0",
                 port: int = 8000, registry=None, tokenizer=None,
                 embedder=None, scorer=None, replica_id: str = "",
                 faults=None):
        self.engine = engine
        # seeded fault injection (serving/faults.py): the health
        # handler's point — a live socket over a lying health surface,
        # what the router's poller hardening is pinned against
        self._flt_health = (
            faults.point("health.handler") if faults is not None else None
        )
        self.host = host
        self.port = port
        self.bound_port: int | None = None
        self.registry = registry
        # fleet identity (serving/fleet.py): a stable id the replica
        # router's registry and dashboards tell replicas apart by —
        # ``--replicaId`` pins it; empty defaults to hostname:port.
        # NOTE: that matches FleetRegistry.from_spec's bare-URL id only
        # when replicas are addressed BY hostname — fleets addressed by
        # IP/service DNS should pin --replicaId (the registry surfaces
        # the reported id either way, so a mismatch is visible, not
        # silent)
        self.replica_id = replica_id
        self._replica_label: str | None = None
        self._t_start = time.monotonic()
        # Optional serving/embeddings.Embedder: enables /v1/embeddings
        self.embedder = embedder
        # Optional serving/scoring.Scorer: enables completions
        # echo=true + max_tokens=0 prompt scoring (lm-eval loglikelihood)
        self.scorer = scorer
        # Optional text seam (serving/tokenizer.py): anything with
        # encode(str)->ids / decode(ids)->str. The engine itself stays
        # token-ids only; text is translated at the HTTP boundary.
        self.tokenizer = tokenizer
        self.tracer = get_tracer()
        # chip attribution (device/allocation.py): frozen at startup, so
        # the extra span attrs are a precomputed dict — {} costs the hot
        # path one empty **splat when no devices are known
        devices = getattr(engine.cb, "devices", None)
        self._device_attrs = (
            {"chips": devices.chips_label(),
             "allocation_id": devices.allocation_id}
            if devices is not None else {}
        )
        self.app = web.Application(middlewares=[self._trace_middleware])
        self.app.router.add_post("/v1/generate", self._generate)
        # disaggregated prefill/decode: snapshot a running request's KV
        # pages + emitted tokens and retire it (the router resubmits the
        # result to a decode replica as resume_out + kv_pages)
        self.app.router.add_post("/v1/kv/export/{rid}", self._kv_export)
        self.app.router.add_get("/v1/health", self._health)
        self.app.router.add_get("/debug/traces", self._debug_traces)
        self.app.router.add_get(
            "/debug/traces/{trace_id}", self._debug_trace_one
        )
        # per-request latency attribution (obs/attribution.py): recent
        # retired-request timelines, one by rid, and the tail-latency
        # flight recorder (step-level detail for threshold breachers)
        self.app.router.add_get("/debug/requests", self._debug_requests)
        self.app.router.add_get(
            "/debug/requests/{rid}", self._debug_request_one
        )
        self.app.router.add_get("/debug/slow", self._debug_slow)
        if registry is not None:
            self.app.router.add_get("/metrics", self._metrics)
        # OpenAI-compatible façade (serving/openai_api.py): /v1/completions,
        # /v1/chat/completions, /v1/models — same engine, translated I/O
        from k8s_gpu_device_plugin_tpu.serving.openai_api import (
            add_openai_routes,
        )

        add_openai_routes(self)

    @property
    def adapter_names(self) -> tuple:
        """Adapter name -> stacked index (multi-LoRA serving); both
        APIs resolve names here and submit indices. A LIVE read of the
        batcher's registry — dynamic registration (AdapterStore) must
        surface new names without a server restart; tombstoned slots
        render "" and resolve nowhere."""
        return tuple(getattr(self.engine.cb, "adapter_names", ()))

    def resolve_adapter(self, name) -> int:
        """Adapter name -> index; None/empty -> base (-1). Raises
        ValueError for unknown names (the request is malformed, not a
        capacity problem)."""
        if name in (None, ""):
            return -1
        if not isinstance(name, str):
            raise ValueError("adapter must be a string name")
        names = self.adapter_names
        try:
            return names.index(name)
        except ValueError:
            raise ValueError(
                f"unknown adapter {name!r}; serving: "
                f"{[n for n in names if n] or '(none)'}"
            ) from None

    def replica_label(self) -> str:
        """This replica's stable fleet identity (``--replicaId``, or
        hostname:port): the id /v1/health reports, the router's
        registry keys on, and — stamped on every ``serving_http`` span
        — the attribute the fleet trace stitcher assigns a span's
        whole subtree to a replica track by. Cached once the ephemeral
        port is bound (the middleware calls this per traced request;
        gethostname() per request would tax the hot path)."""
        if self._replica_label is not None:
            return self._replica_label
        label = self.replica_id or (
            f"{socket.gethostname()}:{self.bound_port or self.port}"
        )
        if self.replica_id or self.bound_port is not None:
            self._replica_label = label  # stable from here on
        return label

    @web.middleware
    async def _trace_middleware(self, request: web.Request, handler):
        """Per-request span (component ``serving_http``), joining the
        caller's W3C ``traceparent`` and echoing one back. The span is
        the ambient parent for everything the handler does on this task
        — including ``engine.submit``, which carries it across the
        engine-thread hop to the batcher's request tree. The
        ``replica`` attribute anchors the span's subtree to this
        replica's track when the router stitches the trace fleet-wide
        (obs/fleet_obs.py — an in-process test fleet shares ONE global
        tracer, so the fragment's origin cannot identify the serving
        replica; this attribute can)."""
        if not self.tracer.enabled:
            return await handler(request)
        from k8s_gpu_device_plugin_tpu.obs.http import (
            is_observation_path,
            route_label,
        )

        remote = parse_traceparent(request.headers.get(TRACEPARENT_HEADER))
        if remote is None and is_observation_path(request.path):
            # telemetry reads — health probes, /metrics scrapes, trace
            # fetches (the router's stitcher included) — may JOIN a
            # trace (traceparent present) but never START one: the
            # router polls every replica each --healthIntervalS and a
            # root span per probe/scrape floods the bounded finished-
            # trace ring, evicting the real request traces the fleet
            # stitcher fetches within ring_size x interval seconds
            return await handler(request)
        # canonical route in the span NAME (it becomes a histogram label
        # — raw paths would be unbounded); raw path as an attribute
        with self.tracer.span(
            f"{request.method} {route_label(request)}",
            component="serving_http",
            parent=remote, method=request.method, path=request.path,
            replica=self.replica_label(), **self._device_attrs,
        ) as span:
            try:
                response = await handler(request)
            except web.HTTPException as http_err:
                span.set(status_code=http_err.status)
                http_err.headers[TRACEPARENT_HEADER] = format_traceparent(span)
                raise
            span.set(status_code=response.status)
            if not response.prepared:  # SSE streams already sent headers
                response.headers[TRACEPARENT_HEADER] = format_traceparent(span)
            return response

    async def _debug_traces(self, request: web.Request) -> web.Response:
        from k8s_gpu_device_plugin_tpu.obs.http import (
            parse_trace_query,
            traces_payload,
        )

        try:
            limit, since = parse_trace_query(request.query)
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)
        return web.json_response(
            traces_payload(self.tracer, limit=limit, since_us=since)
        )

    def _attributor(self):
        """The engine's RequestAttributor, or None when the layer is
        off. Handlers touch it ONLY through its *_stats()/get()
        snapshot methods (thread-ownership contract)."""
        return getattr(self.engine.cb, "attribution", None)

    async def _debug_requests(self, request: web.Request) -> web.Response:
        att = self._attributor()
        if att is None:
            return web.json_response(
                {"error": "attribution disabled (start without "
                          "--attributionOff)"},
                status=404,
            )
        return web.json_response(att.request_stats())

    async def _debug_request_one(self, request: web.Request) -> web.Response:
        att = self._attributor()
        if att is None:
            return web.json_response(
                {"error": "attribution disabled (start without "
                          "--attributionOff)"},
                status=404,
            )
        try:
            rid = int(request.match_info["rid"])
        except ValueError:
            return web.json_response(
                {"error": "rid must be an integer"}, status=400
            )
        record = att.get(rid)
        if record is None:
            return web.json_response(
                {"error": "request not in the timeline buffer"}, status=404
            )
        return web.json_response(record)

    async def _debug_slow(self, request: web.Request) -> web.Response:
        att = self._attributor()
        if att is None:
            return web.json_response(
                {"error": "attribution disabled (start without "
                          "--attributionOff)"},
                status=404,
            )
        return web.json_response(att.slow_stats())

    async def _debug_trace_one(self, request: web.Request) -> web.Response:
        from k8s_gpu_device_plugin_tpu.obs.http import trace_detail_payload

        payload = trace_detail_payload(
            self.tracer, request.match_info["trace_id"]
        )
        if payload is None:
            return web.json_response({"error": "trace not in buffer"},
                                     status=404)
        return web.json_response(payload)

    async def _health(self, request: web.Request) -> web.Response:
        if self._flt_health is not None:
            try:
                self._flt_health.fire()
            except FaultError as e:
                return web.json_response({"error": str(e)}, status=500)
        stats = self.engine.stats()
        # fleet identity + age: the replica router's registry (and any
        # dashboard aggregating N replicas) needs to tell replicas
        # apart and spot restarts (uptime_s resetting = a new process
        # behind the same address); schema pinned in tests/test_health.py
        stats["replica_id"] = self.replica_label()
        stats["uptime_s"] = round(time.monotonic() - self._t_start, 3)
        # a dead engine must fail the readiness probe, not smile at it
        return web.json_response(stats, status=200 if stats["alive"] else 503)

    async def _metrics(self, request: web.Request) -> web.Response:
        # Content negotiation: an OpenMetrics scraper (Prometheus with
        # exemplar storage) gets the OpenMetrics exposition — the only
        # text format that renders the trace-id exemplars on the
        # TTFT/inter-token/phase histogram buckets; everyone else gets
        # the classic text format, byte-compatible with the pre-PR
        # surface (exemplars simply omitted).
        if "application/openmetrics-text" in request.headers.get(
            "Accept", ""
        ):
            from prometheus_client.openmetrics.exposition import (
                CONTENT_TYPE_LATEST,
                generate_latest,
            )

            return web.Response(
                body=generate_latest(self.registry),
                headers={"Content-Type": CONTENT_TYPE_LATEST},
            )
        from prometheus_client import generate_latest

        return web.Response(
            body=generate_latest(self.registry),
            content_type="text/plain",
        )

    async def _kv_export(self, request: web.Request) -> web.Response:
        """POST /v1/kv/export/{rid}: snapshot the request's KV pages and
        retire it (its stream closes with the tokens delivered so far).
        The body is a resubmittable triple — ``kv_pages`` wire blob,
        ``resume_out``, ``resume_logprobs`` — for /v1/generate on a
        decode replica. Status mapping mirrors the cancel surface:
        400 malformed id, 404 unknown/finished, 409 not exportable yet
        (still queued or prefilling — retry after the first token),
        503 dense layout / dead engine / engine-thread timeout."""
        try:
            eid = int(request.match_info["rid"])
        except ValueError:
            return web.json_response(
                {"error": "request id must be an integer"}, status=400
            )
        try:
            res = await self.engine.export_kv(eid)
        except KeyError as e:
            return web.json_response({"error": str(e)}, status=404)
        except ValueError as e:  # not admitted / prefilling: retryable
            return web.json_response({"error": str(e)}, status=409)
        except (RuntimeError, asyncio.TimeoutError) as e:
            return web.json_response({"error": str(e) or "export timed out"},
                                     status=503)
        res["id"] = eid
        return web.json_response(res)

    async def _generate(self, request: web.Request) -> web.StreamResponse:
        try:
            body = await request.json()
            text = body.get("text")
            if text is not None:
                if self.tokenizer is None:
                    raise ValueError(
                        "no tokenizer configured on this server; "
                        "send token ids via 'prompt'"
                    )
                if not isinstance(text, str) or not text:
                    raise ValueError("text must be a non-empty string")
                if "prompt" in body:
                    raise ValueError("send either 'text' or 'prompt', not both")
                prompt = self.tokenizer.encode(text)
            else:
                prompt = body["prompt"]
            max_new = int(body.get("max_new", 64))
            stream = bool(body.get("stream", False))
            n = int(body.get("n", 1))
            adapter = self.resolve_adapter(body.get("adapter"))
            # SLO identity (serving/scheduler.py): optional on the wire,
            # defaulted at the engine edge; validated by the batcher's
            # shared rule so both HTTP planes mean the same thing
            tenant = body.get("tenant")
            priority = body.get("priority")
            deadline_ms = body.get("deadline_ms")
            ContinuousBatcher.validate_sched(tenant, priority, deadline_ms)
            logit_bias = _parse_logit_bias(body.get("logit_bias"))
            # validate BEFORE the per-choice (seed+i) % 2^31 derivation —
            # the modulo would wrap an invalid seed into range silently
            seed = ContinuousBatcher.validate_seed(body.get("seed"))
            stop = body.get("stop", [])
            stop_text = body.get("stop_text", [])
            want_logprobs = bool(body.get("logprobs", False))
            # opt-in per-request latency attribution on the response
            # (obs/attribution.py): phase breakdown of this request's
            # TTFT and wall time; requires the server-side layer
            want_timeline = bool(body.get("timeline", False))
            # cross-replica stream resume (serving/router.py's seam):
            # tokens another incarnation already emitted AND delivered —
            # the engine folds them into the prompt (preemption fold)
            # and this response carries only the continuation
            resume_out = body.get("resume_out")
            resume_lp = body.get("resume_logprobs")
            if resume_out is not None:
                if (not isinstance(resume_out, list) or not resume_out
                        or not all(isinstance(t, int) for t in resume_out)):
                    raise ValueError(
                        "resume_out must be a non-empty list of token ids"
                    )
                if text is not None:
                    raise ValueError(
                        "resume_out requires a token-id 'prompt' "
                        "(the fold is defined over ids, not text)"
                    )
                if n != 1:
                    raise ValueError("resume supports n=1 only")
                if resume_lp is not None and (
                    not isinstance(resume_lp, list)
                    or not all(isinstance(x, (int, float))
                               for x in resume_lp)
                ):
                    raise ValueError(
                        "resume_logprobs must be a list of numbers"
                    )
            # disaggregated prefill/decode: KV pages exported from the
            # prefill replica ride the resume seam — the engine installs
            # them instead of recomputing the prefill chunks
            kv_pages = body.get("kv_pages")
            if kv_pages is not None:
                if resume_out is None:
                    raise ValueError(
                        "kv_pages requires resume_out (the transferred "
                        "pages cover the folded prompt's rows)"
                    )
                if not isinstance(kv_pages, dict):
                    raise ValueError(
                        "kv_pages must be a KV wire blob object "
                        "(see /v1/kv/export)"
                    )
            # per-request sampling: any knob present builds a full
            # Sampler (its own validation applies); absent fields default
            # to greedy/off, NOT to the server sampler — a request that
            # sets only temperature gets exactly what it asked for
            knob_fields = {
                "temperature": float,
                "top_k": int,
                "top_p": float,
                "repetition_penalty": float,
            }
            given = {
                k: cast(body[k]) for k, cast in knob_fields.items()
                if k in body
            }
            sampler = Sampler(**given) if given else None
            if (
                not isinstance(prompt, list)
                or not prompt
                or not all(isinstance(t, int) for t in prompt)
            ):
                raise ValueError("prompt must be a non-empty list of ids")
            if not (1 <= n <= 8):
                raise ValueError("n must be in [1, 8]")
            if n > 1 and stream:
                raise ValueError("streaming supports n=1 only")
            if not isinstance(stop, list) or not all(
                isinstance(st, list) and st
                and all(isinstance(t, int) for t in st)
                for st in stop
            ):
                raise ValueError("stop must be a list of token-id lists")
            if stop_text:
                from k8s_gpu_device_plugin_tpu.serving.tokenizer import (
                    encode_stop_strings,
                )

                stop = list(stop) + encode_stop_strings(
                    self.tokenizer, stop_text, field="stop_text"
                )
        except (json.JSONDecodeError, KeyError, TypeError, ValueError) as e:
            return web.json_response({"error": str(e)}, status=400)
        subs: list[tuple[int, asyncio.Queue]] = []
        try:
            # n>1 with a seed: per-choice seeds (seed+i), reproducible AND
            # distinct — one seed for all n would return identical copies
            for i in range(n):
                subs.append(self.engine.submit(
                    prompt, max_new, stop=stop, sampler=sampler,
                    adapter=adapter, logit_bias=logit_bias,
                    seed=None if seed is None else (seed + i) % 2**31,
                    tenant=tenant, priority=priority,
                    deadline_ms=deadline_ms,
                    resume_out=resume_out, resume_logp=resume_lp,
                    kv_pages=kv_pages,
                ))
        except RequestTooLargeError as e:
            # permanent refusal — no deferral can admit this request:
            # the structured body names the numbers the wall was
            # computed from so the client can resize instead of retry
            return web.json_response({"error": {
                "message": str(e),
                "code": "request_too_large",
                **e.body(),
            }}, status=422)
        except ValueError as e:  # capacity/bucket/sampler validation
            return web.json_response({"error": str(e)}, status=422)
        except SchedulerOverloadError as e:  # queue full: transient
            for eid_, _ in subs:  # a partially submitted n>1 burst
                self.engine.cancel(eid_)
            sched = getattr(self.engine.cb, "scheduler", None)
            if sched is not None:
                sched.count_sync_rejection(self.engine.cb)
            return _overload_response(str(e), e.reason, e.retry_after)
        except RuntimeError as e:  # engine dead
            return web.json_response({"error": str(e)}, status=503)
        rid, q = subs[0]

        if not stream:
            try:
                drained = await asyncio.gather(
                    *(drain_queue(q_) for _, q_ in subs)
                )
            except asyncio.CancelledError:
                # client gone mid-generation: free the slots instead of
                # decoding to the token budget
                for eid_, _ in subs:
                    self.engine.cancel(eid_)
                raise
            err = next((d[2] for d in drained if d[2] is not None), None)
            if err is not None:
                # the engine died (or exhausted its restart budget) under
                # this request: a real error status, never a 200 carrying
                # silently truncated tokens
                return web.json_response(
                    {"error": err.message, "code": err.code}, status=503
                )
            infos = [self.engine.pop_request_info(eid_) for eid_, _ in subs]
            reject = next(
                (i["reject_reason"] for i in infos
                 if i.get("reject_reason")), None,
            )
            if reject is not None and not any(d[0] for d in drained):
                # rejected while queued (deferral budget / a raced queue
                # cap) before emitting anything: overload, not a result
                return _overload_response(
                    "request rejected under overload before admission",
                    reject,
                    max((i.get("retry_after", 1) for i in infos), default=1),
                )
            payload = {
                "id": rid, "tokens": drained[0][0],
                # prompt tokens served from the prefix cache (0 when the
                # cache is off or missed) — the native twin of OpenAI's
                # usage.prompt_tokens_details.cached_tokens, with the
                # same n>1 rule: the best reuse any choice achieved (the
                # first choice may seed the cache for the rest)
                "cached_tokens": max(
                    (i.get("cached_tokens", 0) for i in infos), default=0
                ),
            }
            if want_logprobs:
                payload["logprobs"] = drained[0][1]
            if want_timeline:
                # the primary choice's attribution record (null when the
                # server runs --attributionOff — opt-in field, never an
                # error: the stream itself already succeeded)
                payload["timeline"] = infos[0].get("timeline")
            if n > 1:
                payload["completions"] = [d[0] for d in drained]
                if want_logprobs:
                    payload["completions_logprobs"] = [d[1] for d in drained]
            if self.tokenizer is not None:
                # detokenize phase of the request trace (the batcher owns
                # admit/prefill/decode/retire; text assembly happens here
                # at the HTTP boundary). A resumed request's text covers
                # the WHOLE output — the resumed tokens plus the
                # continuation — even though only the continuation was
                # (re-)delivered on this response.
                full_out = list(resume_out or []) + drained[0][0]
                with self.tracer.span(
                    "detokenize", component="serving",
                    tokens=len(full_out),
                ):
                    payload["text"] = self.tokenizer.decode(full_out)
                    if n > 1:
                        payload["completions_text"] = [
                            self.tokenizer.decode(d[0]) for d in drained
                        ]
            return web.json_response(payload)

        resp = web.StreamResponse(
            headers={"Content-Type": "text/event-stream",
                     "Cache-Control": "no-cache",
                     # the engine id this stream serves: what a router
                     # targets at POST /v1/kv/export/{rid} to lift the
                     # request off this replica mid-stream
                     "X-Request-Id": str(rid)}
        )
        await resp.prepare(request)
        # a resumed stream's closing text must cover the whole output,
        # resumed tokens included (only the continuation is re-streamed)
        streamed: list[int] = list(resume_out or [])
        try:
            while True:
                item = await q.get()
                if isinstance(item, StreamError):
                    # abnormal close: a structured SSE error event, NOT
                    # the done event — clients can tell a crashed stream
                    # from a finished one (the old dead path closed with
                    # a clean done, indistinguishable from success)
                    evt = {"error": {"code": item.code,
                                     "message": item.message}}
                    await resp.write(
                        f"data: {json.dumps(evt)}\n\n".encode()
                    )
                    break
                if item is None:
                    # closing event carries the full decoded text
                    # (incremental per-token decode is wrong across
                    # multi-token characters; clients wanting
                    # text-as-you-go can decode the token prefix
                    # themselves with the same caveat)
                    done: dict = {"done": True}
                    info = self.engine.pop_request_info(rid)
                    if info.get("reject_reason"):
                        # the SSE stream is already prepared (200), so a
                        # mid-stream overload rejection rides the done
                        # event instead of a status code
                        done["rejected"] = info["reject_reason"]
                        done["retry_after"] = info.get("retry_after", 1)
                    if info.get("cached_tokens"):
                        # only when the prefix cache actually served part
                        # of the prompt — the common done event stays lean
                        done["cached_tokens"] = info["cached_tokens"]
                    if want_timeline:
                        # null under --attributionOff, like the
                        # non-streamed payload — the documented contract
                        done["timeline"] = info.get("timeline")
                    if self.tokenizer is not None:
                        with self.tracer.span(
                            "detokenize", component="serving",
                            tokens=len(streamed),
                        ):
                            done["text"] = self.tokenizer.decode(streamed)
                    await resp.write(f"data: {json.dumps(done)}\n\n".encode())
                    break
                tok, lp = item
                streamed.append(tok)
                evt = {"token": tok}
                if want_logprobs:
                    evt["logprob"] = lp
                await resp.write(f"data: {json.dumps(evt)}\n\n".encode())
        except (asyncio.CancelledError, ConnectionResetError):
            # disconnected SSE consumer: free the slot
            self.engine.cancel(rid)
            raise
        await resp.write_eof()
        return resp

    async def run(self, stop: asyncio.Event) -> None:
        runner = web.AppRunner(self.app)
        # kept on self so the test/bench fleet harness can ABORT live
        # connections (serving/testing.py kill_replica): a graceful
        # cleanup waits for in-flight handlers, which is a drain — a
        # process death is not
        self._runner = runner
        await runner.setup()
        site = web.TCPSite(runner, self.host, self.port)
        await site.start()
        self.bound_port = runner.addresses[0][1] if runner.addresses else None
        log.info(
            "inference server listening",
            extra={"fields": {"addr": f"{self.host}:{self.bound_port}"}},
        )
        try:
            await stop.wait()
        finally:
            await runner.cleanup()
            self.engine.shutdown()


def load_adapters(cfg: LlamaConfig, spec: str):
    """``--loraAdapters`` value -> AdapterSet.

    Syntax: ``name=ckptdir[:alpha=X],name2=dir2`` — each dir is an orbax
    checkpoint whose tree carries the LoRA factors under ``"lora"`` (the
    fine-tune state layout, models/lora.py init_lora_state). Rank and
    targets are inferred from the factor shapes; alpha defaults to the
    classic 2·rank unless given (it isn't recorded in the factors)."""
    from k8s_gpu_device_plugin_tpu.models.checkpoint import TrainCheckpointer
    from k8s_gpu_device_plugin_tpu.models.lora import LoraConfig
    from k8s_gpu_device_plugin_tpu.models.lora_serving import stack_adapters

    adapters = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        if "=" not in entry:
            raise ValueError(
                f"--loraAdapters entry {entry!r}: expected name=ckptdir"
            )
        name, rest = entry.split("=", 1)
        name = name.strip()
        if not name:
            raise ValueError(
                f"--loraAdapters entry {entry!r}: empty adapter name "
                "(it would be unreachable — '' routes to the base model)"
            )
        from k8s_gpu_device_plugin_tpu.serving.openai_api import MODEL_ID

        if name == MODEL_ID:
            raise ValueError(
                f"adapter name {name!r} collides with the base model id; "
                "OpenAI-API requests for it would silently serve the base"
            )
        alpha = None
        if ":alpha=" in rest:
            rest, alpha_s = rest.split(":alpha=", 1)
            alpha = float(alpha_s)
        ckpt = TrainCheckpointer(rest, async_save=False)
        try:
            tree = ckpt.restore_unstructured()
        finally:
            ckpt.close()
        lora_params = tree.get("lora", tree)  # fine-tune state or bare factors
        if (
            not isinstance(lora_params, dict)
            or not lora_params
            or not all(
                isinstance(ab, dict) and "a" in ab and "b" in ab
                for ab in lora_params.values()
            )
        ):
            raise ValueError(
                f"no LoRA factors found in {rest!r} (expected "
                "{target: {'a', 'b'}} under 'lora' or at the tree root)"
            )
        if cfg.is_moe and any(t in ("w1", "w2", "w3") for t in lora_params):
            # the same restriction init_random_adapters and training-side
            # lora.py enforce: the MoE decode path never reads mlp adapter
            # leaves, so accepting them would silently serve a
            # partially-applied adapter
            raise ValueError(
                f"adapter {name.strip()!r} targets MoE expert MLPs "
                "(w1/w2/w3), which are not LoRA-servable on an MoE config"
            )
        first = next(iter(lora_params.values()))
        rank = int(first["a"].shape[-1])
        lcfg = LoraConfig(
            rank=rank,
            alpha=alpha if alpha is not None else 2.0 * rank,
            targets=tuple(lora_params),
        )
        adapters.append((name.strip(), lora_params, lcfg))
        log.info(
            "loaded LoRA adapter",
            extra={"fields": {"name": name.strip(), "dir": rest,
                              "rank": rank, "targets": list(lora_params)}},
        )
    return stack_adapters(cfg, adapters)


def load_params(cfg: LlamaConfig, checkpoint_dir: str = ""):
    """Model weights for serving: the latest orbax train checkpoint's
    ``params`` sub-tree, or (loudly) random init for smoke/load tests."""
    import jax

    from k8s_gpu_device_plugin_tpu.models.llama import init_params

    if not checkpoint_dir:
        log.warning("serving RANDOM weights (no --checkpointDir): smoke mode")
        return jax.jit(lambda k: init_params(k, cfg))(jax.random.key(0))

    from k8s_gpu_device_plugin_tpu.models.checkpoint import TrainCheckpointer

    ckpt = TrainCheckpointer(checkpoint_dir, async_save=False)
    try:
        state = ckpt.restore_unstructured()
        params = state["params"]
    finally:
        ckpt.close()
    log.info(
        "restored params for serving",
        extra={"fields": {"dir": checkpoint_dir}},
    )
    return params


def _main(argv: list[str] | None = None) -> int:
    """CLI: serve a model preset over HTTP.

    ``--checkpointDir`` restores the params from the framework's own
    orbax train checkpoints (latest step); without it the server runs
    RANDOM weights — useful only for smoke/load testing, and loudly
    logged as such.
    """
    import argparse

    parser = argparse.ArgumentParser(prog="tpu-inference-server")
    parser.add_argument("--preset", default="tiny",
                        choices=["tiny", "llama3_8b", "llama3_70b",
                                 "mistral_7b", "mixtral_8x7b"])
    parser.add_argument("--port", type=int, default=8000)
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--slots", type=int, default=8)
    parser.add_argument("--maxLen", type=int, default=2048)
    parser.add_argument("--chunkedPrefill", type=int, default=256)
    parser.add_argument("--attnWindow", type=int, default=0,
                        help="sliding-window attention span W (tokens): "
                        "each query attends only the trailing (q-W, q] "
                        "keys. 0 = full causal (the default; every "
                        "serving graph identical to a window-less "
                        "build). With --kvLayout paged and chunked "
                        "prefill, long prompts admit through streaming "
                        "chunk-prefill — pages reserve incrementally "
                        "and out-of-window pages recycle, so a row's "
                        "steady-state KV footprint is O(W), not "
                        "O(length)")
    parser.add_argument("--prefillReserveChunks", type=int, default=2,
                        help="windowed admission tranche: prefill "
                        "chunks' worth of pages reserved up front (the "
                        "rest grow chunk by chunk as the prefill "
                        "cursor advances); meaningful only with "
                        "--attnWindow > 0 and --kvLayout paged")
    parser.add_argument("--tp", type=int, default=1,
                        help="tensor-parallel shards: weights (q/k/v/"
                        "gate/up/lm_head columns) and the KV cache "
                        "(dense rows or the paged pool, on the KV-head "
                        "axis) shard over a tp-device mesh — tp times "
                        "the KV pages/slots per replica; must divide "
                        "the visible device count and the model's "
                        "n_kv_heads (validated at startup); token/"
                        "logprob streams are bit-identical to --tp 1")
    parser.add_argument("--tpPsum", action="store_true",
                        help="with --tp > 1: row-shard the wo/w2 "
                        "contraction axes and let the partitioner psum "
                        "the partials — one collective fewer per layer, "
                        "at the price of the bit-identity pin (the "
                        "split f32 reduction drifts ~1e-5 from --tp 1; "
                        "explicit opt-out, off by default)")
    def _eos_arg(value: str):
        """'none' or a negative int -> EOS stopping OFF; an id -> that id.
        Keeps argparse's clean usage error for garbage like '1.5'."""
        if value.lower() == "none":
            return "none"
        try:
            return int(value)
        except ValueError:
            raise argparse.ArgumentTypeError(
                f"expected an integer or 'none', got {value!r}"
            ) from None

    parser.add_argument("--eosId", type=_eos_arg, default=None,
                        help="EOS token id; unset adopts the tokenizer's "
                        "eos when --tokenizer is given; 'none' (or -1) "
                        "explicitly disables EOS stopping")
    parser.add_argument("--temperature", type=float, default=0.0)
    parser.add_argument("--topK", type=int, default=0)
    parser.add_argument("--topP", type=float, default=1.0)
    parser.add_argument("--weightQuant", default="none",
                        choices=["none", "int8", "int4"])
    parser.add_argument("--cacheQuant", default="none",
                        choices=["none", "int8", "int4"],
                        help="KV-cache quantization: int8 halves decode's "
                        "cache HBM stream, int4 halves it again (coarser "
                        "codes; accuracy trade)")
    parser.add_argument("--decodeAttn", default="auto",
                        choices=["auto", "xla", "ragged"],
                        help="decode/verify cached attention: 'ragged' "
                        "routes T=1 decode and the speculative verify "
                        "window through the unified ragged-paged Pallas "
                        "kernel (shard_map-ed per KV head under --tp); "
                        "auto/xla = the fused XLA gather. The chosen "
                        "backend per mode is logged at startup and on "
                        "/v1/health (decode_attn section)")
    parser.add_argument("--prefillAttn", default="auto",
                        choices=["auto", "xla", "ragged"],
                        help="prefill-chunk cached attention: 'ragged' "
                        "routes chunk windows through the same unified "
                        "kernel (separate knob: prefill's low-bit "
                        "numerics profile changes with the online-"
                        "softmax accumulation order)")
    parser.add_argument("--checkpointDir", default="")
    parser.add_argument("--embeddings", action="store_true",
                        help="enable /v1/embeddings (mean-pooled final "
                        "hidden states; base model only, bf16 weights)")
    parser.add_argument("--scoring", action="store_true",
                        help="enable completions echo=true + max_tokens=0 "
                        "prompt scoring (teacher-forced logprobs; base "
                        "model only, bf16 weights)")
    parser.add_argument("--scoringMaxLen", type=int, default=4096,
                        help="longest scorable prompt; past the largest "
                        "bucket the scorer chunks through the KV-cached "
                        "forward (one extra compile at startup)")
    parser.add_argument("--loraAdapters", default="",
                        help="multi-LoRA serving: name=ckptdir[:alpha=X]"
                        ",... — requests select by name ('adapter' field "
                        "on /v1/generate; 'model' on the OpenAI API)")
    parser.add_argument("--adapterCacheMB", type=int, default=0,
                        help="multi-LoRA HBM residency budget in MB "
                        "(models/lora_serving.AdapterStore): adapters "
                        "past the budget stay host-side and upload on "
                        "demand, LRU-evicting idle ones; 0 = every "
                        "registered adapter stays resident")
    parser.add_argument("--adapterQuota", default="",
                        help="per-adapter hard rate limits: "
                        "name=rate[:burst=B],... (tokens/s of prompt + "
                        "budgeted output; burst defaults to 4x rate). "
                        "Enforced under every --schedPolicy — over-"
                        "quota submits 429 with Retry-After")
    parser.add_argument("--tokenizer", default="",
                        help="text seam: 'byte' (UTF-8 bytes, lossless) or "
                        "a local HF tokenizer directory; empty = token-id "
                        "API only")
    parser.add_argument("--draftPreset", default="",
                        help="enable speculative decoding with this draft "
                        "model preset (greedy or sampled; repetition "
                        "penalty unsupported). Composes with the fast "
                        "path: --kvLayout paged pages both caches, the "
                        "automatic prefix cache serves the target "
                        "zero-copy, --pipelineDepth 1 overlaps rounds")
    parser.add_argument("--draftCheckpointDir", default="")
    parser.add_argument("--gamma", type=int, default=4,
                        help="draft proposals verified per round (pick "
                        "from the spec_accepted_per_round histogram: "
                        "mass at gamma = raise it, mass at 1 = lower it)")
    parser.add_argument("--draftKvPages", type=int, default=0,
                        help="with --draftPreset and --kvLayout paged: "
                        "physical pages in the DRAFT model's KV pool "
                        "(0 sizes it to the draft's dense-equivalent "
                        "capacity)")
    parser.add_argument("--pipelineDepth", type=int, default=1,
                        choices=[0, 1],
                        help="decode pipeline: 1 (default) dispatches "
                        "step t+1 (or speculative round t+1) before "
                        "reading step t back so host token work "
                        "overlaps device compute; 0 restores the "
                        "synchronous loop")
    parser.add_argument("--prefixCacheMB", type=int, default=256,
                        help="HBM byte budget (MiB) for the automatic "
                        "prefix cache: prompts sharing a cached prefix "
                        "(system prompts, multi-turn histories) skip its "
                        "re-prefill; LRU-evicted under this budget. "
                        "Requires chunked prefill; 0 disables")
    parser.add_argument("--prefixCacheMinHits", type=int, default=1,
                        help="promote a prefix into the cache after this "
                        "many sightings (1 = every completed prefill; "
                        "higher trades first-repeat latency for less "
                        "HBM duplication across nested boundaries)")
    parser.add_argument("--prefixCacheOff", action="store_true",
                        help="disable the automatic prefix cache "
                        "(equivalent to --prefixCacheMB 0; token and "
                        "logprob streams are bit-identical either way)")
    parser.add_argument("--kvLayout", default="dense",
                        choices=["dense", "paged"],
                        help="serving KV-cache layout: 'dense' reserves "
                        "maxLen rows per slot; 'paged' maps slots onto a "
                        "shared page pool (HBM scales with live tokens, "
                        "prefix-cache hits alias pages with zero copies; "
                        "composes with --cacheQuant — int8/int4 codes "
                        "AND their scale planes ride the pool — and "
                        "token/logprob streams are bit-identical either "
                        "way)")
    parser.add_argument("--kvPageSize", type=int, default=64,
                        help="token rows per KV page with --kvLayout "
                        "paged; must divide --maxLen (multiples of 8 "
                        "keep the Pallas paged kernel aligned)")
    parser.add_argument("--kvPages", type=int, default=0,
                        help="physical pages in the paged KV pool "
                        "(includes the reserved trap page); 0 sizes it "
                        "to dense-equivalent capacity — shrink to "
                        "overcommit HBM against live tokens (admission "
                        "then gates on pool pressure instead of slots "
                        "alone)")
    parser.add_argument("--schedPolicy", default="fifo",
                        choices=["fifo", "slo"],
                        help="admission policy (serving/scheduler.py): "
                        "'fifo' is arrival order, bit-identical to the "
                        "pre-scheduler server; 'slo' orders by priority "
                        "class, per-tenant weighted fairness and "
                        "earliest deadline, enforces --tenantQuota, and "
                        "preempts lower-class decodes when a deadline "
                        "would be missed (disabled with --draftPreset: "
                        "the speculative engine has no resume path)")
    parser.add_argument("--tenantQuota", default="",
                        help="per-tenant token-bucket quotas + WFQ "
                        "weights (requires --schedPolicy slo): "
                        "name=rate[:burst=B][:weight=W],... — rate in "
                        "tokens/s (prompt + budgeted output charged at "
                        "submit); over-quota tenants are demoted behind "
                        "every in-quota class, never dropped")
    parser.add_argument("--defaultDeadlineMs", type=int, default=0,
                        help="deadline applied to requests that name "
                        "none (0 = no deadline): the SLO the slo policy "
                        "schedules against and the deadline-miss/goodput "
                        "metrics report on")
    parser.add_argument("--maxQueue", type=int, default=0,
                        help="pending-request cap (0 = unbounded): past "
                        "it, submissions answer HTTP 429 with Retry-After "
                        "instead of queueing doomed work (either policy)")
    parser.add_argument("--deferBudgetMs", type=int, default=0,
                        help="how long one request may sit pool-pressure-"
                        "deferred at the queue head before it is rejected "
                        "with 429 (0 = wait forever, the pre-scheduler "
                        "behavior; either policy)")
    parser.add_argument("--attributionOff", action="store_true",
                        help="disable per-request latency attribution + "
                        "live MFU accounting (obs/attribution.py): no "
                        "timelines on the done payloads or "
                        "/debug/requests, no /debug/slow flight "
                        "recorder, no serving_mfu_pct — token/logprob "
                        "streams are bit-identical either way")
    parser.add_argument("--slowRequestMs", type=float, default=0.0,
                        help="flight-recorder threshold: requests whose "
                        "total wall time reaches this keep full step-"
                        "level detail on GET /debug/slow (deadline "
                        "misses always do; 0 adds automatic p99-of-"
                        "window triggering so the tail stays "
                        "explainable untuned)")
    parser.add_argument("--replicaId", default="",
                        help="stable fleet identity reported on "
                        "/v1/health (serving/router.py's registry and "
                        "dashboards key on it); empty = hostname:port")
    parser.add_argument("--devices", default="auto",
                        help="request->chip attribution (device/"
                        "allocation.py): 'auto' reads the device "
                        "plugin's container env contract "
                        "(TPU_VISIBLE_CHIPS + TPU_ALLOCATION_ID), "
                        "'off' disables it, or an explicit "
                        "'[alloc-id:]chip,chip,...' spec pins it — "
                        "spans, timelines, /v1/health and the "
                        "kv_shard_chip gauge then name the physical "
                        "chips under this replica")
    parser.add_argument("--restartBudget", type=int, default=3,
                        help="engine crash recoveries allowed per "
                        "rolling --restartWindowS window (serving/"
                        "supervisor.py): within budget a crashed "
                        "engine rebuilds in place, replays its queue "
                        "in order and resumes in-flight streams "
                        "bit-identically; past it (or with 0) the "
                        "replica degrades to dead and every stream "
                        "closes with a structured error frame")
    parser.add_argument("--restartWindowS", type=float, default=300.0,
                        help="rolling window for --restartBudget")
    parser.add_argument("--faults", default="",
                        help="seeded fault injection (serving/"
                        "faults.py): comma list of armed fault points "
                        "with schedules, e.g. 'decode.apply:nth=40,"
                        "pool.alloc:p=0.25:seed=3:times=6'; also read "
                        "from TPU_SERVING_FAULTS; empty = disarmed "
                        "(the production default — each point costs "
                        "one is-not-None check)")
    parser.add_argument("--tracing", action="store_true",
                        help="span tracing (obs/): request span trees on "
                        "GET /debug/traces, trace ids in JSON logs, span-"
                        "duration histograms on /metrics; default off")
    parser.add_argument("--traceSteps", action="store_true",
                        help="with --tracing: per-decode-step "
                        "decode_dispatch/decode_readback spans (batch-"
                        "scoped traces; shows the pipeline overlap)")
    args = parser.parse_args(argv)

    if args.tracing:
        from k8s_gpu_device_plugin_tpu.obs.prom import SpanMetrics
        from k8s_gpu_device_plugin_tpu.obs.trace import configure
        from prometheus_client import REGISTRY as _REGISTRY

        SpanMetrics(registry=_REGISTRY).install(configure(enabled=True))

    from k8s_gpu_device_plugin_tpu.metrics.serving_metrics import ServingMetrics

    cfg = getattr(LlamaConfig, args.preset)()
    if args.attnWindow < 0:
        raise SystemExit("--attnWindow must be >= 0 (0 = full causal)")
    if args.prefillReserveChunks < 1:
        raise SystemExit("--prefillReserveChunks must be >= 1: the "
                         "tranche has to cover at least the chunk "
                         "being prefilled")
    if args.attnWindow:
        from dataclasses import replace as _replace

        cfg = _replace(cfg, sliding_window=args.attnWindow)
    if args.cacheQuant != "none":
        from dataclasses import replace as _replace

        cfg = _replace(cfg, cache_quant=args.cacheQuant)
    if args.decodeAttn != "auto" or args.prefillAttn != "auto":
        from dataclasses import replace as _replace

        cfg = _replace(cfg, decode_attn=args.decodeAttn,
                       prefill_attn=args.prefillAttn)
    if args.tp != 1:
        # fail BEFORE the (slow) weight load: the shared flag rule
        # (parallel/mesh.py MeshSpec.from_flags — the same validation
        # the trainer's mesh flags go through) checks tp against the
        # device count and the model's KV-head count
        from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec

        try:
            MeshSpec.from_flags(tp=args.tp, n_kv_heads=cfg.n_kv_heads,
                                exact=True)
        except ValueError as e:
            raise SystemExit(str(e)) from None
    if args.tpPsum:
        if args.tp == 1:
            raise SystemExit("--tpPsum needs --tp > 1: there is no "
                             "collective to save on one shard")
        from dataclasses import replace as _replace

        cfg = _replace(cfg, tp_allow_psum=True)
    params = load_params(cfg, args.checkpointDir)

    sampler = Sampler(temperature=args.temperature, top_k=args.topK,
                      top_p=args.topP)
    if args.weightQuant == "int8":
        from k8s_gpu_device_plugin_tpu.models.quantized_serving import (
            quantize_weights_int8,
        )

        params = quantize_weights_int8(params)
    elif args.weightQuant == "int4":
        from k8s_gpu_device_plugin_tpu.models.quantized_serving import (
            quantize_weights_int4,
        )

        params = quantize_weights_int4(params)

    from k8s_gpu_device_plugin_tpu.serving.tokenizer import load_tokenizer

    tokenizer = load_tokenizer(args.tokenizer)
    # Three states, all expressible: unset -> adopt the tokenizer's EOS
    # (or no EOS without one); explicit 'none'/-1 -> EOS stopping OFF even
    # with a tokenizer; explicit id -> that id.
    if args.eosId is None:
        eos_id = getattr(tokenizer, "eos_id", None)
    elif str(args.eosId).lower() == "none" or int(args.eosId) < 0:
        eos_id = None
    else:
        eos_id = int(args.eosId)

    adapters = None
    if args.loraAdapters:
        if args.draftPreset:
            raise SystemExit(
                "--loraAdapters is unsupported with --draftPreset: the "
                "draft model has no adapter stacks to mirror the target's"
            )
        adapters = load_adapters(cfg, args.loraAdapters)
    if args.adapterCacheMB and not args.loraAdapters:
        raise SystemExit(
            "--adapterCacheMB needs --loraAdapters: an HBM residency "
            "budget with no adapters to hold would silently do nothing"
        )
    if args.adapterCacheMB < 0:
        raise SystemExit("--adapterCacheMB must be >= 0")

    # /v1/embeddings: the hidden-state forward is the training-path
    # matmul, incompatible with decode-path quantized weight leaves.
    # Constructed (and bucket-warmed) BEFORE the engine so all embedding
    # compiles happen while this thread is the only compiler — executor-
    # thread compiles racing the engine thread's decode compiles have
    # segfaulted XLA:CPU (see tests/conftest.py).
    embedder = None
    if args.embeddings:
        if args.weightQuant != "none":
            raise SystemExit(
                "--embeddings is unsupported with --weightQuant: the "
                "hidden-state forward cannot consume quantized leaves"
            )
        from k8s_gpu_device_plugin_tpu.serving.embeddings import Embedder

        embedder = Embedder(params, cfg)

    # echo=true prompt scoring: same training-path forward, same
    # warm-before-engine compile discipline as the embedder
    scorer = None
    if args.scoring:
        if args.weightQuant != "none":
            raise SystemExit(
                "--scoring is unsupported with --weightQuant: the "
                "teacher-forced forward cannot consume quantized leaves"
            )
        from k8s_gpu_device_plugin_tpu.serving.scoring import Scorer

        scorer = Scorer(params, cfg, max_len=args.scoringMaxLen)

    metrics = ServingMetrics()
    # Automatic prefix caching: on by default wherever it can work —
    # chunked prefill (the suffix scheduler) is the only requirement;
    # the speculative batcher serves the target from the cache and
    # re-prefills the draft's rows itself. Promotion boundaries are the
    # batcher's own prompt-bucket ladder.
    prefix_cache = None
    if (
        not args.prefixCacheOff and args.prefixCacheMB > 0
        and args.chunkedPrefill > 0
    ):
        from k8s_gpu_device_plugin_tpu.models.batching import (
            DEFAULT_PROMPT_BUCKETS,
        )
        from k8s_gpu_device_plugin_tpu.serving.prefix_cache import PrefixCache

        buckets = tuple(b for b in DEFAULT_PROMPT_BUCKETS if b <= args.maxLen)
        if buckets:  # a maxLen below the smallest boundary: nothing cacheable
            prefix_cache = PrefixCache(
                cfg,
                buckets=buckets,
                budget_bytes=args.prefixCacheMB << 20,
                min_hits=args.prefixCacheMinHits,
                metrics=metrics,
            )
    if args.kvLayout == "dense" and (
        args.kvPages or args.kvPageSize != 64
    ):
        # silently serving the full static reservation when the operator
        # asked for a sized pool would mislead exactly like the combo
        # refused below (64 is the --kvPageSize default, the one value
        # that cannot be told apart from "not passed")
        raise SystemExit(
            "--kvPages/--kvPageSize have no effect under --kvLayout "
            "dense (the dense cache reserves slots*maxLen rows); add "
            "--kvLayout paged"
        )
    if args.draftKvPages and (
        args.kvLayout != "paged" or not args.draftPreset
    ):
        raise SystemExit(
            "--draftKvPages sizes the speculative draft model's page "
            "pool: it needs both --draftPreset and --kvLayout paged"
        )
    from k8s_gpu_device_plugin_tpu.serving.scheduler import make_scheduler

    try:
        scheduler = make_scheduler(
            args.schedPolicy,
            max_queue=args.maxQueue,
            defer_budget_ms=args.deferBudgetMs,
            tenant_quota=args.tenantQuota,
            # the speculative engine has no preemption resume path; the
            # slo policy still orders/quotas it (documented, not silent:
            # the health endpoint reports the policy either way)
            preempt=not args.draftPreset,
            adapter_quota=args.adapterQuota,
        )
    except ValueError as e:
        raise SystemExit(str(e)) from None

    # Per-request latency attribution + live MFU/roofline accounting:
    # on by default (the operator-facing numbers), one flag off. The
    # cost model prices against the detected TPU generation's spec-sheet
    # peaks (device/topology.py); off-TPU it falls back to v5e so the
    # ratios stay well-defined.
    attribution = None
    mfu = None
    if not args.attributionOff:
        from k8s_gpu_device_plugin_tpu.metrics.roofline import (
            MfuAccumulator,
            ServingCostModel,
        )
        from k8s_gpu_device_plugin_tpu.obs.attribution import (
            RequestAttributor,
        )

        attribution = RequestAttributor(
            slow_ms=args.slowRequestMs, metrics=metrics
        )
        mfu = MfuAccumulator(
            ServingCostModel.for_config(cfg, tp=args.tp), metrics=metrics
        )

    from k8s_gpu_device_plugin_tpu.serving.faults import FaultPlane

    fault_plane = FaultPlane.from_cli(args.faults)

    # Request->chip attribution (device/allocation.py): under the device
    # plugin the container env names the allocated chips; 'auto' quietly
    # yields None elsewhere (dev boxes), an explicit spec fails loudly.
    from k8s_gpu_device_plugin_tpu.device.allocation import AllocatedDevices

    if args.devices == "auto":
        devices = AllocatedDevices.from_env()
    elif args.devices == "off":
        devices = None
    else:
        devices = AllocatedDevices.from_spec(args.devices)

    batcher = None
    if args.draftPreset:
        from k8s_gpu_device_plugin_tpu.models.spec_batching import (
            SpeculativeBatcher,
        )

        draft_cfg = getattr(LlamaConfig, args.draftPreset)()
        draft_params = load_params(draft_cfg, args.draftCheckpointDir)
        # the fast-path stack goes to the batcher's own constructor
        # (the engine refuses the flags alongside an injected batcher):
        # prefix cache, paged KV for BOTH caches, pipelined rounds
        batcher = SpeculativeBatcher(
            params, cfg, draft_params, draft_cfg,
            n_slots=args.slots, max_len=args.maxLen, gamma=args.gamma,
            draft_kv_pages=args.draftKvPages,
            sampler=sampler, eos_id=eos_id,
            chunked_prefill=min(args.chunkedPrefill, args.maxLen),
            metrics=metrics,
            pipeline_depth=args.pipelineDepth,
            trace_steps=args.traceSteps and args.tracing,
            prefix_cache=prefix_cache,
            kv_layout=args.kvLayout,
            kv_page_size=(
                args.kvPageSize if args.kvLayout == "paged" else None
            ),
            kv_pages=args.kvPages,
            scheduler=scheduler,
            tp=args.tp,
            attribution=attribution,
            mfu=mfu,
            faults=fault_plane,
            devices=devices,
        )
    engine = InferenceEngine(
        params, cfg, n_slots=args.slots, max_len=args.maxLen,
        sampler=sampler, eos_id=eos_id,
        chunked_prefill=args.chunkedPrefill, metrics=metrics,
        batcher=batcher, adapters=adapters,
        adapter_cache_mb=args.adapterCacheMB,
        pipeline_depth=args.pipelineDepth,
        trace_steps=args.traceSteps and args.tracing,
        prefix_cache=None if batcher is not None else prefix_cache,
        kv_layout=None if batcher is not None else args.kvLayout,
        kv_page_size=None if batcher is not None else (
            args.kvPageSize if args.kvLayout == "paged" else None
        ),
        kv_pages=0 if batcher is not None else args.kvPages,
        prefill_reserve_chunks=(
            2 if batcher is not None else args.prefillReserveChunks
        ),
        scheduler=None if batcher is not None else scheduler,
        default_deadline_ms=args.defaultDeadlineMs,
        tp=None if batcher is not None else args.tp,
        attribution=None if batcher is not None else attribution,
        mfu=None if batcher is not None else mfu,
        # the speculative engine has no resume path (injected batcher:
        # no rebuild recipe) — its crashes degrade to the dead state,
        # now with structured error frames either way
        supervisor=None if batcher is not None else EngineSupervisor(
            max_restarts=args.restartBudget, window_s=args.restartWindowS,
        ),
        faults=None if batcher is not None else fault_plane,
        devices=None if batcher is not None else devices,
    )
    from prometheus_client import REGISTRY

    server = InferenceServer(engine, host=args.host, port=args.port,
                             registry=REGISTRY, tokenizer=tokenizer,
                             embedder=embedder, scorer=scorer,
                             replica_id=args.replicaId,
                             faults=fault_plane)

    async def serve():
        stop = asyncio.Event()
        import signal

        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await server.run(stop)

    asyncio.run(serve())
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
