"""Shared bucketed single-row dispatch for auxiliary serving forwards.

Embeddings (serving/embeddings.py) and prompt scoring
(serving/scoring.py) are the same machine with different jitted
functions: pad a token list to the smallest fitting prompt bucket, run a
per-bucket-compiled forward, serialize dispatches behind one lock, and
compile every bucket at CONSTRUCTION — before the engine thread exists —
so aiohttp executor threads only ever dispatch cached executables
(concurrent XLA:CPU compilation segfaults intermittently in this jaxlib
build; see tests/conftest.py). One implementation here so the bucket
policy, warmup discipline, and over-cap error can never diverge between
the two.
"""

from __future__ import annotations

import threading

import jax.numpy as jnp


class BucketedForward:
    """Pad-to-bucket dispatcher over ``fn(params, padded, length, cfg)``.

    ``kind`` names the consumer in the over-cap error ("embedding",
    "scoring"); ``buckets`` are the compiled pad lengths.
    """

    def __init__(self, fn, params, cfg,
                 buckets: tuple[int, ...], kind: str,
                 warmup: bool = True):
        self._fn = fn
        self.params = params
        self.cfg = cfg
        self.buckets = tuple(sorted(buckets))
        self.kind = kind
        self._lock = threading.Lock()
        if warmup:
            self.warmup()

    def warmup(self) -> None:
        """Compile every bucket NOW, on the constructing thread (see
        module docstring)."""
        import jax

        for b in self.buckets:
            jax.block_until_ready(self._fn(
                self.params, jnp.zeros((b,), jnp.int32), jnp.int32(1),
                self.cfg,
            ))

    def dispatch(self, ids: list[int]):
        """Pad ``ids`` to its bucket and run the forward (lock-serialized);
        returns the device array."""
        if not ids:
            raise ValueError("empty input")
        # the serving prefill's own smallest-fitting-bucket rule — one
        # implementation, so the bucket policies can never diverge
        from k8s_gpu_device_plugin_tpu.models.batching import _bucket

        try:
            b = _bucket(len(ids), self.buckets)
        except ValueError:
            raise ValueError(
                f"input of {len(ids)} tokens exceeds the {self.kind} "
                f"bucket cap {self.buckets[-1]}"
            ) from None
        padded = jnp.asarray(list(ids) + [0] * (b - len(ids)), jnp.int32)
        with self._lock:
            return self._fn(
                self.params, padded, jnp.int32(len(ids)), self.cfg
            )
