"""Automatic prefix caching: radix-tree KV reuse across serving requests.

PR 2 removed the host from the decode loop; the remaining dominant
serving cost under realistic traffic is redundant PREFILL — every
multi-turn chat re-prefills its whole history, and every request behind
a shared system prompt re-computes the same KV rows. The batcher already
has the mechanism (``PrefixState`` / ``_insert_prefix``,
models/batching.py: N requests naming one prefilled prefix pay one
prefill total) but it is manual. This module makes it automatic:

- a **radix tree over token ids** indexes every prefix the engine has
  prefilled, keyed by ``(adapter, tokens)`` — adapter-aware because the
  K/V rows depend on the weights that produced them (``PrefixState``
  records its adapter; ``submit`` rejects a mismatch);
- every incoming request is **matched automatically at admission** (the
  slot-assignment step right after submit — past validation, and late
  enough that a queued burst sees what its queue-mates just promoted):
  the longest cached prefix of the prompt is inserted through the
  existing ``_insert_prefix`` path, so only the suffix is
  chunk-prefilled;
- the cache **populates itself**: after a request's prefill completes
  the batcher offers its prompt back (``on_prefill_done``), and prefixes
  are promoted at the batcher's ``prompt_buckets`` boundaries — the same
  ladder the prefill compiles quantize to, so ``_insert_prefix`` and the
  row extraction compile once per boundary, not once per prompt;
- residency is bounded by an **HBM byte budget** (computed from the KV
  dtype and model config — :func:`prefix_kv_bytes`) with LRU eviction.

Bucket-aligned radix edges: promotion and matching both happen at
``prompt_buckets`` boundaries only, so the tree's edges span exactly one
boundary gap each (root -> tokens[0:32] -> tokens[32:64] -> ...). That
keeps the radix property (one hash per edge, O(prompt) total match cost)
without per-token nodes, and two prompts diverging inside a gap share
every boundary below their divergence — exactly the reuse the insert
path can express, since it only copies whole boundary-aligned row
blocks.

Policy knobs: ``min_hits=1`` promotes every completed prefill
("always"); ``min_hits=N`` defers the HBM spend until a prefix has been
seen N times (first-repeat latency traded for less duplication — nested
boundary entries each hold their own row copy). Eviction drops the
device arrays only from the TREE; requests that already matched an
entry hold their own reference, so an eviction mid-flight is invisible
to them — the bit-exactness guarantee (cache on vs off produces
identical greedy/seeded token and logprob streams) needs no pinning or
refcounts, and tests/test_prefix_cache.py pins it across
admit/retire/cancel/eviction interleavings.

Single-threaded by design: every mutating call happens on the engine
thread (``submit`` runs there via the engine's admission queue), the
same discipline as the batcher itself. ``stats()`` is a GIL-consistent
read for HTTP handlers.

The speculative batcher (models/spec_batching.py) consumes this cache
through the same two calls: entries always hold TARGET-model rows (or
page refs), matched and aliased exactly as here; the draft cache never
enters the tree — the batcher re-prefills the matched region through
the draft model at admission, which keeps every entry reusable by both
speculative and plain batchers' traffic shapes without draft-keyed
roots.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np

from k8s_gpu_device_plugin_tpu.models.batching import (
    PrefixState,
    effective_prefix_reuse,
)
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig
from k8s_gpu_device_plugin_tpu.models.paging import kv_token_bytes
from k8s_gpu_device_plugin_tpu.obs.trace import get_tracer


def prefix_kv_bytes(cfg: LlamaConfig, p: int) -> int:
    """HBM bytes a ``p``-token cached prefix occupies: K and V rows
    (L, 1, p, Hkv, hd) in the cache dtype, plus the f32 scale planes on
    the quantized paths. The byte budget is denominated in THIS, so an
    operator's ``--prefixCacheMB`` means the same thing under bf16, int8
    and int4 caches (int4 packs two codes per byte in HBM). Under the
    paged KV layout an entry PINS whole pool pages (models/paging.py),
    so residency rounds ``p`` up to the page boundary. This is a
    PER-ENTRY charge: nested entries promoted from one prompt share
    physical pages (each holds its own pool reference), and each is
    charged for every page it pins — so the cache-wide sum is an upper
    bound on distinct pages denied to the pool, and the byte budget
    evicts conservatively (never lets the cache outgrow ``budget_bytes``
    of pins, may evict while distinct residency is lower).

    Under tensor-parallel serving (``cfg.tp`` > 1) this is the
    AGGREGATE across shards — each shard resides ``1/tp`` of it
    (parallel/tp_serving.py: entries' rows/pages shard on the KV-head
    axis) — so ``--prefixCacheMB`` keeps meaning total HBM given to the
    cache, and a tp replica's budget buys tp times the entries per
    shard. Entries are mesh-bound: the batcher attach guard refuses a
    cache whose entries were materialized under a different tp."""
    if getattr(cfg, "kv_layout", "dense") == "paged":
        ps = cfg.kv_page_size
        p = -(-p // ps) * ps
    return p * kv_token_bytes(cfg)


class _Node:
    """One radix-tree node at a bucket-boundary depth. ``span`` is the
    edge label from the parent (the tokens between the two boundaries);
    ``entry`` is the materialized PrefixState when this boundary has
    been promoted, None while it is only being hit-counted."""

    __slots__ = ("span", "parent", "children", "entry", "entry_bytes",
                 "hits", "depth")

    def __init__(self, span: tuple, parent: "_Node | None", depth: int):
        self.span = span
        self.parent = parent
        self.children: dict[tuple, _Node] = {}
        self.entry: PrefixState | None = None
        self.entry_bytes = 0
        self.hits = 0
        self.depth = depth


@dataclass
class PrefixCacheStats:
    """Plain counters, exposed via ``stats()`` (and mirrored to the
    prometheus ServingMetrics when one is attached)."""

    hits: int = 0
    misses: int = 0
    evictions: int = 0
    promotions: int = 0
    tokens_saved: int = 0
    resident_bytes: int = 0
    entries: int = 0
    nodes: int = 0

    def as_dict(self) -> dict:
        d = {k: getattr(self, k) for k in (
            "hits", "misses", "evictions", "promotions", "tokens_saved",
            "resident_bytes", "entries", "nodes",
        )}
        total = self.hits + self.misses
        d["hit_rate"] = self.hits / total if total else 0.0
        return d


@dataclass
class PrefixCache:
    """Adapter-aware radix index of prefilled prefixes with an LRU HBM
    budget. The batcher is the only caller: ``match`` at admission,
    ``on_prefill_done`` after each completed prefill."""

    cfg: LlamaConfig
    buckets: tuple[int, ...]
    budget_bytes: int
    #: promotion policy: 1 = always (every completed prefill's boundary
    #: prefixes are materialized), N = only after N sightings
    min_hits: int = 1
    #: the batcher's chunked-prefill size, set by the consuming batcher
    #: at construction. Savings are whole-chunk-granular (the scheduler
    #: dispatches fixed-C chunks from the prefix boundary plus the same
    #: finish chunk either way — effective_prefix_reuse), so matches
    #: that would skip zero dispatches are refused and all reuse
    #: accounting reports skipped dispatch work, not copied rows.
    #: 0 = uncapped (pure-trie tests/benches).
    chunk: int = 0
    metrics: object = None
    #: entry constructor, rebound by a PAGED batcher: under
    #: kv_layout="paged" the extractor returns page ids (zero-copy
    #: aliasing) and entries are PagedPrefixState; dense stays the
    #: row-copying PrefixState. Same kwargs either way.
    entry_factory: object = PrefixState
    #: eviction hook, bound by a paged batcher: an evicted entry's page
    #: references must return to the pool (dense entries are plain
    #: immutable arrays — dropping the reference IS the release)
    release_entry: object = None
    #: host-memory backstop for the hit-counting (unmaterialized) nodes:
    #: beyond this, new prompts stop growing the tree (existing entries
    #: keep matching; the LRU keeps recycling)
    max_nodes: int = 65536
    stats: PrefixCacheStats = field(default_factory=PrefixCacheStats)

    def __post_init__(self):
        self.buckets = tuple(sorted(set(int(b) for b in self.buckets)))
        if not self.buckets:
            raise ValueError("prefix cache needs a non-empty bucket ladder")
        if self.min_hits < 1:
            raise ValueError(f"min_hits must be >= 1, got {self.min_hits}")
        self._roots: dict[int, _Node] = {}  # adapter root; owner: engine
        self._lru: "OrderedDict[_Node, None]" = OrderedDict()  # owner: engine
        self._tracer = get_tracer()

    # --- submit side ---

    def match(self, tokens, adapter: int = -1, count: bool = True):
        """Longest cached prefix of ``tokens`` under ``adapter``, as
        ``(PrefixState, matched_len)`` — or None. The match is capped at
        ``len(tokens) - 1``: at least one suffix token must remain for
        the finish chunk to sample the first generated token from.
        Prompts no longer than ``chunk`` never match: the back-scheduled
        finish window would recompute every matched row anyway, making a
        hit pure overhead with phantom savings.

        The batcher calls this once per request, at ADMISSION — past
        validation, past cancel-while-pending, and after any prefix a
        queue-mate's prefill promoted — so hits/misses record exactly
        one final disposition per admitted request. ``count=False``
        splits lookup from disposition: a paged-pool deferral can still
        end in a cancel, so the batcher looks up at the queue head and
        calls :meth:`record_match` only when the request takes a slot
        (prometheus counters cannot un-count a phantom hit)."""
        node = self._roots.get(adapter)
        best: _Node | None = None
        depth = 0
        if node is not None and len(tokens) > self.chunk:
            cap = len(tokens) - 1
            for b in self.buckets:
                if b > cap:
                    break
                child = node.children.get(tuple(tokens[depth:b]))
                if child is None:
                    break
                node, depth = child, b
                if node.entry is not None:
                    best = node
        if best is not None and self.effective_reuse(
            best.depth, len(tokens)
        ) <= 0:
            # the chunk grid would just shift without skipping a single
            # dispatch (savings are whole-chunk-granular): a hit here is
            # pure copy overhead, so it counts — and serves — as a miss
            best = None
        if best is None:
            if count:
                self.record_match(None, len(tokens), adapter)
            return None
        # keep the entry warm even on an uncounted lookup: a deferred
        # request is about to alias it, so the LRU (and the paged pool's
        # pressure-relief eviction) should treat it as just-used
        self._lru.move_to_end(best)
        if count:
            self.record_match(best.depth, len(tokens), adapter)
        return best.entry, best.depth

    def record_match(self, depth: "int | None", prompt_len: int,
                     adapter: int = -1) -> None:
        """Record one request's final hit/miss disposition (``depth`` is
        the matched prefix length, None for a miss). Split from
        :meth:`match` so the paged batcher can commit it at slot
        assignment rather than at the (cancellable) queue-head lookup."""
        if depth is None:
            self.stats.misses += 1
            if self.metrics is not None:
                on_miss = getattr(self.metrics, "on_prefix_miss", None)
                if on_miss is not None:
                    on_miss()
            return
        self.stats.hits += 1
        saved = self.effective_reuse(depth, prompt_len)
        self.stats.tokens_saved += saved
        if self.metrics is not None:
            on_hit = getattr(self.metrics, "on_prefix_hit", None)
            if on_hit is not None:
                on_hit(saved)
        if self._tracer.enabled:
            self._tracer.span(
                "prefix_match", component="prefix_cache",
                matched=depth, saved=saved, prompt_len=prompt_len,
                adapter=adapter,
            ).end()

    def effective_reuse(self, matched: int, prompt_len: int) -> int:
        """This cache's view of :func:`effective_prefix_reuse` (the one
        definition of the finish-window cap, models/batching.py)."""
        return effective_prefix_reuse(matched, prompt_len, self.chunk)

    # --- promotion side ---

    def on_prefill_done(self, tokens, adapter: int, extract) -> None:
        """A request's prefill just completed: walk/grow its boundary
        path, bump hit counts, and materialize every boundary that
        crossed ``min_hits`` and fits the budget. ``extract(p)`` returns
        the slot's first ``p`` KV rows as a (L, 1, p, Hkv, hd) KVCache —
        the batcher's jitted slice, one compile per boundary."""
        if self.budget_bytes <= 0:
            return
        root = self._roots.get(adapter)
        if root is None:
            root = self._roots[adapter] = _Node((), None, 0)
            self.stats.nodes += 1
        node, depth = root, 0
        # one presence mask per WALK, extended incrementally: boundary
        # b's mask covers tokens[:b], so each materialization scatters
        # only the tokens since the last one instead of rebuilding a
        # (V,) mask from scratch per boundary (engine-thread host work)
        presence_np = covered = None
        for b in self.buckets:
            if b > len(tokens):
                break
            span = tuple(tokens[depth:b])
            child = node.children.get(span)
            if child is None:
                if self.stats.nodes >= self.max_nodes:
                    return
                child = _Node(span, node, b)
                node.children[span] = child
                self.stats.nodes += 1
            node, depth = child, b
            node.hits += 1
            if node.entry is None and node.hits >= self.min_hits:
                if presence_np is None:
                    presence_np = np.zeros((self.cfg.vocab_size,), bool)
                    covered = 0
                presence_np[np.asarray(tokens[covered:b], np.int64)] = True
                covered = b
                self._materialize(node, tokens[:b], adapter, extract,
                                  presence_np)

    def _materialize(self, node: _Node, tokens, adapter: int, extract,
                     presence_np) -> None:
        nbytes = prefix_kv_bytes(self.cfg, node.depth)
        if nbytes > self.budget_bytes:
            return  # an uncacheable giant must not wipe the whole LRU
        while self.stats.resident_bytes + nbytes > self.budget_bytes:
            # keep=node: the eviction's prune cascade must not detach
            # the (entry-less, possibly still childless) node this very
            # call is materializing — pruning it mid-walk would leave
            # the promotion writing into a subtree the matcher can no
            # longer reach, and a later eviction of that orphan would
            # try to delete a span its parent no longer holds
            self._evict_lru(keep=node)
        node.entry = self.entry_factory(
            rows=extract(node.depth), tokens=tuple(tokens),
            # jnp.asarray copies NOW, so the walk extending presence_np
            # for the next boundary cannot alias this entry's mask
            presence=jnp.asarray(presence_np), adapter=adapter,
        )
        node.entry_bytes = nbytes
        self._lru[node] = None
        self.stats.promotions += 1
        self.stats.entries += 1
        self.stats.resident_bytes += nbytes
        self._report_residency()
        if self._tracer.enabled:
            self._tracer.span(
                "prefix_promote", component="prefix_cache",
                prefix_len=node.depth, bytes=nbytes, adapter=adapter,
                hits=node.hits,
            ).end()

    # --- eviction ---

    def reset(self) -> None:
        """Drop every node and entry WITHOUT the release hook — the
        crash-recovery path (serving/supervisor.py): a paged entry's
        page ids index the pool of the batcher that promoted them, and
        after an engine crash that pool no longer exists (running
        ``release_entry`` against a fresh pool would decref pages it
        never allocated). Cumulative counters (hits/misses/evictions)
        survive; residency zeroes. The next batcher attach rebinds the
        entry factory and hooks as usual."""
        self._roots.clear()
        self._lru.clear()
        self.stats.nodes = 0
        self.stats.entries = 0
        self.stats.resident_bytes = 0
        self._report_residency()

    def evict_adapter(self, adapter: int) -> int:
        """Drop one adapter's ENTIRE root — every entry and every
        hit-counting node under it — returning the entry count evicted.
        The unregister path (``ContinuousBatcher.unregister_adapter``):
        an unregistered adapter's index can never match again, so its
        cached K/V is dead weight that would otherwise LEAK until LRU
        pressure happened to reach it. Entries release through the same
        hook/accounting as LRU eviction (paged entries' pages return to
        the pool); a no-op (0) when the adapter never promoted."""
        root = self._roots.pop(adapter, None)
        if root is None:
            return 0
        evicted = nodes = 0
        stack = [root]
        while stack:
            node = stack.pop()
            nodes += 1
            stack.extend(node.children.values())
            if node.entry is None:
                continue
            freed = node.entry_bytes
            if self.release_entry is not None:
                self.release_entry(node.entry)
            node.entry = None
            node.entry_bytes = 0
            self._lru.pop(node, None)
            evicted += 1
            self.stats.evictions += 1
            self.stats.entries -= 1
            self.stats.resident_bytes -= freed
            if self.metrics is not None:
                on_evict = getattr(self.metrics, "on_prefix_evict", None)
                if on_evict is not None:
                    on_evict(freed)
        self.stats.nodes -= nodes
        self._report_residency()
        if self._tracer.enabled:
            self._tracer.span(
                "prefix_evict_adapter", component="prefix_cache",
                adapter=adapter, entries=evicted, nodes=nodes,
            ).end()
        return evicted

    def evict_one(self) -> bool:
        """Evict the least-recently-used entry; False when the cache is
        already empty. The paged batcher's pool-pressure relief valve:
        cached prefixes are reclaimable pool capacity, and without a way
        to reclaim them an idle server whose free pages are all pinned
        by promoted prefixes would defer admissions forever (match-time
        pins keep any prefix a queued request already aliases)."""
        if not self._lru:
            return False
        self._evict_lru()
        return True

    def _evict_lru(self, keep: "_Node | None" = None) -> None:
        node, _ = self._lru.popitem(last=False)
        freed, depth = node.entry_bytes, node.depth
        if self.release_entry is not None:
            # paged layout: give the entry's page references back to the
            # pool BEFORE the tree forgets it (requests that already
            # matched hold their own pins, so this never frees rows a
            # mid-flight admission is about to alias)
            self.release_entry(node.entry)
        node.entry = None
        node.entry_bytes = 0
        self.stats.evictions += 1
        self.stats.entries -= 1
        self.stats.resident_bytes -= freed
        # prune entry-less leaves so the tree doesn't accumulate dead
        # paths (their hit counts go with them — a pruned prefix starts
        # cold again, which is what LRU eviction means). ``keep`` guards
        # the node a _materialize in progress is about to fill; the
        # identity check makes pruning safe even if a stale orphan ever
        # reaches the LRU — deleting a SPAN rather than THIS node would
        # sever a live branch.
        while (
            node is not None and node is not keep and node.entry is None
            and not node.children and node.parent is not None
            and node.parent.children.get(node.span) is node
        ):
            del node.parent.children[node.span]
            self.stats.nodes -= 1
            node = node.parent
        self._report_residency()
        if self.metrics is not None:
            on_evict = getattr(self.metrics, "on_prefix_evict", None)
            if on_evict is not None:
                on_evict(freed)
        if self._tracer.enabled:
            self._tracer.span(
                "prefix_evict", component="prefix_cache",
                prefix_len=depth, bytes=freed,
            ).end()

    def _report_residency(self) -> None:
        if self.metrics is not None:
            set_res = getattr(self.metrics, "set_prefix_resident_bytes", None)
            if set_res is not None:
                set_res(self.stats.resident_bytes, self.stats.entries)
