"""Prefix-affinity replica router: N engines behaving like one service.

Everything below the router is a single-replica stack (one
``InferenceEngine`` per process, serving/server.py); this is the first
scale-out tier (ROADMAP item 3a): an asyncio HTTP front end exposing
the SAME native + OpenAI surfaces, fanning requests out to N replica
backends. Three decisions per request, in order:

1. **Affinity** (``--policy affinity``, the default): the request's
   bucket-aligned token-prefix path (serving/fleet.py
   :func:`~k8s_gpu_device_plugin_tpu.serving.fleet.affinity_key` — the
   same ``prompt_buckets`` boundaries the prefix cache promotes at)
   hashes onto a consistent-hash ring; the first ring candidate is the
   key's HOME, where its cached prefix lives. Routing a shared-system-
   prompt tenant anywhere else re-pays the whole prefill — placement is
   semantically load-bearing, not just balancing. ``--policy rr``
   round-robins instead (the A/B arm serve_bench measures against).
2. **Bounded load**: a home drowning in work must spill — the classic
   consistent-hashing-with-bounded-loads rule: a candidate already
   carrying more than ``load_factor`` x the fleet's mean in-flight
   count is skipped for the next ring candidate (so spill traffic is
   deterministic too, not scattered).
3. **Failover**: a connection failure or 429 moves to the next ring
   candidate. 429s honor ``Retry-After`` — the replica is cooled down
   for that long, so a whole burst doesn't re-probe a replica that
   just said "not now". Only failures BEFORE response headers are
   retried: once a stream has started, replaying it would duplicate
   tokens the client already consumed, so a mid-stream death surfaces
   as the stream closing (the client's retry is the safe one).

Liveness comes from polling each replica's ``/v1/health`` (the queue
depth / kv pool pressure / sched stats the engines already export):
``dead_after`` consecutive failures (poll or proxy) mark a replica
dead and routing skips it; any success revives it. Fleet operations:

- ``POST /fleet/drain/{replica}``: stop NEW admissions to a replica
  (the router is the fleet's admission seam, the same valve the
  scheduler's queue cap rides inside one replica) and wait until its
  in-flight streams retire — the rolling-update primitive. Returns
  ``drain_seconds``; 504 with ``drained: false`` past
  ``drain_timeout_s``.
- ``POST /fleet/undrain/{replica}``: restore admission.
- ``GET /fleet/health``: the aggregate (per-replica liveness, drain
  state, in-flight, health digest) + the router's own counters.

When NO replica can admit, submits are refused with a structured 503 —
``{"code": "draining"}`` when drains caused it (both API surfaces:
native top-level code, OpenAI error envelope), ``{"code":
"no_replica"}`` when the fleet is dead. When every candidate answered
429, the LAST 429 (body + Retry-After) is forwarded — overload is the
backend's message to deliver, not the router's to invent.

The proxy is byte-transparent: request bodies are forwarded exactly as
received and response bodies/SSE frames are relayed unmodified, so
token/logprob streams through the router are bit-identical to
direct-to-replica submission (pinned in tests/test_router.py). Spans
propagate via W3C ``traceparent`` — the router's proxy span becomes
the remote parent of the replica's ``serving_http`` span, so one trace
covers edge -> router -> replica -> engine.

Event-loop discipline: the router is single-threaded asyncio end to
end — backend I/O rides one shared aiohttp ClientSession, waits are
``asyncio.sleep``, and the blocking-in-async graftlint checker keeps
it that way (the firing fixture covers exactly this proxy shape).
"""

from __future__ import annotations

import asyncio
import json
import math
import time

import aiohttp
from aiohttp import web

from k8s_gpu_device_plugin_tpu.serving.faults import FaultError
from k8s_gpu_device_plugin_tpu.serving.fleet import (
    FleetRegistry,
    HashRing,
    Replica,
    affinity_key,
)
from k8s_gpu_device_plugin_tpu.obs.trace import (
    TRACEPARENT_HEADER,
    format_traceparent,
    get_tracer,
    parse_traceparent,
)
from k8s_gpu_device_plugin_tpu.utils.log import get_logger

log = get_logger()

#: proxied POST surfaces (both APIs; the router adds nothing of its own
#: to them — byte-transparent by contract)
PROXY_POSTS = (
    "/v1/generate", "/v1/completions", "/v1/chat/completions",
    "/v1/embeddings",
)


class RouterMetrics:
    """Prometheus mirror of the router's counters (optional — the plain
    ``router_stats()`` snapshot always exists). Collector names are
    fixed; call :meth:`close` before building a second instance on the
    same registry (tests, restarts)."""

    def __init__(self, registry=None, prefix: str = "tpu_router"):
        from prometheus_client import REGISTRY, Counter, Gauge

        self._registry = registry if registry is not None else REGISTRY
        self.requests = Counter(
            f"{prefix}_requests_total",
            "Requests relayed, by replica and outcome",
            ["replica", "outcome"],
            registry=self._registry,
        )
        self.affinity_hits = Counter(
            f"{prefix}_affinity_hits_total",
            "Requests dispatched to their ring-home replica",
            registry=self._registry,
        )
        self.failovers = Counter(
            f"{prefix}_failovers_total",
            "Dispatch attempts beyond the first candidate "
            "(connection failure or 429 moved the request on)",
            registry=self._registry,
        )
        self.inflight = Gauge(
            f"{prefix}_inflight",
            "Requests currently relayed to each replica",
            ["replica"],
            registry=self._registry,
        )
        self.replica_up = Gauge(
            f"{prefix}_replica_up",
            "1 = replica routable (alive, not draining, not cooling down)",
            ["replica"],
            registry=self._registry,
        )

    def close(self) -> None:
        for c in (self.requests, self.affinity_hits, self.failovers,
                  self.inflight, self.replica_up):
            try:
                self._registry.unregister(c)
            except KeyError:
                pass  # already unregistered


class _Unreachable(Exception):
    """Connection-level failure before response headers: safe to retry
    the next ring candidate (no bytes reached the client)."""


class _Overloaded(Exception):
    """Backend answered 429: cool the replica down for Retry-After and
    try the next candidate; forwarded verbatim if every candidate 429s."""

    def __init__(self, body: bytes, retry_after: int, content_type: str):
        super().__init__("backend overloaded")
        self.body = body
        self.retry_after = retry_after
        self.content_type = content_type


class ReplicaRouter:
    """aiohttp app over a FleetRegistry (port 0 = ephemeral)."""

    def __init__(
        self,
        fleet: FleetRegistry,
        host: str = "0.0.0.0",
        port: int = 8100,
        policy: str = "affinity",
        prompt_buckets: "tuple[int, ...] | None" = None,  # None = the
        # batcher's DEFAULT_PROMPT_BUCKETS ladder — affinity keys are
        # only load-bearing when they cut at the boundaries the
        # replicas' prefix caches promote at, so a fleet whose replicas
        # run custom buckets (or a small --maxLen trimming the ladder)
        # must pass the same ladder here (--promptBuckets on the CLI)
        load_factor: float = 1.25,
        health_interval_s: float = 1.0,
        drain_timeout_s: float = 120.0,
        connect_timeout_s: float = 2.0,
        header_timeout_s: float = 300.0,  # finite: a wedged replica
        # must fail over, not hang the client forever (0 = unbounded)
        registry=None,          # prometheus registry (None = no /metrics)
        metrics: "RouterMetrics | None" = None,
        faults=None,            # serving.faults.FaultPlane (None = disarmed)
    ):
        if policy not in ("affinity", "rr"):
            raise ValueError(
                f"unknown router policy {policy!r} "
                "(expected 'affinity' or 'rr')"
            )
        if load_factor <= 1.0:
            raise ValueError(
                f"load_factor must be > 1.0, got {load_factor} "
                "(1.0 would refuse the mean load itself)"
            )
        self.fleet = fleet
        self.ring = HashRing(fleet.ids())
        self.host = host
        self.port = port
        self.bound_port: int | None = None
        self.policy = policy
        if prompt_buckets is None:
            from k8s_gpu_device_plugin_tpu.models.batching import (
                DEFAULT_PROMPT_BUCKETS,
            )

            prompt_buckets = DEFAULT_PROMPT_BUCKETS
        self.prompt_buckets = tuple(sorted(prompt_buckets))
        self.load_factor = float(load_factor)
        self.health_interval_s = float(health_interval_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)
        # bound the HEADER phase of a dispatch (a wedged replica whose
        # socket accepts but never answers must fail over like a
        # connection failure, not hang the client forever — which is
        # exactly what an unbounded default did). The default sits
        # ABOVE the worst legitimate case — a non-streamed generate's
        # headers arrive only when generation completes, minutes on a
        # cold compile, so 5 minutes clears it; a premature timeout
        # would cascade failovers across a healthy fleet. Operators
        # who stream (headers arrive at prepare time) can set this
        # tight; 0 restores unbounded.
        self.header_timeout_s = float(header_timeout_s)
        # seeded fault injection (serving/faults.py): the two
        # router-side seams — pre-dispatch connect and mid-SSE-relay
        self._flt_connect = (
            faults.point("router.connect") if faults is not None else None
        )
        self._flt_midstream = (
            faults.point("router.midstream") if faults is not None else None
        )
        self.registry = registry
        self.metrics = metrics
        self.tracer = get_tracer()
        self._rr_next = 0
        # plain counters (always on; RouterMetrics mirrors them): the
        # serve-bench fleet A/B and /fleet/health read these
        self._requests = 0
        self._affinity_hits = 0
        self._failovers = 0
        self._refused: dict[str, int] = {}
        self._outcomes: dict[str, int] = {}
        self._session: aiohttp.ClientSession | None = None
        self._poll_task: asyncio.Task | None = None
        self.app = web.Application(middlewares=[self._trace_middleware])
        for path in PROXY_POSTS:
            self.app.router.add_post(path, self._proxy_post)
        self.app.router.add_get("/v1/models", self._proxy_get)
        self.app.router.add_get("/v1/health", self._health)
        self.app.router.add_get("/fleet/health", self._fleet_health)
        self.app.router.add_post("/fleet/drain/{replica}", self._drain)
        self.app.router.add_post("/fleet/undrain/{replica}", self._undrain)
        if registry is not None:
            self.app.router.add_get("/metrics", self._metrics)

    # --- lifecycle --------------------------------------------------------

    async def run(self, stop: asyncio.Event) -> None:
        """Serve until ``stop`` is set (the InferenceServer idiom)."""
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(
                total=None, connect=self.connect_timeout_s,
            )
        )
        runner = web.AppRunner(self.app)
        try:
            # everything past session creation is inside the try: a bind
            # failure must not leak the session or a live poller into
            # the embedding process (serving/testing.py fleets)
            self._poll_task = asyncio.ensure_future(self._poll_loop())
            await runner.setup()
            site = web.TCPSite(runner, self.host, self.port)
            await site.start()
            self.bound_port = (
                runner.addresses[0][1] if runner.addresses else None
            )
            log.info(
                "replica router listening",
                extra={"fields": {
                    "addr": f"{self.host}:{self.bound_port}",
                    "policy": self.policy,
                    "replicas": self.fleet.ids(),
                }},
            )
            await stop.wait()
        finally:
            if self._poll_task is not None:
                self._poll_task.cancel()
                try:
                    await self._poll_task
                except asyncio.CancelledError:
                    pass
                self._poll_task = None
            await runner.cleanup()
            await self._session.close()
            self._session = None

    # --- health polling ---------------------------------------------------

    async def _probe_health(self, rep: Replica) -> dict | None:
        """One /v1/health contact, feeding the liveness ledger either
        way: a 200 payload revives the replica, anything else (engine
        dead behind a live socket, unreachable, garbage JSON) counts a
        failure. The poll loop AND the drain wait share this."""
        try:
            async with self._session.get(
                f"{rep.url}/v1/health",
                timeout=aiohttp.ClientTimeout(total=self.connect_timeout_s),
            ) as resp:
                if resp.status != 200:
                    self.fleet.note_failure(rep)
                    return None
                # ValueError covers json.JSONDecodeError (a truncated
                # body must count as a failed probe, not kill the poller)
                health = await resp.json()
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                ValueError):
            self.fleet.note_failure(rep)
            return None
        self.fleet.note_success(rep, health)
        return health

    async def _poll_one(self, rep: Replica) -> None:
        """One replica's probe, hardened: ANY unexpected exception (a
        raising metrics callback, a pathological payload — anything
        _probe_health's expected-failure net doesn't catch) counts a
        liveness failure for THIS replica and never reaches the poll
        loop — one bad replica must not blind routing to the rest."""
        try:
            await self._probe_health(rep)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 - a dead poller blinds routing
            log.exception(
                "health probe failed unexpectedly",
                extra={"fields": {"replica": rep.rid}},
            )
            self.fleet.note_failure(rep)

    async def _poll_loop(self) -> None:
        while True:
            try:
                await asyncio.gather(
                    *(self._poll_one(r) for r in self.fleet.all())
                )
                if self.metrics is not None:
                    now = time.monotonic()
                    for r in self.fleet.all():
                        self.metrics.replica_up.labels(r.rid).set(
                            1 if r.routable(now) else 0
                        )
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 - a dead poller blinds routing
                log.exception("router health poll pass failed")
            await asyncio.sleep(self.health_interval_s)

    # --- tracing ----------------------------------------------------------

    @web.middleware
    async def _trace_middleware(self, request: web.Request, handler):
        if not self.tracer.enabled:
            return await handler(request)
        from k8s_gpu_device_plugin_tpu.obs.http import route_label

        remote = parse_traceparent(request.headers.get(TRACEPARENT_HEADER))
        with self.tracer.span(
            f"{request.method} {route_label(request)}",
            component="router_http",
            parent=remote, method=request.method, path=request.path,
        ) as span:
            try:
                response = await handler(request)
            except web.HTTPException as http_err:
                span.set(status_code=http_err.status)
                http_err.headers[TRACEPARENT_HEADER] = format_traceparent(span)
                raise
            span.set(status_code=response.status)
            if not response.prepared:  # SSE relays already sent headers
                response.headers[TRACEPARENT_HEADER] = format_traceparent(span)
            return response

    def _backend_headers(self, request: web.Request) -> dict:
        headers = {
            "Content-Type": request.headers.get(
                "Content-Type", "application/json"
            ),
        }
        if self.tracer.enabled:
            from k8s_gpu_device_plugin_tpu.obs.trace import current_context

            ctx = current_context()
            if ctx is not None:
                # the router span becomes the replica span's remote
                # parent: one trace covers edge -> router -> engine
                headers[TRACEPARENT_HEADER] = format_traceparent(ctx)
        return headers

    # --- routing ----------------------------------------------------------

    def _affinity_source(self, path: str, body) -> object | None:
        """The prefix-bearing field of each surface. Chat messages key
        on the serialized message list — the system prompt + history
        prefix is its head, which is exactly what the replica's prefix
        cache will match after templating."""
        if not isinstance(body, dict):
            return None
        if path == "/v1/generate":
            return body.get("prompt") or body.get("text")
        if path == "/v1/completions":
            return body.get("prompt")
        if path == "/v1/chat/completions":
            return body.get("messages")
        return None  # embeddings: no KV reuse — balance only

    def _pick(
        self, key: bytes | None
    ) -> tuple[list[Replica], "Replica | None"]:
        """-> (dispatch order, the key's ring HOME or None). Affinity
        walks the ring from the key's point and applies the
        bounded-load skip; rr (or a keyless request) rotates /
        least-loads over the live set. An empty list means nobody can
        admit right now."""
        now = time.monotonic()
        live = [r for r in self.fleet.all() if r.routable(now)]
        if not live:
            # cooldown is ADVICE, not refusal: with every candidate
            # cooling down from a 429, the backend's own 429 (fresh
            # Retry-After included) is the right answer — not a made-up
            # 503. Draining/dead replicas stay excluded.
            live = [
                r for r in self.fleet.all()
                if r.alive and not r.draining
            ]
        if not live:
            return [], None
        usable = set(id(r) for r in live)
        if self.policy == "rr" or key is None:
            self._rr_next += 1
            i = self._rr_next % len(live)
            return live[i:] + live[:i], None
        ring_order = [
            self.fleet.get(rid) for rid in self.ring.candidates(key)
        ]
        home = ring_order[0] if ring_order else None
        order = [
            r for r in ring_order if r is not None and id(r) in usable
        ]
        if not order:
            return [], None
        # bounded load: a candidate already past load_factor x the mean
        # in-flight spills to the NEXT ring candidate (deterministic
        # spill target), never to a random replica
        cap = max(2.0, math.ceil(
            self.load_factor * (sum(r.inflight for r in live) + 1)
            / len(live)
        ))
        target = next((r for r in order if r.inflight < cap), None)
        if target is None:
            target = min(order, key=lambda r: r.inflight)
        rest = [r for r in order if r is not target]
        return [target] + rest, home

    # --- refusals (per-surface shapes) ------------------------------------

    def _refuse(self, path: str, code: str, message: str,
                status: int = 503) -> web.Response:
        self._refused[code] = self._refused.get(code, 0) + 1
        if self.metrics is not None:
            self.metrics.requests.labels("none", code).inc()
        if path == "/v1/generate":
            # the native structured-error shape (the 429 body's sibling)
            return web.json_response(
                {"error": message, "code": code}, status=status
            )
        # OpenAI envelope; 5xx reads as retryable server_error, which is
        # what a drain IS from the client's side — retry lands post-drain
        return web.json_response(
            {"error": {"message": message, "type": "server_error",
                       "code": code}},
            status=status,
        )

    # --- the proxy --------------------------------------------------------

    async def _proxy_post(self, request: web.Request) -> web.StreamResponse:
        raw = await request.read()
        try:
            body = json.loads(raw) if raw else None
        except json.JSONDecodeError:
            body = None  # the backend's 400 is the authoritative answer
        key = affinity_key(
            self._affinity_source(request.path, body), self.prompt_buckets
        )
        order, home = self._pick(key)
        if not order:
            if self.fleet.any_draining():
                return self._refuse(
                    request.path, "draining",
                    "all replicas are draining; retry after the rolling "
                    "update completes",
                )
            return self._refuse(
                request.path, "no_replica",
                "no live replica available",
            )
        self._requests += 1
        headers = self._backend_headers(request)
        last_429: _Overloaded | None = None
        for attempt, rep in enumerate(order):
            if attempt > 0:
                self._failovers += 1
                if self.metrics is not None:
                    self.metrics.failovers.inc()
            rep.inflight += 1
            if self.metrics is not None:
                self.metrics.inflight.labels(rep.rid).set(rep.inflight)
            try:
                resp = await self._relay(rep, request, raw, headers)
            except _Unreachable:
                self.fleet.note_failure(rep)
                self._count(rep, "unreachable")
                continue
            except _Overloaded as e:
                rep.cooldown_until = time.monotonic() + e.retry_after
                self._count(rep, "overloaded")
                last_429 = e
                continue
            finally:
                rep.inflight -= 1
                if self.metrics is not None:
                    self.metrics.inflight.labels(rep.rid).set(rep.inflight)
            if resp.status < 500:
                # only app-level answers prove the engine alive; a 5xx
                # (dead engine behind a live socket) must keep counting
                # toward dead_after or steady traffic would reset the
                # ledger faster than the poller can fail it
                self.fleet.note_success(rep)
            else:
                self.fleet.note_failure(rep)
            if rep is home:
                # counted on the SERVING dispatch, not at plan time: a
                # home that failed over is a miss for cache locality
                self._affinity_hits += 1
                if self.metrics is not None:
                    self.metrics.affinity_hits.inc()
            self._count(rep, self._outcome(resp.status))
            return resp
        if last_429 is not None:
            # every candidate said "not now": deliver the backend's own
            # overload message + Retry-After, don't invent a new one
            return web.Response(
                body=last_429.body, status=429,
                content_type=last_429.content_type,
                headers={"Retry-After": str(last_429.retry_after)},
            )
        return self._refuse(
            request.path, "no_replica",
            "every replica is unreachable",
        )

    @staticmethod
    def _outcome(status: int) -> str:
        if status < 400:
            return "ok"
        return "client_error" if status < 500 else "backend_error"

    def _count(self, rep: Replica, outcome: str) -> None:
        rep.relayed += 1
        self._outcomes[outcome] = self._outcomes.get(outcome, 0) + 1
        if self.metrics is not None:
            self.metrics.requests.labels(rep.rid, outcome).inc()

    async def _relay(self, rep: Replica, request: web.Request,
                     raw: bytes, headers: dict) -> web.StreamResponse:
        """One dispatch attempt: forward the body verbatim, relay the
        response (SSE streamed frame-by-frame, JSON in one piece).
        Raises _Unreachable/_Overloaded for the failover loop; anything
        past response headers is final."""
        url = f"{rep.url}{request.path}"
        if self._flt_connect is not None:
            try:
                self._flt_connect.fire()
            except FaultError as e:
                # injected connection failure: the failover loop moves
                # to the next ring candidate, like a real refusal
                raise _Unreachable(str(e)) from None
        try:
            post = self._session.post(url, data=raw, headers=headers)
            if self.header_timeout_s > 0:
                # session.post resolves when response HEADERS arrive, so
                # this bounds exactly the header phase — the body/SSE
                # relay stays unbounded (legitimate long generations)
                resp = await asyncio.wait_for(post, self.header_timeout_s)
            else:
                resp = await post
        except (aiohttp.ClientError, asyncio.TimeoutError,
                ConnectionResetError, OSError) as e:
            raise _Unreachable(str(e)) from None
        try:
            if resp.status == 429:
                body = await resp.read()
                try:
                    ra = int(resp.headers.get("Retry-After", "1"))
                except ValueError:
                    ra = 1
                raise _Overloaded(
                    body, max(1, ra),
                    resp.headers.get("Content-Type", "application/json")
                    .split(";")[0],
                )
            ctype = resp.headers.get("Content-Type", "")
            if ctype.startswith("text/event-stream"):
                out = web.StreamResponse(headers={
                    "Content-Type": "text/event-stream",
                    "Cache-Control": "no-cache",
                })
                await out.prepare(request)
                # byte-transparent relay: frames forwarded as received,
                # so the stream is bit-identical to direct submission
                async for chunk in resp.content.iter_any():
                    await out.write(chunk)
                    if self._flt_midstream is not None:
                        try:
                            self._flt_midstream.fire()
                        except FaultError:
                            # injected mid-relay death: close the
                            # backend HARD and end the client stream
                            # without a done event — a VISIBLE
                            # truncation, never retried (the client
                            # already consumed bytes; replay would
                            # duplicate them)
                            resp.close()
                            return out
                await out.write_eof()
                resp.release()
                return out
            body = await resp.read()
            resp.release()
            return web.Response(
                body=body, status=resp.status,
                content_type=ctype.split(";")[0] or "application/json",
            )
        except (_Overloaded, _Unreachable):
            resp.release()
            raise
        except BaseException:
            # client disconnect / cancellation mid-relay: close the
            # backend connection HARD so the replica sees the disconnect
            # and cancels the generation (release() would try to drain
            # the rest of the stream first)
            resp.close()
            raise

    async def _proxy_get(self, request: web.Request) -> web.Response:
        """GET passthrough (/v1/models): any live replica's answer —
        the fleet serves ONE model, so they all agree. Cooldown AND
        drain are advisory here: both only gate new GENERATION
        admissions, and a cooling or draining replica still serves
        cheap metadata reads — model discovery must not fail for a
        whole rolling-update window."""
        now = time.monotonic()
        candidates = [r for r in self.fleet.all() if r.routable(now)]
        if not candidates:
            candidates = [r for r in self.fleet.all() if r.alive]
        for rep in candidates:
            try:
                async with self._session.get(
                    f"{rep.url}{request.path}",
                    timeout=aiohttp.ClientTimeout(
                        total=self.connect_timeout_s
                    ),
                ) as resp:
                    body = await resp.read()
                    return web.Response(
                        body=body, status=resp.status,
                        content_type=(resp.headers.get("Content-Type", "")
                                      .split(";")[0] or "application/json"),
                    )
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                self.fleet.note_failure(rep)
                continue
        return self._refuse(request.path, "no_replica",
                            "no live replica available")

    # --- fleet operations -------------------------------------------------

    def router_stats(self) -> dict:
        return {
            "policy": self.policy,
            "requests": self._requests,
            "affinity_hits": self._affinity_hits,
            "failovers": self._failovers,
            "refused": dict(self._refused),
            "outcomes": dict(self._outcomes),
        }

    async def _health(self, request: web.Request) -> web.Response:
        """The router's own liveness (LB probes): up as long as at
        least one replica can ADMIT (alive and not draining) — a fleet
        mid-rolling-drain that refuses every submit must fail the
        probe, not smile at it."""
        snap = self.fleet.snapshot()
        admitting = sum(
            1 for r in self.fleet.all() if r.alive and not r.draining
        )
        return web.json_response(
            {"router": True, "alive": admitting > 0,
             "policy": self.policy,
             "replicas": snap["total"], "live": snap["live"],
             "admitting": admitting, "draining": snap["draining"]},
            status=200 if admitting else 503,
        )

    async def _fleet_health(self, request: web.Request) -> web.Response:
        snap = self.fleet.snapshot()
        snap["router"] = self.router_stats()
        return web.json_response(snap)

    async def _drain(self, request: web.Request) -> web.Response:
        rid = request.match_info["replica"]
        rep = self.fleet.get(rid)
        if rep is None:
            return web.json_response(
                {"error": f"unknown replica {rid!r}",
                 "replicas": self.fleet.ids()},
                status=404,
            )
        rep.draining = True
        t0 = time.monotonic()
        log.info("draining replica", extra={"fields": {"replica": rid}})
        while time.monotonic() - t0 < self.drain_timeout_s:
            if rep.inflight == 0:
                # the router-side count says nothing is being relayed;
                # confirm with the replica itself that every admitted
                # request retired (clients that submitted before the
                # drain may still be decoding)
                h = await self._probe_health(rep)
                if h is not None and not (
                    h.get("active", 0) or h.get("prefilling", 0)
                    or h.get("queued", 0)
                ):
                    secs = time.monotonic() - t0
                    log.info(
                        "replica drained",
                        extra={"fields": {"replica": rid,
                                          "drain_seconds": round(secs, 3)}},
                    )
                    return web.json_response({
                        "replica": rid, "draining": True, "drained": True,
                        "drain_seconds": round(secs, 4),
                    })
                if h is None and not rep.alive:
                    # nothing in flight and the replica is gone: as
                    # drained as it will ever be (the restart case)
                    return web.json_response({
                        "replica": rid, "draining": True, "drained": True,
                        "drain_seconds": round(time.monotonic() - t0, 4),
                        "unreachable": True,
                    })
            await asyncio.sleep(0.05)
        return web.json_response(
            {"replica": rid, "draining": True, "drained": False,
             "drain_seconds": round(time.monotonic() - t0, 4)},
            status=504,
        )

    async def _undrain(self, request: web.Request) -> web.Response:
        rid = request.match_info["replica"]
        rep = self.fleet.get(rid)
        if rep is None:
            return web.json_response(
                {"error": f"unknown replica {rid!r}",
                 "replicas": self.fleet.ids()},
                status=404,
            )
        rep.draining = False
        log.info("undrained replica", extra={"fields": {"replica": rid}})
        return web.json_response(
            {"replica": rid, "draining": False}
        )

    async def _metrics(self, request: web.Request) -> web.Response:
        from prometheus_client import generate_latest

        return web.Response(
            body=generate_latest(self.registry), content_type="text/plain"
        )


def _main(argv: list[str] | None = None) -> int:
    """CLI: route two HTTP API surfaces across N replica backends."""
    import argparse

    parser = argparse.ArgumentParser(prog="tpu-replica-router")
    parser.add_argument("--replicas", required=True,
                        help="comma list of replica backends: "
                        "[id=]http://host:port,... (id defaults to "
                        "host:port, matching the replica's own "
                        "--replicaId default)")
    parser.add_argument("--port", type=int, default=8100)
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--policy", default="affinity",
                        choices=["affinity", "rr"],
                        help="'affinity' (default) routes each request's "
                        "bucket-aligned token-prefix path onto a "
                        "consistent-hash ring with bounded-load spill, "
                        "so shared-prefix tenants land where their "
                        "prefix cache lives; 'rr' round-robins (the "
                        "serve-bench A/B arm)")
    parser.add_argument("--loadFactor", type=float, default=1.25,
                        help="bounded-load spill: a ring candidate "
                        "already carrying more than this times the "
                        "fleet's mean in-flight count spills to the "
                        "next candidate")
    parser.add_argument("--healthIntervalS", type=float, default=1.0,
                        help="replica /v1/health poll cadence")
    parser.add_argument("--deadAfter", type=int, default=3,
                        help="consecutive health/proxy failures before a "
                        "replica is routed around (any success revives)")
    parser.add_argument("--drainTimeoutS", type=float, default=120.0,
                        help="POST /fleet/drain/{replica} gives up (504, "
                        "drained:false) after this long")
    parser.add_argument("--promptBuckets", default="",
                        help="comma list of prompt-bucket boundaries "
                        "for the affinity key (default: the batcher's "
                        "DEFAULT_PROMPT_BUCKETS ladder). MUST match the "
                        "replicas' effective ladder — custom buckets or "
                        "a small --maxLen trimming it — or affinity "
                        "keys cut where no cache ever promotes")
    parser.add_argument("--headerTimeoutS", type=float, default=300.0,
                        help="bound the header phase of a dispatch so a "
                        "wedged replica (socket accepts, never answers) "
                        "fails over like a connection failure within "
                        "the timeout instead of hanging the client "
                        "forever; the default sits above a non-streamed "
                        "generate's cold-compile worst case (headers "
                        "arrive only at completion — minutes); 0 "
                        "restores unbounded")
    parser.add_argument("--faults", default="",
                        help="seeded fault injection (serving/faults.py) "
                        "for the router-side points router.connect / "
                        "router.midstream, e.g. 'router.connect:nth=2'; "
                        "also read from TPU_SERVING_FAULTS; empty = "
                        "disarmed")
    parser.add_argument("--tracing", action="store_true",
                        help="span tracing: router spans propagate to "
                        "the replicas via traceparent")
    args = parser.parse_args(argv)

    if args.tracing:
        from k8s_gpu_device_plugin_tpu.obs.prom import SpanMetrics
        from k8s_gpu_device_plugin_tpu.obs.trace import configure
        from prometheus_client import REGISTRY as _SPAN_REGISTRY

        SpanMetrics(registry=_SPAN_REGISTRY).install(configure(enabled=True))

    from prometheus_client import REGISTRY

    buckets = None
    if args.promptBuckets:
        try:
            buckets = tuple(
                int(b) for b in args.promptBuckets.split(",") if b.strip()
            )
        except ValueError:
            raise SystemExit(
                f"--promptBuckets {args.promptBuckets!r}: expected a "
                "comma list of integers"
            ) from None

    from k8s_gpu_device_plugin_tpu.serving.faults import FaultPlane

    fault_plane = FaultPlane.from_cli(args.faults)

    fleet = FleetRegistry.from_spec(args.replicas, dead_after=args.deadAfter)
    router = ReplicaRouter(
        fleet, host=args.host, port=args.port, policy=args.policy,
        prompt_buckets=buckets,
        load_factor=args.loadFactor,
        health_interval_s=args.healthIntervalS,
        drain_timeout_s=args.drainTimeoutS,
        header_timeout_s=args.headerTimeoutS,
        registry=REGISTRY, metrics=RouterMetrics(registry=REGISTRY),
        faults=fault_plane,
    )

    async def serve():
        stop = asyncio.Event()
        import signal

        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await router.run(stop)

    asyncio.run(serve())
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
