"""Prefix-affinity replica router: N engines behaving like one service.

Everything below the router is a single-replica stack (one
``InferenceEngine`` per process, serving/server.py); this is the first
scale-out tier (ROADMAP item 3a): an asyncio HTTP front end exposing
the SAME native + OpenAI surfaces, fanning requests out to N replica
backends. Three decisions per request, in order:

1. **Affinity** (``--policy affinity``, the default): the request's
   bucket-aligned token-prefix path (serving/fleet.py
   :func:`~k8s_gpu_device_plugin_tpu.serving.fleet.affinity_key` — the
   same ``prompt_buckets`` boundaries the prefix cache promotes at)
   hashes onto a consistent-hash ring; the first ring candidate is the
   key's HOME, where its cached prefix lives. Routing a shared-system-
   prompt tenant anywhere else re-pays the whole prefill — placement is
   semantically load-bearing, not just balancing. ``--policy rr``
   round-robins instead (the A/B arm serve_bench measures against).
2. **Bounded load**: a home drowning in work must spill — the classic
   consistent-hashing-with-bounded-loads rule: a candidate already
   carrying more than ``load_factor`` x the fleet's mean in-flight
   count is skipped for the next ring candidate (so spill traffic is
   deterministic too, not scattered).
3. **Failover**: a connection failure or 429 moves to the next ring
   candidate. 429s honor ``Retry-After`` (delta-seconds AND RFC 9110
   HTTP-dates) — the replica is cooled down for that long, so a whole
   burst doesn't re-probe a replica that just said "not now". Failures
   BEFORE response headers retry the next candidate; a mid-stream
   replica death on a journaled native SSE stream RESUMES (below);
   everything else surfaces as the stream closing visibly.

**Cross-replica stream resume** — the fleet tier's recovery guarantee,
mirroring what the engine supervisor gives one replica: no client-
visible stream dies because a replica did. Each native token-id SSE
stream carries a journal (body, sampling seed, every token/logprob
relayed — single-writer, bounded); on a mid-stream replica death the
router resubmits through the native ``resume_out`` seam (emitted
tokens folded into the prompt via the preemption fold, so greedy AND
seeded continuations are bit-identical) to the next ring candidate and
splices the continuation into the SAME client response with zero
re-emitted tokens. Resumes are budgeted per replica DEATH
(``--fleetRestartBudget`` / ``--fleetRestartWindowS``, the
supervisor's rolling-budget shape); past the budget the stream ends
with the PR-12 structured error frame — never a silent truncation.

**Warm spares** (``--warmSpares N``): the last N ``--replicas``
entries stay registered and health-polled but OFF the ring; when an
active replica is marked dead, a spare is promoted in its place (ring
rebuilt, affinity keys remap the consistent-hashing way), surfaced on
``/fleet/health`` and ``tpu_router_promotions_total``. A revived
ex-active re-enters as a spare.

**Rolling restart** (``POST /fleet/rolling-restart``): drain →
restart-wait → undrain sequenced across the fleet, one replica at a
time — the weight-update maintenance cycle with zero dropped and zero
from-scratch-retried streams.

**Fleet observability plane** (obs/fleet_obs.py): the router is also
the fleet's aggregation point — ``GET /fleet/debug/traces/{id}``
stitches a trace's span fragments from every replica (plus the
router's own ring, also served on ``GET /debug/traces`` with the
shared ``?limit=``/``?since=`` surface) into ONE Perfetto document
with a process row per replica; ``GET /fleet/metrics`` federates the
replicas' ``/metrics`` under a ``replica`` label (OpenMetrics
exemplars preserved) with fleet MFU/bandwidth/latency aggregates;
``GET /fleet/events`` is the journal of every fleet operation
(failover, cooldown, drain, promotion, stream resume, rolling-restart
phases — deterministic under the seeded fault plane); and
``GET /fleet/debug/requests`` serves per-stream router timelines whose
route/relay/resume-gap segments sum EXACTLY to the client-observed
wall time, retained for resumed/failed-over/SLO-breaching streams by
a flight recorder.

Liveness comes from polling each replica's ``/v1/health`` (the queue
depth / kv pool pressure / sched stats the engines already export):
``dead_after`` consecutive failures (poll or proxy) mark a replica
dead and routing skips it; any success revives it. Fleet operations:

- ``POST /fleet/drain/{replica}``: stop NEW admissions to a replica
  (the router is the fleet's admission seam, the same valve the
  scheduler's queue cap rides inside one replica) and wait until its
  in-flight streams retire — the rolling-update primitive. Returns
  ``drain_seconds``; 504 with ``drained: false`` past
  ``drain_timeout_s``.
- ``POST /fleet/undrain/{replica}``: restore admission.
- ``GET /fleet/health``: the aggregate (per-replica liveness, drain
  state, in-flight, health digest) + the router's own counters.

When NO replica can admit, submits are refused with a structured 503 —
``{"code": "draining"}`` when drains caused it (both API surfaces:
native top-level code, OpenAI error envelope), ``{"code":
"no_replica"}`` when the fleet is dead. When every candidate answered
429, the LAST 429 (body + Retry-After) is forwarded — overload is the
backend's message to deliver, not the router's to invent.

The proxy is byte-transparent: request bodies are forwarded exactly as
received and response bodies/SSE frames are relayed unmodified, so
token/logprob streams through the router are bit-identical to
direct-to-replica submission (pinned in tests/test_router.py). Spans
propagate via W3C ``traceparent`` — the router's proxy span becomes
the remote parent of the replica's ``serving_http`` span, so one trace
covers edge -> router -> replica -> engine.

Event-loop discipline: the router is single-threaded asyncio end to
end — backend I/O rides one shared aiohttp ClientSession, waits are
``asyncio.sleep``, and the blocking-in-async graftlint checker keeps
it that way (the firing fixture covers exactly this proxy shape).
"""

from __future__ import annotations

import asyncio
import json
import math
import time

import aiohttp
from aiohttp import web

from k8s_gpu_device_plugin_tpu.serving.faults import FaultError
from k8s_gpu_device_plugin_tpu.serving.fleet import (
    FleetRegistry,
    FleetRestartBudget,
    HashRing,
    Replica,
    affinity_key,
    parse_retry_after,
    poll_phase,
)
from k8s_gpu_device_plugin_tpu.obs.fleet_obs import (
    FleetEventJournal,
    RouterFlightRecorder,
    federate_metrics,
    spans_from_chrome,
    stitched_trace_payload,
)
from k8s_gpu_device_plugin_tpu.obs.trace import (
    TRACEPARENT_HEADER,
    current_context,
    current_trace_ids,
    format_traceparent,
    get_tracer,
    parse_traceparent,
)
from k8s_gpu_device_plugin_tpu.utils.log import get_logger

log = get_logger()

#: proxied POST surfaces (both APIs; the router adds nothing of its own
#: to them — byte-transparent by contract)
PROXY_POSTS = (
    "/v1/generate", "/v1/completions", "/v1/chat/completions",
    "/v1/embeddings",
)


class RouterMetrics:
    """Prometheus mirror of the router's counters (optional — the plain
    ``router_stats()`` snapshot always exists). Collector names are
    fixed; call :meth:`close` before building a second instance on the
    same registry (tests, restarts)."""

    def __init__(self, registry=None, prefix: str = "tpu_router"):
        from prometheus_client import REGISTRY, Counter, Gauge

        self._registry = registry if registry is not None else REGISTRY
        self.requests = Counter(
            f"{prefix}_requests_total",
            "Requests relayed, by replica and outcome",
            ["replica", "outcome"],
            registry=self._registry,
        )
        self.affinity_hits = Counter(
            f"{prefix}_affinity_hits_total",
            "Requests dispatched to their ring-home replica",
            registry=self._registry,
        )
        self.failovers = Counter(
            f"{prefix}_failovers_total",
            "Dispatch attempts beyond the first candidate "
            "(connection failure or 429 moved the request on)",
            registry=self._registry,
        )
        self.promotions = Counter(
            f"{prefix}_promotions_total",
            "Warm spares promoted into the ring after an active "
            "replica died",
            registry=self._registry,
        )
        self.stream_resumes = Counter(
            f"{prefix}_stream_resumes_total",
            "Mid-stream replica deaths resumed onto another replica "
            "(the client-visible stream continued, zero re-emitted "
            "tokens)",
            registry=self._registry,
        )
        self.kv_transfers = Counter(
            f"{prefix}_kv_transfers_total",
            "Disaggregated prefill->decode KV-page transfers, by "
            "outcome (ok; fallback = degraded to re-prefill; "
            "completed_on_prefill = the stream finished before any "
            "transfer was needed)",
            ["outcome"],
            registry=self._registry,
        )
        self.inflight = Gauge(
            f"{prefix}_inflight",
            "Requests currently relayed to each replica",
            ["replica"],
            registry=self._registry,
        )
        self.replica_up = Gauge(
            f"{prefix}_replica_up",
            "1 = replica routable (alive, not draining, not cooling down)",
            ["replica"],
            registry=self._registry,
        )

    def close(self) -> None:
        for c in (self.requests, self.affinity_hits, self.failovers,
                  self.promotions, self.stream_resumes, self.kv_transfers,
                  self.inflight, self.replica_up):
            try:
                self._registry.unregister(c)
            except KeyError:
                pass  # already unregistered


class _Unreachable(Exception):
    """Connection-level failure before response headers: safe to retry
    the next ring candidate (no bytes reached the client)."""


class _Overloaded(Exception):
    """Backend answered 429: cool the replica down for Retry-After and
    try the next candidate; forwarded verbatim if every candidate 429s."""

    def __init__(self, body: bytes, retry_after: int, content_type: str):
        super().__init__("backend overloaded")
        self.body = body
        self.retry_after = retry_after
        self.content_type = content_type


class _StreamJournal:
    """One in-flight resumable stream's recovery record: the original
    request body plus every (token, logprob) relayed so far. Written by
    exactly ONE task — the relay pumping that stream (the engine-owned
    single-writer discipline, transplanted to the event loop) — and
    bounded: tokens cannot outgrow the request's ``max_new``, and the
    router caps how many streams are journaled at once
    (``journal_limit`` — a stream past the cap serves normally, it just
    isn't resumable, counted in ``router_stats``)."""

    __slots__ = ("body", "key", "tokens", "logps", "closed")

    def __init__(self, body: dict, key: "bytes | None"):
        self.body = body                       # parsed original request
        self.key = key                         # its ring affinity key
        # pre-seed with a client-supplied resume: those tokens were
        # already delivered by an EARLIER incarnation, so a death here
        # must carry them forward too
        self.tokens: list[int] = [
            int(t) for t in (body.get("resume_out") or ())
        ]
        self.logps: list[float] = [
            float(x) for x in (body.get("resume_logprobs") or ())
        ]
        if len(self.logps) < len(self.tokens):
            self.logps += [0.0] * (len(self.tokens) - len(self.logps))
        self.closed = False                    # done/error frame relayed

    def observe(self, evt: dict) -> None:
        if "token" in evt:
            self.tokens.append(int(evt["token"]))
            self.logps.append(float(evt.get("logprob", 0.0)))
        elif "done" in evt or "error" in evt:
            self.closed = True

    def resume_body(self) -> bytes:
        body = dict(self.body)
        if self.tokens:
            body["resume_out"] = list(self.tokens)
            body["resume_logprobs"] = list(self.logps)
        else:
            # died before any token was relayed: a plain from-scratch
            # resubmit IS the resume (there is nothing to fold)
            body.pop("resume_out", None)
            body.pop("resume_logprobs", None)
        return json.dumps(body).encode()


class _BackendLost(Exception):
    """The backend died mid-SSE-relay (after headers, before the done
    frame): the resume path's trigger. Carries nothing — the journal
    has everything."""


class _ClientGone(Exception):
    """The CLIENT side of a relay vanished mid-stream. Distinct from
    _BackendLost so a client disconnect cancels the upstream request
    (close the backend connection hard — the replica sees the reset and
    frees the slot) instead of triggering a pointless resume."""


class ReplicaRouter:
    """aiohttp app over a FleetRegistry (port 0 = ephemeral)."""

    def __init__(
        self,
        fleet: FleetRegistry,
        host: str = "0.0.0.0",
        port: int = 8100,
        policy: str = "affinity",
        prompt_buckets: "tuple[int, ...] | None" = None,  # None = the
        # batcher's DEFAULT_PROMPT_BUCKETS ladder — affinity keys are
        # only load-bearing when they cut at the boundaries the
        # replicas' prefix caches promote at, so a fleet whose replicas
        # run custom buckets (or a small --maxLen trimming the ladder)
        # must pass the same ladder here (--promptBuckets on the CLI)
        load_factor: float = 1.25,
        health_interval_s: float = 1.0,
        drain_timeout_s: float = 120.0,
        connect_timeout_s: float = 2.0,
        header_timeout_s: float = 300.0,  # finite: a wedged replica
        # must fail over, not hang the client forever (0 = unbounded)
        resume_timeout_s: float = 30.0,  # how long a mid-stream resume
        # keeps retrying candidates (429s honored, promotions awaited)
        # before the stream ends with the structured error frame
        registry=None,          # prometheus registry (None = no /metrics)
        metrics: "RouterMetrics | None" = None,
        faults=None,            # serving.faults.FaultPlane (None = disarmed)
        warm_spares: int = 0,   # last N --replicas entries held OFF the
        # ring as standbys, promoted when an active replica dies
        fleet_restart_budget: int = 3,   # replica-death stream resumes
        fleet_restart_window_s: float = 300.0,  # per rolling window
        journal_limit: int = 1024,  # concurrent streams journaled for
        # resume; streams past the cap serve normally, un-resumably
        journal_events: int = 1024,  # fleet event journal ring size
        # (obs/fleet_obs.py; GET /fleet/events)
        timelines: bool = True,  # router-side request timelines + the
        # flight recorder (GET /fleet/debug/requests); False leaves the
        # proxy hot path with is-not-None guards only
        slow_stream_ms: float = 0.0,  # SLO-breach retention threshold
        # for the router flight recorder (resumed/failed-over/error
        # streams are always retained; 0 = only those)
        roles: "str | None" = None,  # disaggregated prefill/decode:
        # a --roles spec ("prefill=idA,idB decode=idC"; unlisted
        # replicas stay 'any'). None/empty leaves routing byte-
        # identical to an unroled fleet
        disagg_min_prompt: int = 64,  # prompts at least this many
        # tokens (journaled native SSE streams only) take the
        # prefill-worker -> KV-transfer -> decode-worker path; shorter
        # ones go straight to a decode-capable replica
        plugins: "list[tuple[str, str]] | None" = None,  # device-plugin
        # control planes to federate: [(node_id, base_url)]. Their
        # /metrics joins /fleet/metrics (node= relabeling + fleet chip
        # aggregates) and their /debug/allocations journals join
        # /fleet/events with plane="plugin". None/empty leaves both
        # surfaces byte-identical to the replica-only fleet.
        adapter_names: "tuple[str, ...] | None" = None,  # LoRA adapters
        # the replicas serve (--loraAdapters there, --adapterNames
        # here): a request selecting a LISTED adapter folds its name
        # into the affinity key, so one adapter's traffic concentrates
        # on the replica(s) already holding its stacks HBM-resident —
        # prefix affinity one level up. Unlisted/base requests keep the
        # pre-adapter key byte-identical.
    ):
        if policy not in ("affinity", "rr"):
            raise ValueError(
                f"unknown router policy {policy!r} "
                "(expected 'affinity' or 'rr')"
            )
        if load_factor <= 1.0:
            raise ValueError(
                f"load_factor must be > 1.0, got {load_factor} "
                "(1.0 would refuse the mean load itself)"
            )
        self.fleet = fleet
        if roles:
            fleet.assign_roles(roles)
        # computed ONCE: every disaggregation branch below gates on
        # this, so an unroled fleet runs the exact pre-roles code paths
        self._roles_on = fleet.roles_configured()
        self.disagg_min_prompt = int(disagg_min_prompt)
        if warm_spares:
            fleet.mark_spares(warm_spares)
        # the ring is the ACTIVE membership only: spares join (and dead
        # actives leave) at promotion time, remapping affinity keys the
        # consistent-hashing way (~1/N of the keyspace moves)
        self.ring = HashRing([r.rid for r in fleet.active()])
        self.host = host
        self.port = port
        self.bound_port: int | None = None
        self.policy = policy
        if prompt_buckets is None:
            from k8s_gpu_device_plugin_tpu.models.batching import (
                DEFAULT_PROMPT_BUCKETS,
            )

            prompt_buckets = DEFAULT_PROMPT_BUCKETS
        self.prompt_buckets = tuple(sorted(prompt_buckets))
        self.adapter_names = frozenset(adapter_names or ())
        self._adapter_requests: dict[str, int] = {}  # listed-name tally
        self.load_factor = float(load_factor)
        self.health_interval_s = float(health_interval_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)
        # bound the HEADER phase of a dispatch (a wedged replica whose
        # socket accepts but never answers must fail over like a
        # connection failure, not hang the client forever — which is
        # exactly what an unbounded default did). The default sits
        # ABOVE the worst legitimate case — a non-streamed generate's
        # headers arrive only when generation completes, minutes on a
        # cold compile, so 5 minutes clears it; a premature timeout
        # would cascade failovers across a healthy fleet. Operators
        # who stream (headers arrive at prepare time) can set this
        # tight; 0 restores unbounded.
        self.header_timeout_s = float(header_timeout_s)
        self.resume_timeout_s = float(resume_timeout_s)
        # seeded fault injection (serving/faults.py): the two
        # router-side seams — pre-dispatch connect and mid-SSE-relay
        self._flt_connect = (
            faults.point("router.connect") if faults is not None else None
        )
        self._flt_midstream = (
            faults.point("router.midstream") if faults is not None else None
        )
        self.registry = registry
        self.metrics = metrics
        self.tracer = get_tracer()
        self._rr_next = 0
        # cross-replica stream resume (the fleet tier's recovery
        # guarantee): budgeted like the supervisor's restarts, one
        # charge per replica DEATH (not per stream)
        self._fleet_budget = FleetRestartBudget(
            fleet_restart_budget, fleet_restart_window_s
        )
        self.journal_limit = int(journal_limit)
        self._journaled = 0       # streams currently carrying a journal
        # fleet observability plane (obs/fleet_obs.py): the event
        # journal (always on — it writes only on failure/control-plane
        # paths, and rare kinds ride a ring request-rate failover/429
        # noise cannot evict) and the per-stream timeline flight
        # recorder (optional)
        self.journal = FleetEventJournal(maxlen=journal_events)
        self._recorder: "RouterFlightRecorder | None" = (
            RouterFlightRecorder(slow_ms=slow_stream_ms)
            if timelines else None
        )
        # plain counters (always on; RouterMetrics mirrors them): the
        # serve-bench fleet A/B and /fleet/health read these
        self._requests = 0
        self._affinity_hits = 0
        self._failovers = 0
        self._promotions = 0
        self._resumes = 0          # mid-stream deaths spliced over
        self._resume_failures = 0  # ended with the structured error frame
        # disaggregated prefill/decode bookkeeping: transfers by
        # outcome, pages shipped, and a bounded wall-time sample ring
        # (serve_bench reads these for kv_transfer_ms percentiles)
        self._kv_transfers: dict[str, int] = {}
        self._kv_transfer_pages = 0
        self._kv_transfer_ms: list[float] = []
        self._unjournaled = 0      # streams served past journal_limit
        self._refused: dict[str, int] = {}
        self._outcomes: dict[str, int] = {}
        self.plugins: "list[tuple[str, str]]" = list(plugins or [])
        self._session: aiohttp.ClientSession | None = None
        self._poll_task: asyncio.Task | None = None
        self.app = web.Application(middlewares=[self._trace_middleware])
        for path in PROXY_POSTS:
            self.app.router.add_post(path, self._proxy_post)
        self.app.router.add_get("/v1/models", self._proxy_get)
        self.app.router.add_get("/v1/health", self._health)
        self.app.router.add_get("/fleet/health", self._fleet_health)
        self.app.router.add_post("/fleet/drain/{replica}", self._drain)
        self.app.router.add_post("/fleet/undrain/{replica}", self._undrain)
        self.app.router.add_post(
            "/fleet/rolling-restart", self._rolling_restart
        )
        # the fleet observability plane (obs/fleet_obs.py): the
        # router's OWN trace ring (the third /debug/traces plane, same
        # ?limit=/?since= surface), cross-replica stitching, federated
        # metrics, the event journal and the stream timelines
        self.app.router.add_get("/debug/traces", self._debug_traces)
        self.app.router.add_get(
            "/debug/traces/{trace_id}", self._debug_trace_one
        )
        self.app.router.add_get(
            "/fleet/debug/traces/{trace_id}", self._fleet_trace_one
        )
        self.app.router.add_get("/fleet/metrics", self._fleet_metrics)
        self.app.router.add_get("/fleet/events", self._fleet_events)
        self.app.router.add_get(
            "/fleet/debug/requests", self._fleet_requests
        )
        self.app.router.add_get(
            "/fleet/debug/requests/{rid}", self._fleet_request_one
        )
        if registry is not None:
            self.app.router.add_get("/metrics", self._metrics)

    # --- lifecycle --------------------------------------------------------

    async def run(self, stop: asyncio.Event) -> None:
        """Serve until ``stop`` is set (the InferenceServer idiom)."""
        self._session = aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(
                total=None, connect=self.connect_timeout_s,
            )
        )
        runner = web.AppRunner(self.app)
        try:
            # everything past session creation is inside the try: a bind
            # failure must not leak the session or a live poller into
            # the embedding process (serving/testing.py fleets)
            self._poll_task = asyncio.ensure_future(self._poll_loop())
            await runner.setup()
            site = web.TCPSite(runner, self.host, self.port)
            await site.start()
            self.bound_port = (
                runner.addresses[0][1] if runner.addresses else None
            )
            log.info(
                "replica router listening",
                extra={"fields": {
                    "addr": f"{self.host}:{self.bound_port}",
                    "policy": self.policy,
                    "replicas": self.fleet.ids(),
                }},
            )
            await stop.wait()
        finally:
            if self._poll_task is not None:
                self._poll_task.cancel()
                try:
                    await self._poll_task
                except asyncio.CancelledError:
                    pass
                self._poll_task = None
            await runner.cleanup()
            await self._session.close()
            self._session = None

    # --- health polling ---------------------------------------------------

    async def _probe_health(self, rep: Replica) -> dict | None:
        """One /v1/health contact, feeding the liveness ledger either
        way: a 200 payload revives the replica, anything else (engine
        dead behind a live socket, unreachable, garbage JSON) counts a
        failure. The poll loop AND the drain wait share this."""
        try:
            async with self._session.get(
                f"{rep.url}/v1/health",
                timeout=aiohttp.ClientTimeout(total=self.connect_timeout_s),
            ) as resp:
                if resp.status != 200:
                    self.fleet.note_failure(rep)
                    return None
                # ValueError covers json.JSONDecodeError (a truncated
                # body must count as a failed probe, not kill the poller)
                health = await resp.json()
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                ValueError):
            self.fleet.note_failure(rep)
            return None
        self.fleet.note_success(rep, health)
        return health

    async def _poll_one(self, rep: Replica) -> None:
        """One replica's probe, hardened: ANY unexpected exception (a
        raising metrics callback, a pathological payload — anything
        _probe_health's expected-failure net doesn't catch) counts a
        liveness failure for THIS replica and never reaches the poll
        loop — one bad replica must not blind routing to the rest."""
        try:
            await self._probe_health(rep)
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 - a dead poller blinds routing
            log.exception(
                "health probe failed unexpectedly",
                extra={"fields": {"replica": rep.rid}},
            )
            self.fleet.note_failure(rep)

    async def _poll_loop(self) -> None:
        """One staggered probe loop per replica: each replica's probes
        fire at a deterministic phase offset inside the interval
        (serving/fleet.py ``poll_phase``), so an N-replica fleet does
        not synchronize its health probes into a thundering herd on
        every ``--healthIntervalS`` tick. Spares are polled too — a
        promotion must hand traffic to a replica whose liveness is
        current, not assumed."""

        async def one(rep: Replica) -> None:
            await asyncio.sleep(poll_phase(rep.rid, self.health_interval_s))
            while True:
                try:
                    await self._poll_one(rep)
                    self._maybe_promote()
                    if self.metrics is not None:
                        self.metrics.replica_up.labels(rep.rid).set(
                            1 if rep.routable(time.monotonic()) else 0
                        )
                except asyncio.CancelledError:
                    raise
                except Exception:  # noqa: BLE001 - a dead poller blinds
                    log.exception("router health poll pass failed")
                await asyncio.sleep(self.health_interval_s)

        tasks = [
            asyncio.ensure_future(one(rep)) for rep in self.fleet.all()
        ]
        try:
            await asyncio.gather(*tasks)
        finally:
            for t in tasks:
                t.cancel()

    # --- warm spares ------------------------------------------------------

    def _maybe_promote(self) -> None:
        """Promote warm spares over dead active replicas (called from
        the poll loop and from proxy-observed failures — wherever a
        death becomes visible). Each promotion swaps ring membership
        and rebuilds the ring once, remapping affinity keys; surfaced
        on /fleet/health (``promotions``) and
        ``tpu_router_promotions_total``."""
        promoted = False
        for rep in self.fleet.active():
            if rep.alive:
                continue
            spare = self.fleet.promote_spare(rep)
            if spare is None:
                break  # no idle live spare; later deaths can't do better
            promoted = True
            self._promotions += 1
            if self.metrics is not None:
                self.metrics.promotions.inc()
            self.journal.emit("promote", promoted=spare.rid,
                              replaced=rep.rid)
            # "replica" is the log-correlation key dashboards slice on;
            # trace_id rides in via the emit-time filter when a proxy-
            # observed death triggered the promotion inside a request
            # span (the poll loop has no ambient span)
            log.warning(
                "promoted warm spare into the ring",
                extra={"fields": {"replica": spare.rid,
                                  "promoted": spare.rid,
                                  "replaced": rep.rid,
                                  "promotions": self._promotions}},
            )
        if promoted:
            self.ring = HashRing([r.rid for r in self.fleet.active()])

    # --- tracing ----------------------------------------------------------

    @web.middleware
    async def _trace_middleware(self, request: web.Request, handler):
        if not self.tracer.enabled:
            return await handler(request)
        from k8s_gpu_device_plugin_tpu.obs.http import (
            is_observation_path,
            route_label,
        )

        remote = parse_traceparent(request.headers.get(TRACEPARENT_HEADER))
        if remote is None and is_observation_path(request.path):
            # the replica middleware's rule, at the router seam:
            # telemetry reads (LB health probes, federation scrapes,
            # stitch fetches) may join a trace but never start one —
            # root spans per observation would churn the router's own
            # ring (the stitcher's "router" track source) out of the
            # real request traces being observed
            return await handler(request)
        with self.tracer.span(
            f"{request.method} {route_label(request)}",
            component="router_http",
            parent=remote, method=request.method, path=request.path,
        ) as span:
            try:
                response = await handler(request)
            except web.HTTPException as http_err:
                span.set(status_code=http_err.status)
                http_err.headers[TRACEPARENT_HEADER] = format_traceparent(span)
                raise
            span.set(status_code=response.status)
            if not response.prepared:  # SSE relays already sent headers
                response.headers[TRACEPARENT_HEADER] = format_traceparent(span)
            return response

    def _backend_headers(self, request: web.Request) -> dict:
        headers = {
            "Content-Type": request.headers.get(
                "Content-Type", "application/json"
            ),
        }
        if self.tracer.enabled:
            from k8s_gpu_device_plugin_tpu.obs.trace import current_context

            ctx = current_context()
            if ctx is not None:
                # the router span becomes the replica span's remote
                # parent: one trace covers edge -> router -> engine
                headers[TRACEPARENT_HEADER] = format_traceparent(ctx)
        return headers

    # --- routing ----------------------------------------------------------

    def _affinity_source(self, path: str, body) -> object | None:
        """The prefix-bearing field of each surface. Chat messages key
        on the serialized message list — the system prompt + history
        prefix is its head, which is exactly what the replica's prefix
        cache will match after templating."""
        if not isinstance(body, dict):
            return None
        if path == "/v1/generate":
            return body.get("prompt") or body.get("text")
        if path == "/v1/completions":
            return body.get("prompt")
        if path == "/v1/chat/completions":
            return body.get("messages")
        return None  # embeddings: no KV reuse — balance only

    @staticmethod
    def _adapter_source(path: str, body) -> "str | None":
        """The adapter-selecting field of each surface: the native
        ``"adapter"`` name, or the OpenAI ``"model"`` when it names
        something other than the base model. None = base-model request
        (or a surface with no adapter notion — embeddings)."""
        if not isinstance(body, dict):
            return None
        if path == "/v1/generate":
            name = body.get("adapter")
            return name if isinstance(name, str) and name else None
        if path in ("/v1/completions", "/v1/chat/completions"):
            from k8s_gpu_device_plugin_tpu.serving.openai_api import MODEL_ID

            name = body.get("model")
            if isinstance(name, str) and name and name != MODEL_ID:
                return name
        return None

    def _fold_adapter(self, path: str, body, key: "bytes | None"):
        """Prefold a LISTED adapter's name onto the affinity key: the
        adapter's stacks are HBM state exactly like a promoted prefix,
        so its traffic should concentrate where they already live (a
        gather on every other replica re-uploads nothing, but a MISS
        costs an H2D upload + a head-of-line deferral). The fold
        prefixes rather than replaces — requests on one adapter still
        spread by prompt prefix once they share a home neighborhood.
        Unlisted or base-model requests return ``key`` unchanged (the
        byte-identical pin the fleet A/B rests on)."""
        name = self._adapter_source(path, body)
        if name is None or name not in self.adapter_names:
            return key
        self._adapter_requests[name] = (
            self._adapter_requests.get(name, 0) + 1
        )
        return b"a:" + name.encode() + b"\x00" + (key or b"")

    @staticmethod
    def _resumable_body(path: str, body) -> bool:
        """Which streams can carry a recovery journal: the native SSE
        surface with a token-id prompt and n=1 — exactly what the
        resume seam (``resume_out``) is defined over. Text prompts need
        the replica's tokenizer (the router has none), n>1 has no
        single stream to splice, and the OpenAI SSE framing carries no
        raw token ids to journal; those streams serve exactly as
        before (a mid-stream death stays a visible truncation the
        client retries)."""
        if path != "/v1/generate" or not isinstance(body, dict):
            return False
        if not body.get("stream"):
            return False
        prompt = body.get("prompt")
        if (not isinstance(prompt, list) or not prompt or not all(
            isinstance(t, int) and not isinstance(t, bool) for t in prompt
        )):
            return False
        try:
            if int(body.get("n", 1) or 1) != 1:
                return False
        except (TypeError, ValueError):
            return False
        # a client-supplied resume pre-seeds the journal: malformed
        # fields must not be journaled (the int()/float() casts would
        # 500 here) — forwarded unjournaled, the replica answers its
        # clean 4xx
        rout = body.get("resume_out")
        if rout is not None and (
            not isinstance(rout, list) or not all(
                isinstance(t, int) and not isinstance(t, bool)
                for t in rout
            )
        ):
            return False
        rlps = body.get("resume_logprobs")
        if rlps is not None and (
            not isinstance(rlps, list) or not all(
                isinstance(x, (int, float)) and not isinstance(x, bool)
                for x in rlps
            )
        ):
            return False
        return True

    def _pick(
        self, key: bytes | None, role: "str | None" = None
    ) -> tuple[list[Replica], "Replica | None"]:
        """-> (dispatch order, the key's ring HOME or None). Affinity
        walks the ring from the key's point and applies the
        bounded-load skip; rr (or a keyless request) rotates /
        least-loads over the live set. An empty list means nobody can
        admit right now.

        ``role`` narrows the candidates to replicas that can serve that
        side of a disaggregated fleet (exact role or ``"any"``); when
        NO live replica covers the role, the filter falls away — a
        specialized fleet degraded to one surviving generalist must
        keep serving, not refuse on principle."""
        now = time.monotonic()
        live = [r for r in self.fleet.all() if r.routable(now)]
        if not live:
            # cooldown is ADVICE, not refusal: with every candidate
            # cooling down from a 429, the backend's own 429 (fresh
            # Retry-After included) is the right answer — not a made-up
            # 503. Draining/dead/spare replicas stay excluded.
            live = [
                r for r in self.fleet.all()
                if r.alive and not r.draining and not r.spare
            ]
        if role is not None and self._roles_on:
            roled = [r for r in live if r.role in (role, "any")]
            if roled:
                live = roled
        if not live:
            return [], None
        usable = set(id(r) for r in live)
        if self.policy == "rr" or key is None:
            self._rr_next += 1
            i = self._rr_next % len(live)
            return live[i:] + live[:i], None
        ring_order = [
            self.fleet.get(rid) for rid in self.ring.candidates(key)
        ]
        home = ring_order[0] if ring_order else None
        order = [
            r for r in ring_order if r is not None and id(r) in usable
        ]
        if not order:
            return [], None
        # bounded load: a candidate already past load_factor x the mean
        # in-flight spills to the NEXT ring candidate (deterministic
        # spill target), never to a random replica
        cap = max(2.0, math.ceil(
            self.load_factor * (sum(r.inflight for r in live) + 1)
            / len(live)
        ))
        target = next((r for r in order if r.inflight < cap), None)
        if target is None:
            target = min(order, key=lambda r: r.inflight)
        rest = [r for r in order if r is not target]
        return [target] + rest, home

    # --- refusals (per-surface shapes) ------------------------------------

    def _refuse(self, path: str, code: str, message: str,
                status: int = 503) -> web.Response:
        self._refused[code] = self._refused.get(code, 0) + 1
        if self.metrics is not None:
            self.metrics.requests.labels("none", code).inc()
        if path == "/v1/generate":
            # the native structured-error shape (the 429 body's sibling)
            resp = web.json_response(
                {"error": message, "code": code}, status=status
            )
        else:
            # OpenAI envelope; 5xx reads as retryable server_error,
            # which is what a drain IS from the client's side — retry
            # lands post-drain
            resp = web.json_response(
                {"error": {"message": message, "type": "server_error",
                           "code": code}},
                status=status,
            )
        # the timeline outcome must tell a ROUTER refusal (this 503)
        # apart from a relayed backend 5xx — both are >=500 by the time
        # the flight recorder sees them
        resp.router_refusal = code
        return resp

    # --- the proxy --------------------------------------------------------

    async def _proxy_post(self, request: web.Request) -> web.StreamResponse:
        # the stream timeline starts at request receipt: the segments
        # below sum exactly to the wall time THIS seam observed — the
        # PR-9 invariant, one tier up (obs/fleet_obs.RouterTimeline)
        tl = None
        if self._recorder is not None:
            ids = current_trace_ids()
            tl = self._recorder.start(
                request.path, ids[0] if ids is not None else ""
            )
        raw = await request.read()
        try:
            body = json.loads(raw) if raw else None
        except json.JSONDecodeError:
            body = None  # the backend's 400 is the authoritative answer
        key = affinity_key(
            self._affinity_source(request.path, body), self.prompt_buckets
        )
        key = self._fold_adapter(request.path, body, key)
        # disaggregated prefill/decode (--roles): journaled long-prompt
        # streams take the prefill-worker leg (relay until the first
        # token, export the KV pages, resubmit to a decode worker);
        # everything else routes to decode-capable replicas so prefill
        # workers stay clear for prefill bursts. All of it is inert on
        # an unroled fleet (role=None -> the pre-roles code paths).
        wants_disagg = (
            self._roles_on
            and self._resumable_body(request.path, body)
            and body.get("kv_pages") is None
            and not body.get("resume_out")
            and len(body.get("prompt") or ()) >= self.disagg_min_prompt
        )
        role = None
        if self._roles_on and request.path != "/v1/embeddings":
            role = "prefill" if wants_disagg else "decode"
        order, home = self._pick(key, role=role)
        if not order:
            if self.fleet.any_draining():
                resp = self._refuse(
                    request.path, "draining",
                    "all replicas are draining; retry after the rolling "
                    "update completes",
                )
            else:
                resp = self._refuse(
                    request.path, "no_replica",
                    "no live replica available",
                )
            if tl is not None:
                self._recorder.on_done(tl.finalize("refused", resp.status))
            return resp
        self._requests += 1
        headers = self._backend_headers(request)
        # journal eligibility: native token-id SSE streams (n=1) carry
        # a recovery journal so a mid-stream replica death resumes on
        # another ring candidate instead of truncating the client
        journal: "_StreamJournal | None" = None
        if self._resumable_body(request.path, body):
            if self._journaled < self.journal_limit:
                journal = _StreamJournal(body, key)
                self._journaled += 1
            else:
                self._unjournaled += 1
        disagg = wants_disagg and journal is not None
        if wants_disagg and journal is None:
            # past the journal cap there is no token record to drive a
            # transfer: serve colocated on a decode-capable replica
            # instead of stranding a decoding stream on a prefill one
            order, home = self._pick(key, role="decode")
        resp = None
        try:
            resp = await self._dispatch(
                request, raw, headers, order, home, journal, tl,
                relay=self._relay_disagg if disagg else None,
            )
            return resp
        finally:
            if journal is not None:
                self._journaled -= 1
            if tl is not None:
                if journal is not None:
                    tl.tokens = len(journal.tokens)
                if resp is None:
                    # the handler is unwinding (client disconnect /
                    # cancellation): the wall time still closes exactly
                    rec = tl.finalize("cancelled")
                else:
                    rec = tl.finalize(
                        self._tl_outcome(tl, resp), resp.status
                    )
                self._recorder.on_done(rec)

    @staticmethod
    def _tl_outcome(tl, resp) -> str:
        """Collapse a finished relay into the timeline's outcome label
        (the flight recorder's retention key). Agrees with the
        ``_outcome`` counter taxonomy: a relayed backend 5xx is
        ``backend_error``; ``refused`` is reserved for the router's own
        503s (``_refuse`` tags those)."""
        if tl.error_code:
            return tl.error_code    # fleet_budget_exhausted/resume_failed
        if getattr(resp, "router_refusal", None) is not None:
            return "refused"
        status = resp.status
        if status == 429:
            return "overloaded"
        if status >= 500:
            return "backend_error"
        if status >= 400:
            return "client_error"
        return "resumed" if tl.resumes else "ok"

    async def _dispatch(self, request: web.Request, raw: bytes,
                        headers: dict, order: "list[Replica]",
                        home: "Replica | None",
                        journal: "_StreamJournal | None",
                        tl=None,
                        relay=None,  # per-attempt relay (default
                        # self._relay; the disagg path substitutes
                        # _relay_disagg and rides the same failover
                        # loop, cooldown handling, and postlude)
                        ) -> web.StreamResponse:
        relay_fn = relay if relay is not None else self._relay
        last_429: _Overloaded | None = None
        for attempt, rep in enumerate(order):
            if attempt > 0:
                self._failovers += 1
                if tl is not None:
                    tl.failovers += 1
                if self.metrics is not None:
                    self.metrics.failovers.inc()
                self.journal.emit(
                    "failover", path=request.path,
                    prev=order[attempt - 1].rid, replica=rep.rid,
                    attempt=attempt,
                )
            rep.inflight += 1
            if self.metrics is not None:
                self.metrics.inflight.labels(rep.rid).set(rep.inflight)
            if self.tracer.enabled:
                # emit-time filter stamps trace_id/span_id (the
                # middleware span is this task's ambient context)
                log.debug(
                    "request submitted to replica",
                    extra={"fields": {
                        "replica": rep.rid,
                        "path": request.path,
                        "affinity_hit": rep is home,
                        "attempt": attempt,
                    }},
                )
            try:
                resp = await relay_fn(rep, request, raw, headers,
                                      journal=journal, tl=tl)
            except _Unreachable:
                self.fleet.note_failure(rep)
                self._maybe_promote()
                self._count(rep, "unreachable")
                continue
            except _Overloaded as e:
                rep.cooldown_until = time.monotonic() + e.retry_after
                self._count(rep, "overloaded")
                self.journal.emit("cooldown_429", replica=rep.rid,
                                  retry_after_s=e.retry_after)
                last_429 = e
                continue
            finally:
                rep.inflight -= 1
                if self.metrics is not None:
                    self.metrics.inflight.labels(rep.rid).set(rep.inflight)
            final = getattr(resp, "router_final_rep", rep)
            if final is rep:
                if resp.status < 500:
                    # only app-level answers prove the engine alive; a
                    # 5xx (dead engine behind a live socket) must keep
                    # counting toward dead_after or steady traffic would
                    # reset the ledger faster than the poller can fail it
                    self.fleet.note_success(rep)
                else:
                    self.fleet.note_failure(rep)
                self._count(rep, self._outcome(resp.status))
            # else: the stream died under rep mid-relay and the resume
            # path already fed the liveness ledger and outcome counters
            # for both the dead replica and whoever finished the stream
            if rep is home:
                # counted on the SERVING dispatch, not at plan time: a
                # home that failed over is a miss for cache locality
                self._affinity_hits += 1
                if tl is not None:
                    tl.affinity_hit = True
                if self.metrics is not None:
                    self.metrics.affinity_hits.inc()
            if self.tracer.enabled:
                # the middleware span (the ambient context on this
                # task) gains the routing decision: which replica
                # served, whether the ring home took it, whether the
                # resume path spliced it — the attrs a stitched trace
                # is sliced by
                span = current_context()
                if span is not None and hasattr(span, "set"):
                    # resumed means a live replica FINISHED the splice;
                    # final=None (error frame / synthesized done) must
                    # not read as a successful resume, and the replica
                    # attr then names the last replica that relayed
                    span.set(
                        replica=(final.rid if final is not None
                                 else rep.rid),
                        affinity_hit=rep is home,
                        resumed=(final is not None and final is not rep),
                    )
            return resp
        if last_429 is not None:
            # every candidate said "not now": deliver the backend's own
            # overload message + Retry-After, don't invent a new one
            return web.Response(
                body=last_429.body, status=429,
                content_type=last_429.content_type,
                headers={"Retry-After": str(last_429.retry_after)},
            )
        return self._refuse(
            request.path, "no_replica",
            "every replica is unreachable",
        )

    @staticmethod
    def _outcome(status: int) -> str:
        if status < 400:
            return "ok"
        return "client_error" if status < 500 else "backend_error"

    def _count(self, rep: Replica, outcome: str) -> None:
        rep.relayed += 1
        self._outcomes[outcome] = self._outcomes.get(outcome, 0) + 1
        if self.metrics is not None:
            self.metrics.requests.labels(rep.rid, outcome).inc()

    async def _open_backend(self, url: str, raw: bytes, headers: dict):
        """POST to a backend, bounding the HEADER phase: session.post
        resolves when response headers arrive, so the timeout covers
        exactly the wedge window — the body/SSE relay stays unbounded
        (legitimate long generations). Raises _Unreachable for the
        failover loop."""
        try:
            post = self._session.post(url, data=raw, headers=headers)
            if self.header_timeout_s > 0:
                return await asyncio.wait_for(post, self.header_timeout_s)
            return await post
        except (aiohttp.ClientError, asyncio.TimeoutError,
                ConnectionResetError, OSError) as e:
            raise _Unreachable(str(e)) from None

    @staticmethod
    async def _client_write(out: web.StreamResponse, data: bytes) -> None:
        """Write to the CLIENT side of the relay, renaming its failures:
        a vanished client must read as _ClientGone (cancel upstream),
        never as a backend loss the resume path would act on."""
        try:
            await out.write(data)
        except (ConnectionResetError, OSError, RuntimeError) as e:
            raise _ClientGone(str(e)) from None

    async def _pump_sse(self, resp, out: web.StreamResponse,
                        journal: "_StreamJournal | None") -> None:
        """Relay one backend SSE body into the client stream.

        Without a journal: the old byte-transparent chunk relay
        (non-resumable streams — OpenAI SSE, text prompts, n>1); a
        backend death propagates and the stream ends visibly truncated.

        With a journal: frames are forwarded at event granularity (the
        bytes of each complete frame pass unmodified, so the relay
        stays byte-transparent for streams that finish) and every
        token/logprob is journaled as it passes; a backend death —
        or the armed ``router.midstream`` fault — raises _BackendLost,
        the resume path's trigger. Buffering to frame boundaries is
        what makes the splice clean: a death mid-frame discards the
        partial frame instead of gluing half a JSON line to the
        continuation."""
        if journal is None:
            async for chunk in resp.content.iter_any():
                await self._client_write(out, chunk)
                if self._flt_midstream is not None:
                    try:
                        self._flt_midstream.fire()
                    except FaultError:
                        # injected mid-relay death on a non-resumable
                        # stream: close the backend HARD and end the
                        # client stream without a done event — a
                        # VISIBLE truncation, never retried
                        resp.close()
                        return
            return
        buf = b""
        try:
            async for chunk in resp.content.iter_any():
                buf += chunk
                while b"\n\n" in buf:
                    frame, buf = buf.split(b"\n\n", 1)
                    await self._client_write(out, frame + b"\n\n")
                    self._observe_frame(journal, frame)
                    # the fault advances per FRAME, not per network
                    # chunk: TCP coalescing groups frames differently
                    # run to run, and an nth=N schedule counted in
                    # chunks would journal a different tokens_at_death
                    # each time — the journal's replay-determinism
                    # contract (obs/fleet_obs.py) pins frame counting
                    if self._flt_midstream is not None \
                            and not journal.closed:
                        try:
                            self._flt_midstream.fire()
                        except FaultError:
                            raise _BackendLost() from None
        except (aiohttp.ClientError, asyncio.TimeoutError,
                ConnectionResetError, OSError) as e:
            if journal.closed:
                return  # every frame delivered; the EOF hiccup is moot
            raise _BackendLost() from e
        if not journal.closed:
            # the body ended with no done/error frame: the backend gave
            # up on this stream even if the socket closed politely —
            # as dead, for the client's purposes, as a reset
            raise _BackendLost()

    @staticmethod
    def _observe_frame(journal: _StreamJournal, frame: bytes) -> None:
        """Feed one relayed SSE frame into the journal (single writer:
        the task pumping this stream). A frame the replica emits that
        we cannot parse is ignored — the journal then resumes with
        fewer tokens than the client saw ONLY if the replica broke its
        own framing contract, which the parse-everything stance below
        makes loud in tests."""
        for line in frame.split(b"\n"):
            if not line.startswith(b"data: "):
                continue
            try:
                evt = json.loads(line[len(b"data: "):])
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue
            if isinstance(evt, dict):
                journal.observe(evt)

    async def _error_frame(self, out: web.StreamResponse, code: str,
                           message: str) -> None:
        """End a client stream with the PR-12 structured error frame
        (the native SSE shape the replicas themselves emit on engine
        death) — a resume that cannot happen must be VISIBLE, never a
        silent truncation that reads like a short completion."""
        evt = {"error": {"code": code, "message": message}}
        try:
            await self._client_write(
                out, f"data: {json.dumps(evt)}\n\n".encode()
            )
        except _ClientGone:
            pass  # nobody left to tell

    async def _resume_stream(self, dead: Replica, request: web.Request,
                             out: web.StreamResponse,
                             journal: _StreamJournal,
                             headers: dict, tl=None) -> "Replica | None":
        """The fleet tier's recovery guarantee: a replica died under a
        journaled stream — resubmit the request through the native
        resume seam (emitted tokens folded into the prompt;
        ``prefilled_out`` keeps greedy AND seeded continuations
        bit-identical) to the next ring candidate and splice the
        continuation into the SAME client response, zero re-emitted
        tokens. Chained deaths loop (each charges the fleet budget for
        ITS replica); past the budget the stream ends with the
        structured error frame. Returns the replica that finished the
        stream, or None when no LIVE replica finished it (the error
        frame, or a synthesized done after a tokens-complete death) —
        the caller then leaves the liveness ledger to what this path
        already recorded."""
        try:
            max_new = int(journal.body.get("max_new", 64) or 0)
        except (TypeError, ValueError):
            max_new = 0
        while True:
            tokens_at_death = len(journal.tokens)
            self.fleet.note_failure(dead)
            # the dead replica's relay gets its outcome recorded (once
            # per death observation — chained deaths re-enter here with
            # a new ``dead``): per-replica requests_total must not
            # undercount exactly the replicas an operator is diagnosing
            self._count(dead, "died_midstream")
            self._maybe_promote()
            if not self._fleet_budget.charge(dead):
                self._resume_failures += 1
                self.journal.emit("budget_exhausted", replica=dead.rid,
                                  tokens_at_death=tokens_at_death)
                if tl is not None:
                    tl.error_code = "fleet_budget_exhausted"
                log.warning(
                    "mid-stream replica death past the fleet restart "
                    "budget; ending stream with an error frame",
                    extra={"fields": {"replica": dead.rid,
                                      **self._fleet_budget.stats()}},
                )
                await self._error_frame(
                    out, "fleet_budget_exhausted",
                    f"replica {dead.rid!r} died mid-stream and the "
                    "fleet restart budget is exhausted; partial output "
                    f"({len(journal.tokens)} tokens) was delivered",
                )
                return None
            if max_new and len(journal.tokens) >= max_new:
                # the death ate only the done frame — every budgeted
                # token was already delivered. Synthesize a bare done
                # instead of resubmitting an empty resume (id-surface
                # caveat: the replica's closing event can carry decoded
                # text/cached_tokens; those are unrecoverable without a
                # tokenizer — the token/logprob stream itself is
                # complete and exact).
                self._resumes += 1
                if tl is not None:
                    tl.resumes += 1
                if self.metrics is not None:
                    self.metrics.stream_resumes.inc()
                self.journal.emit(
                    "stream_resume", source=dead.rid, target=None,
                    tokens_at_death=tokens_at_death, synthesized_done=True,
                )
                try:
                    await self._client_write(out, b'data: {"done": true}\n\n')
                except _ClientGone:
                    pass
                # no live finisher: the corpse must NOT be handed back
                # as this stream's final replica — _dispatch would mark
                # it successful, cancelling the death it just caused
                return None
            raw = journal.resume_body()
            resp = None
            target = None
            t_scan = time.monotonic()
            refused: set[str] = set()
            while resp is None:
                # scan the ring candidates; a fully-refusing fleet is
                # RETRIED within resume_timeout_s — the survivor may be
                # momentarily overloaded (429) or a promotion may be a
                # poll-tick away, and a long-lived stream is worth a
                # short wait (the client is blocked on us either way)
                wait = None
                order, _ = self._pick(journal.key)
                candidates = [r for r in order if r is not dead]
                usable = [r for r in candidates if r.rid not in refused]
                if candidates and not usable:
                    # every reachable candidate REFUSED the resume at
                    # the app level (an engine that can't fold — e.g.
                    # speculative): deterministic, waiting can't help
                    break
                for rep in usable:
                    self._failovers += 1
                    if tl is not None:
                        tl.failovers += 1
                    if self.metrics is not None:
                        self.metrics.failovers.inc()
                    try:
                        r = await self._open_backend(
                            f"{rep.url}{request.path}", raw, headers
                        )
                    except _Unreachable:
                        self.fleet.note_failure(rep)
                        self._maybe_promote()
                        continue
                    if r.status == 429:
                        # can't forward a status mid-stream: cool the
                        # replica down and try the next candidate
                        await r.read()
                        r.release()
                        ra = parse_retry_after(
                            r.headers.get("Retry-After"), default=1.0
                        )
                        rep.cooldown_until = time.monotonic() + ra
                        wait = ra if wait is None else min(wait, ra)
                        continue
                    ctype = r.headers.get("Content-Type", "")
                    if r.status != 200 or not ctype.startswith(
                        "text/event-stream"
                    ):
                        # a resume the replica refused: only a 5xx is
                        # dead-engine evidence — a 4xx is an app-level
                        # answer PROVING the engine alive (the dispatch
                        # path's own rule), it just can't continue this
                        # stream, ever (deterministic: skip it in later
                        # scans instead of re-asking)
                        await r.read()
                        r.release()
                        if r.status >= 500:
                            self.fleet.note_failure(rep)
                        else:
                            self.fleet.note_success(rep)
                            refused.add(rep.rid)
                        continue
                    resp, target = r, rep
                    break
                if resp is not None:
                    break
                if time.monotonic() - t_scan > self.resume_timeout_s:
                    break
                await asyncio.sleep(min(wait if wait is not None else 0.1,
                                        1.0))
            if resp is None:
                self._resume_failures += 1
                self.journal.emit("resume_failed", replica=dead.rid,
                                  tokens_at_death=tokens_at_death)
                if tl is not None:
                    tl.error_code = "resume_failed"
                await self._error_frame(
                    out, "resume_failed",
                    f"replica {dead.rid!r} died mid-stream and no "
                    "candidate could resume the request; partial output "
                    f"({len(journal.tokens)} tokens) was delivered",
                )
                return None
            self._count_resume(dead, target)
            self.journal.emit("stream_resume", source=dead.rid,
                              target=target.rid,
                              tokens_at_death=tokens_at_death)
            if tl is not None:
                tl.resumes += 1
                # the resume gap closes here: the continuation's bytes
                # are about to flow from the new replica
                tl.relay_on(target.rid)
            target.inflight += 1
            if self.metrics is not None:
                self.metrics.inflight.labels(target.rid).set(target.inflight)
            try:
                await self._pump_sse(resp, out, journal)
            except _BackendLost:
                # the continuation's replica died too: charge ITS death
                # and loop — the journal kept growing, so the next
                # resume starts exactly where this one ended
                resp.close()
                if tl is not None:
                    tl.advance("resume_gap")
                dead = target
                continue
            except _ClientGone:
                # the client vanished mid-continuation: cancel upstream
                # (hard close) and stop — nobody left to stream to
                resp.close()
                return target
            except BaseException:
                resp.close()
                raise
            finally:
                target.inflight -= 1
                if self.metrics is not None:
                    self.metrics.inflight.labels(target.rid).set(
                        target.inflight
                    )
            self.fleet.note_success(target)
            self._count(target, "resumed")
            resp.release()
            return target

    def _count_resume(self, dead: Replica, target: Replica) -> None:
        self._resumes += 1
        if self.metrics is not None:
            self.metrics.stream_resumes.inc()
        # "replica" = the continuation's server (the correlation key);
        # trace_id rides in via the emit-time filter — the resume runs
        # inside the dying relay's handler task, whose ambient span is
        # still the middleware's
        log.warning(
            "resumed mid-stream after replica death",
            extra={"fields": {"replica": target.rid, "dead": dead.rid,
                              "resumed_on": target.rid,
                              "resumes": self._resumes}},
        )

    async def _relay(self, rep: Replica, request: web.Request,
                     raw: bytes, headers: dict,
                     journal: "_StreamJournal | None" = None,
                     tl=None,
                     ) -> web.StreamResponse:
        """One dispatch attempt: forward the body verbatim, relay the
        response (SSE streamed frame-by-frame, JSON in one piece).
        Raises _Unreachable/_Overloaded for the failover loop; anything
        past response headers is final — except a journaled stream's
        mid-relay backend death, which the resume path splices over."""
        url = f"{rep.url}{request.path}"
        if self._flt_connect is not None:
            try:
                self._flt_connect.fire()
            except FaultError as e:
                # injected connection failure: the failover loop moves
                # to the next ring candidate, like a real refusal
                raise _Unreachable(str(e)) from None
        resp = await self._open_backend(url, raw, headers)
        try:
            if resp.status == 429:
                body = await resp.read()
                ra = parse_retry_after(
                    resp.headers.get("Retry-After"), default=1.0
                )
                raise _Overloaded(
                    body, max(1, int(math.ceil(ra))),
                    resp.headers.get("Content-Type", "application/json")
                    .split(";")[0],
                )
            ctype = resp.headers.get("Content-Type", "")
            if tl is not None:
                # headers arrived and the status is an answer (not a
                # 429 hop): the candidate scan ends, relay bytes flow
                tl.relay_on(rep.rid)
            if ctype.startswith("text/event-stream"):
                out = web.StreamResponse(headers={
                    "Content-Type": "text/event-stream",
                    "Cache-Control": "no-cache",
                })
                await out.prepare(request)
                # which replica fed the liveness ledger for this stream
                # (the resume path may hand it to another replica; None
                # = the stream ended on an error frame)
                out.router_final_rep = rep
                try:
                    await self._pump_sse(resp, out, journal)
                except _BackendLost:
                    resp.close()
                    if tl is not None:
                        # the resume gap opens at the observed death
                        # and closes when a continuation's relay starts
                        tl.advance("resume_gap")
                    out.router_final_rep = await self._resume_stream(
                        rep, request, out, journal, headers, tl=tl
                    )
                    try:
                        await out.write_eof()
                    except (ConnectionResetError, OSError, RuntimeError):
                        pass
                    return out
                except _ClientGone:
                    # the CLIENT vanished mid-relay: close the backend
                    # connection HARD so the replica sees the disconnect
                    # and cancels the generation — no resume, no retry
                    # (there is nobody left to stream to)
                    resp.close()
                    return out
                await out.write_eof()
                resp.release()
                return out
            body = await resp.read()
            resp.release()
            return web.Response(
                body=body, status=resp.status,
                content_type=ctype.split(";")[0] or "application/json",
            )
        except (_Overloaded, _Unreachable):
            resp.release()
            raise
        except BaseException:
            # client disconnect / cancellation mid-relay: close the
            # backend connection HARD so the replica sees the disconnect
            # and cancels the generation (release() would try to drain
            # the rest of the stream first)
            resp.close()
            raise

    # --- disaggregated prefill/decode (KV-page transfer) ------------------

    def _count_kv_transfer(self, outcome: str, pages: int,
                           ms: "float | None") -> None:
        self._kv_transfers[outcome] = (
            self._kv_transfers.get(outcome, 0) + 1
        )
        self._kv_transfer_pages += int(pages)
        if ms is not None:
            # only attempts that MOVED (or tried to move) pages feed
            # the latency record; completed_on_prefill never transfers
            self._kv_transfer_ms.append(round(float(ms), 3))
        if len(self._kv_transfer_ms) > 4096:
            # keep the recent half: serve_bench reads percentiles of a
            # run's own transfers, not the process's whole history
            del self._kv_transfer_ms[:2048]
        if self.metrics is not None:
            self.metrics.kv_transfers.labels(outcome).inc()

    async def _pump_first_token(self, resp, out: web.StreamResponse,
                                journal: _StreamJournal) -> None:
        """Relay the prefill leg until a token frame proves the request
        is decoding (export is only defined past prefill). Every
        COMPLETE frame in the triggering network chunk is relayed and
        journaled exactly like _pump_sse; a partial trailing frame is
        abandoned with the connection — its token is inside the
        export's atomic snapshot and the gap synthesis re-emits it.
        Raises _BackendLost when the body ends before a token or a
        close frame (the normal resume trigger)."""
        buf = b""
        try:
            async for chunk in resp.content.iter_any():
                buf += chunk
                while b"\n\n" in buf:
                    frame, buf = buf.split(b"\n\n", 1)
                    await self._client_write(out, frame + b"\n\n")
                    self._observe_frame(journal, frame)
                if journal.tokens or journal.closed:
                    return
        except (aiohttp.ClientError, asyncio.TimeoutError,
                ConnectionResetError, OSError) as e:
            if journal.closed:
                return
            raise _BackendLost() from e
        if not journal.closed:
            raise _BackendLost()

    async def _synthesize_gap(self, out: web.StreamResponse,
                              journal: _StreamJournal, exp: dict) -> None:
        """The export's engine-thread snapshot can surface tokens the
        relay never read (the engine flushes in-flight pipelined decode
        before snapshotting, and the relay stops at the first token):
        those tokens are part of the stream the client was promised.
        Emit their frames exactly as the replica would have — same JSON
        shape, logprob field only when the request asked for it — and
        journal them so any later resume starts from the full record."""
        toks = exp.get("resume_out") or []
        lps = exp.get("resume_logprobs") or []
        want_lp = bool(journal.body.get("logprobs"))
        for i in range(len(journal.tokens), len(toks)):
            t = int(toks[i])
            lp = float(lps[i]) if i < len(lps) else 0.0
            evt = {"token": t, "logprob": lp}
            journal.observe(evt)
            wire = evt if want_lp else {"token": t}
            await self._client_write(
                out, f"data: {json.dumps(wire)}\n\n".encode()
            )

    async def _pump_leg(self, rep: Replica, resp, request: web.Request,
                        out: web.StreamResponse,
                        journal: _StreamJournal, headers: dict,
                        tl=None) -> "Replica | None":
        """Pump one continuation leg (the decode worker, or a plain-
        resume fallback target) to completion, with the full recovery
        story: a mid-leg backend death hands off to _resume_stream
        (a REAL death — it charges the fleet budget and feeds the
        liveness ledger like any other). Returns the replica that
        finished the stream, or None when the error-frame path ended
        it."""
        if tl is not None:
            tl.relay_on(rep.rid)
        rep.inflight += 1
        if self.metrics is not None:
            self.metrics.inflight.labels(rep.rid).set(rep.inflight)
        try:
            await self._pump_sse(resp, out, journal)
        except _BackendLost:
            resp.close()
            if tl is not None:
                tl.advance("resume_gap")
            return await self._resume_stream(
                rep, request, out, journal, headers, tl=tl
            )
        except _ClientGone:
            resp.close()
            return rep
        except BaseException:
            resp.close()
            raise
        finally:
            rep.inflight -= 1
            if self.metrics is not None:
                self.metrics.inflight.labels(rep.rid).set(rep.inflight)
        self.fleet.note_success(rep)
        self._count(rep, "ok")
        resp.release()
        return rep

    async def _splice_resume(self, request: web.Request,
                             out: web.StreamResponse,
                             journal: _StreamJournal, headers: dict,
                             tl=None) -> "Replica | None":
        """The transfer-failure degrade: resubmit the journal's PLAIN
        resume body (no kv_pages — the target re-prefills through the
        PR-14 fold, bit-identically) and splice the continuation. One
        pass over the decode-capable candidates; if none answers, the
        stream ends with the structured error frame — a failed transfer
        must be a performance event, never a dropped stream."""
        try:
            max_new = int(journal.body.get("max_new", 64) or 0)
        except (TypeError, ValueError):
            max_new = 0
        if max_new and len(journal.tokens) >= max_new:
            # the export surfaced every budgeted token: close the
            # stream here (the _resume_stream synthesized-done rule)
            try:
                await self._client_write(out, b'data: {"done": true}\n\n')
            except _ClientGone:
                pass
            return None
        raw = journal.resume_body()
        # role="decode" prefers decode-capable replicas, but the filter
        # falls away when none is live — then even the SOURCE prefill
        # replica is a valid target (it retired/cancelled the original,
        # and a re-prefill resume is admissible anywhere)
        order, _ = self._pick(journal.key, role="decode")
        for rep in order:
            try:
                r = await self._open_backend(
                    f"{rep.url}{request.path}", raw, headers
                )
            except _Unreachable:
                self.fleet.note_failure(rep)
                self._maybe_promote()
                continue
            if r.status == 429:
                await r.read()
                r.release()
                ra = parse_retry_after(
                    r.headers.get("Retry-After"), default=1.0
                )
                rep.cooldown_until = time.monotonic() + ra
                continue
            ctype = r.headers.get("Content-Type", "")
            if r.status != 200 or not ctype.startswith("text/event-stream"):
                await r.read()
                r.release()
                if r.status >= 500:
                    self.fleet.note_failure(rep)
                else:
                    self.fleet.note_success(rep)
                continue
            return await self._pump_leg(
                rep, r, request, out, journal, headers, tl=tl
            )
        self._resume_failures += 1
        self.journal.emit("resume_failed", replica=None,
                          tokens_at_death=len(journal.tokens))
        if tl is not None:
            tl.error_code = "resume_failed"
        await self._error_frame(
            out, "resume_failed",
            "KV transfer failed and no candidate could resume the "
            f"request; partial output ({len(journal.tokens)} tokens) "
            "was delivered",
        )
        return None

    async def _kv_handoff(self, rep: Replica, src_resp,
                          request: web.Request, out: web.StreamResponse,
                          journal: _StreamJournal, headers: dict,
                          tl=None) -> "Replica | None":
        """The transfer itself: export the request's KV pages off the
        prefill replica (which atomically retires it), synthesize any
        tokens the snapshot surfaced past the relay, and resubmit
        resume_out + kv_pages to a decode worker, splicing its stream
        into the same client response. ANY failure — export refused,
        worker unreachable, pool pressure (429 kv_pool_pressure) —
        degrades to the plain re-prefill resume; the page blob is sized
        for this exact moment, so waiting out a 429 would only stale
        it. Returns the finishing replica (None = error frame)."""
        t0 = time.monotonic()
        if tl is not None:
            # the client-perceived stall between the prefill leg's last
            # relayed byte and the decode leg's first — the disagg twin
            # of resume_gap, summed into the timeline's phases
            tl.advance("transfer_gap")
        tokens_at = len(journal.tokens)
        eid = src_resp.headers.get("X-Request-Id")
        exp = None
        with self.tracer.span(
            "kv_transfer", component="router", source=rep.rid,
            tokens_at_transfer=tokens_at,
        ) as span:
            if eid is not None:
                try:
                    r = await self._session.post(
                        f"{rep.url}/v1/kv/export/{eid}", headers=headers,
                        timeout=aiohttp.ClientTimeout(
                            total=max(30.0, self.connect_timeout_s)
                        ),
                    )
                    try:
                        if r.status == 200:
                            exp = await r.json()
                        else:
                            await r.read()
                    finally:
                        r.release()
                except (aiohttp.ClientError, asyncio.TimeoutError,
                        OSError, ValueError):
                    exp = None
            # the export retired the request (or failed — then this
            # disconnect cancels it server-side and the fallback
            # re-prefills); either way the source stream is finished.
            # Closed only AFTER the export returns: closing first would
            # race the disconnect-cancel against the snapshot.
            src_resp.close()
            if exp is not None and not (
                isinstance(exp.get("resume_out"), list)
                and exp["resume_out"]
                and isinstance(exp.get("kv_pages"), dict)
            ):
                exp = None
            target = None
            if exp is not None:
                try:
                    await self._synthesize_gap(out, journal, exp)
                except _ClientGone:
                    return None
                body2 = dict(journal.body)
                body2["resume_out"] = [int(t) for t in exp["resume_out"]]
                body2["resume_logprobs"] = [
                    float(x) for x in (exp.get("resume_logprobs") or ())
                ]
                body2["kv_pages"] = exp["kv_pages"]
                raw2 = json.dumps(body2).encode()
                order, _ = self._pick(journal.key, role="decode")
                for rep2 in order:
                    if rep2 is rep:
                        continue  # the source just dropped these pages
                    try:
                        r2 = await self._open_backend(
                            f"{rep2.url}{request.path}", raw2, headers
                        )
                    except _Unreachable:
                        self.fleet.note_failure(rep2)
                        self._maybe_promote()
                        continue
                    ctype = r2.headers.get("Content-Type", "")
                    if r2.status != 200 or not ctype.startswith(
                        "text/event-stream"
                    ):
                        await r2.read()
                        r2.release()
                        if r2.status == 429:
                            rep2.cooldown_until = (
                                time.monotonic() + parse_retry_after(
                                    r2.headers.get("Retry-After"),
                                    default=1.0,
                                )
                            )
                        elif r2.status >= 500:
                            self.fleet.note_failure(rep2)
                        else:
                            self.fleet.note_success(rep2)
                        continue
                    target = rep2
                    break
            pages = int((exp or {}).get("kv_pages", {}).get("n_pages", 0))
            ms = (time.monotonic() - t0) * 1e3
            if target is None:
                self._count_kv_transfer("fallback", 0, ms)
                self.journal.emit(
                    "kv_transfer", source=rep.rid, target=None,
                    outcome="fallback", tokens_at_transfer=tokens_at,
                )
                if hasattr(span, "set"):
                    span.set(outcome="fallback")
                log.warning(
                    "kv transfer failed; degrading to re-prefill resume",
                    extra={"fields": {"replica": rep.rid,
                                      "tokens_at_transfer": tokens_at}},
                )
                return await self._splice_resume(
                    request, out, journal, headers, tl=tl
                )
            self._count_kv_transfer("ok", pages, ms)
            self.journal.emit(
                "kv_transfer", source=rep.rid, target=target.rid,
                outcome="ok", pages=pages, tokens_at_transfer=tokens_at,
            )
            if hasattr(span, "set"):
                span.set(outcome="ok", target=target.rid, pages=pages)
        return await self._pump_leg(
            target, r2, request, out, journal, headers, tl=tl
        )

    async def _relay_disagg(self, rep: Replica, request: web.Request,
                            raw: bytes, headers: dict,
                            journal: "_StreamJournal | None" = None,
                            tl=None) -> web.StreamResponse:
        """One disaggregated dispatch attempt (the _relay substitute
        the role-aware dispatch loop drives): relay the prefill leg
        until the first token, then hand the stream to _kv_handoff.
        Pre-header failures raise _Unreachable/_Overloaded for the
        failover loop exactly like _relay; a prefill-leg death falls
        back to the normal resume path (re-prefill elsewhere)."""
        url = f"{rep.url}{request.path}"
        if self._flt_connect is not None:
            try:
                self._flt_connect.fire()
            except FaultError as e:
                raise _Unreachable(str(e)) from None
        resp = await self._open_backend(url, raw, headers)
        try:
            if resp.status == 429:
                body = await resp.read()
                ra = parse_retry_after(
                    resp.headers.get("Retry-After"), default=1.0
                )
                raise _Overloaded(
                    body, max(1, int(math.ceil(ra))),
                    resp.headers.get("Content-Type", "application/json")
                    .split(";")[0],
                )
            ctype = resp.headers.get("Content-Type", "")
            if tl is not None:
                tl.relay_on(rep.rid)
            if not ctype.startswith("text/event-stream"):
                # an app-level answer (4xx validation, 5xx): final —
                # relayed verbatim, the dispatch postlude counts it
                body = await resp.read()
                resp.release()
                return web.Response(
                    body=body, status=resp.status,
                    content_type=ctype.split(";")[0] or "application/json",
                )
            out = web.StreamResponse(headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
            })
            await out.prepare(request)
            out.router_final_rep = rep
            try:
                await self._pump_first_token(resp, out, journal)
            except _BackendLost:
                resp.close()
                if tl is not None:
                    tl.advance("resume_gap")
                out.router_final_rep = await self._resume_stream(
                    rep, request, out, journal, headers, tl=tl
                )
            except _ClientGone:
                resp.close()
                return out
            else:
                if journal.closed:
                    # the whole stream fit before the first transfer
                    # point (tiny max_new / instant stop hit): done —
                    # nothing to move
                    self._count_kv_transfer("completed_on_prefill", 0, None)
                    resp.release()
                else:
                    # the prefill replica did its half; the handoff
                    # owns src_resp from here (export-then-close)
                    self.fleet.note_success(rep)
                    out.router_final_rep = await self._kv_handoff(
                        rep, resp, request, out, journal, headers, tl=tl
                    )
            try:
                await out.write_eof()
            except (ConnectionResetError, OSError, RuntimeError):
                pass
            return out
        except (_Overloaded, _Unreachable):
            resp.release()
            raise
        except BaseException:
            resp.close()
            raise
    async def _proxy_get(self, request: web.Request) -> web.Response:
        """GET passthrough (/v1/models): any live replica's answer —
        the fleet serves ONE model, so they all agree. Cooldown AND
        drain are advisory here: both only gate new GENERATION
        admissions, and a cooling or draining replica still serves
        cheap metadata reads — model discovery must not fail for a
        whole rolling-update window."""
        now = time.monotonic()
        candidates = [r for r in self.fleet.all() if r.routable(now)]
        if not candidates:
            candidates = [r for r in self.fleet.all() if r.alive]
        for rep in candidates:
            try:
                async with self._session.get(
                    f"{rep.url}{request.path}",
                    timeout=aiohttp.ClientTimeout(
                        total=self.connect_timeout_s
                    ),
                ) as resp:
                    body = await resp.read()
                    return web.Response(
                        body=body, status=resp.status,
                        content_type=(resp.headers.get("Content-Type", "")
                                      .split(";")[0] or "application/json"),
                    )
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                self.fleet.note_failure(rep)
                continue
        return self._refuse(request.path, "no_replica",
                            "no live replica available")

    # --- fleet operations -------------------------------------------------

    def router_stats(self) -> dict:
        return {
            "policy": self.policy,
            "requests": self._requests,
            "affinity_hits": self._affinity_hits,
            "adapter_requests": dict(self._adapter_requests),
            "failovers": self._failovers,
            "promotions": self._promotions,
            "resumes": self._resumes,
            "resume_failures": self._resume_failures,
            "journaled": self._journaled,
            "unjournaled": self._unjournaled,
            "fleet_budget": self._fleet_budget.stats(),
            "refused": dict(self._refused),
            "outcomes": dict(self._outcomes),
            "journal": self.journal.stats(),
            "kv_transfers": dict(self._kv_transfers),
            "kv_transferred_pages": self._kv_transfer_pages,
            "kv_transfer_ms": list(self._kv_transfer_ms),
            "roles": (
                {r.rid: r.role for r in self.fleet.all()}
                if self._roles_on else {}
            ),
            "timelines": (
                self._recorder.stats() if self._recorder is not None
                else None
            ),
        }

    def fleet_stats(self, include_router: bool = True) -> dict:
        """THE fleet-state snapshot: per-replica state, fleet tallies,
        the admitting count and (by default) the router's own counters,
        built in one pass. Both health handlers read through this
        single accessor — the thread-ownership discipline the
        engine-side ``*_stats()`` snapshots follow (and graftlint
        pins): handlers never recompute per-replica state inline from
        registry objects the health poller mutates.
        ``include_router=False`` skips the router-counter block (dict
        copies, budget/journal/recorder stats) for the LB liveness
        probe, which only reads the snapshot tallies."""
        snap = self.fleet.snapshot()
        snap["admitting"] = sum(
            1 for r in self.fleet.active() if r.alive and not r.draining
        )
        if include_router:
            snap["router"] = self.router_stats()
        return snap

    async def _health(self, request: web.Request) -> web.Response:
        """The router's own liveness (LB probes): up as long as at
        least one replica can ADMIT (alive and not draining) — a fleet
        mid-rolling-drain that refuses every submit must fail the
        probe, not smile at it."""
        snap = self.fleet_stats(include_router=False)
        return web.json_response(
            {"router": True, "alive": snap["admitting"] > 0,
             "policy": self.policy,
             "replicas": snap["total"], "live": snap["live"],
             "admitting": snap["admitting"], "draining": snap["draining"]},
            status=200 if snap["admitting"] else 503,
        )

    async def _fleet_health(self, request: web.Request) -> web.Response:
        return web.json_response(self.fleet_stats())

    async def _drain_wait(self, rep: Replica) -> dict:
        """The drain wait shared by POST /fleet/drain and the rolling
        restart: router-side in-flight zero AND the replica's own
        health showing no admitted work (clients that submitted before
        the drain may still be decoding). The caller has already set
        ``rep.draining``."""
        t0 = time.monotonic()
        while time.monotonic() - t0 < self.drain_timeout_s:
            if rep.inflight == 0:
                h = await self._probe_health(rep)
                if h is not None and not (
                    h.get("active", 0) or h.get("prefilling", 0)
                    or h.get("queued", 0)
                ):
                    secs = time.monotonic() - t0
                    log.info(
                        "replica drained",
                        extra={"fields": {"replica": rep.rid,
                                          "drain_seconds": round(secs, 3)}},
                    )
                    return {"drained": True, "drain_seconds": round(secs, 4)}
                if h is None and not rep.alive:
                    # nothing in flight and the replica is gone: as
                    # drained as it will ever be (the restart case)
                    return {"drained": True, "unreachable": True,
                            "drain_seconds": round(
                                time.monotonic() - t0, 4)}
            await asyncio.sleep(0.05)
        return {"drained": False,
                "drain_seconds": round(time.monotonic() - t0, 4)}

    async def _drain(self, request: web.Request) -> web.Response:
        rid = request.match_info["replica"]
        rep = self.fleet.get(rid)
        if rep is None:
            return web.json_response(
                {"error": f"unknown replica {rid!r}",
                 "replicas": self.fleet.ids()},
                status=404,
            )
        refusal = self.fleet.removal_empties_role(rep)
        if refusal is not None:
            # a specialized fleet must never drain itself into a state
            # where one side of the prefill/decode split has no server
            self.journal.emit("drain_refused", replica=rid,
                              reason="role_empty")
            return web.json_response(
                {"error": refusal, "code": "role_empty"}, status=409
            )
        rep.draining = True
        self.journal.emit("drain", replica=rid)
        log.info("draining replica", extra={"fields": {"replica": rid}})
        res = await self._drain_wait(rep)
        self.journal.emit("drain_done", replica=rid,
                          drained=res["drained"])
        return web.json_response(
            {"replica": rid, "draining": True, **res},
            status=200 if res["drained"] else 504,
        )

    async def _wait_restart(self, rep: Replica, timeout_s: float) -> bool:
        """Wait for a NEW process behind the replica's address:
        ``uptime_s`` on /v1/health resetting below its pre-drain value
        (the restart-detection contract the replicas export for
        exactly this)."""
        before = (rep.health or {}).get("uptime_s")
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout_s:
            h = await self._probe_health(rep)
            if h is not None:
                up = h.get("uptime_s")
                if up is not None and (before is None or up < before):
                    return True
            await asyncio.sleep(min(0.2, max(self.health_interval_s, 0.02)))
        return False

    async def _rolling_restart(self, request: web.Request) -> web.Response:
        """POST /fleet/rolling-restart: sequence drain → restart-wait →
        undrain across every active replica, one at a time — the
        weight-update maintenance cycle as ONE fleet operation. Each
        replica stops taking new work (spilling it to the others),
        finishes every in-flight stream (zero drops, zero from-scratch
        retries — nothing ever dies, so nothing needs the resume path),
        optionally waits for the operator's restart to show (a fresh
        ``uptime_s``; ``wait_restart_s`` in the JSON body, default 0 =
        don't wait), then resumes admission before the next replica
        drains. 504 when any drain times out (that replica is
        undrained and the cycle continues, so a wedge degrades to a
        partial cycle, not a half-drained fleet)."""
        body: dict = {}
        if request.can_read_body:
            try:
                body = await request.json()
            except json.JSONDecodeError:
                return web.json_response(
                    {"error": "body must be JSON"}, status=400
                )
        try:
            wait_restart_s = float(body.get("wait_restart_s", 0.0) or 0.0)
        except (TypeError, ValueError):
            return web.json_response(
                {"error": "wait_restart_s must be a number"}, status=400
            )
        targets = [r for r in self.fleet.active() if r.alive]
        log.info(
            "rolling restart started",
            extra={"fields": {"replicas": [r.rid for r in targets],
                              "wait_restart_s": wait_restart_s}},
        )
        self.journal.emit("rolling_restart",
                          replicas=[r.rid for r in targets])
        results: dict = {}
        completed = True
        for rep in targets:
            refusal = self.fleet.removal_empties_role(rep)
            if refusal is not None:
                # a disaggregated fleet too small to cover a role
                # one-down skips that replica instead of serving a
                # role-less window mid-cycle — the partial-cycle
                # degrade, same stance as a drain timeout
                self.journal.emit("drain_refused", replica=rep.rid,
                                  reason="role_empty")
                results[rep.rid] = {"drained": False,
                                    "refused": "role_empty"}
                completed = False
                continue
            rep.draining = True
            self.journal.emit("rolling_drain", replica=rep.rid)
            res = await self._drain_wait(rep)
            if res["drained"] and wait_restart_s > 0:
                res["restarted"] = await self._wait_restart(
                    rep, wait_restart_s
                )
                completed = completed and res["restarted"]
            rep.draining = False
            self.journal.emit("rolling_undrain", replica=rep.rid,
                              drained=res["drained"])
            results[rep.rid] = res
            completed = completed and res["drained"]
        self.journal.emit("rolling_restart_done", completed=completed)
        return web.json_response(
            {"replicas": results, "completed": completed},
            status=200 if completed else 504,
        )

    async def _undrain(self, request: web.Request) -> web.Response:
        rid = request.match_info["replica"]
        rep = self.fleet.get(rid)
        if rep is None:
            return web.json_response(
                {"error": f"unknown replica {rid!r}",
                 "replicas": self.fleet.ids()},
                status=404,
            )
        rep.draining = False
        self.journal.emit("undrain", replica=rid)
        log.info("undrained replica", extra={"fields": {"replica": rid}})
        return web.json_response(
            {"replica": rid, "draining": False}
        )

    async def _metrics(self, request: web.Request) -> web.Response:
        from prometheus_client import generate_latest

        return web.Response(
            body=generate_latest(self.registry), content_type="text/plain"
        )

    # --- the fleet observability plane (obs/fleet_obs.py) ----------------

    async def _fan_out_get(
        self, path: str, headers: "dict | None" = None
    ) -> "list[tuple[str, int | None, str | None]]":
        """Concurrently GET ``path`` from every registered replica ->
        ``[(rid, status, body_text)]`` in registry order. ``status``
        None = network failure (timeout/refused). Concurrency is the
        point: a fleet with several dead replicas must cost ONE
        connect timeout per pass, not their sum — a sequential scrape
        would blow a Prometheus scrape deadline on the survivors'
        behalf."""

        async def one(rep: Replica):
            try:
                async with self._session.get(
                    f"{rep.url}{path}", headers=headers or {},
                    timeout=aiohttp.ClientTimeout(
                        total=self.connect_timeout_s
                    ),
                ) as resp:
                    return rep.rid, resp.status, await resp.text()
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                return rep.rid, None, None

        return list(await asyncio.gather(
            *(one(rep) for rep in self.fleet.all())
        ))

    async def _plugin_fan_out_get(
        self, path: str
    ) -> "list[tuple[str, int | None, str | None]]":
        """``_fan_out_get`` over the configured device-plugin control
        planes -> ``[(node_id, status, body_text)]`` in spec order."""

        async def one(node: str, base: str):
            try:
                async with self._session.get(
                    f"{base}{path}",
                    timeout=aiohttp.ClientTimeout(
                        total=self.connect_timeout_s
                    ),
                ) as resp:
                    return node, resp.status, await resp.text()
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                return node, None, None

        return list(await asyncio.gather(
            *(one(node, base) for node, base in self.plugins)
        ))

    async def _debug_traces(self, request: web.Request) -> web.Response:
        """The router's OWN trace ring — the third ``/debug/traces``
        plane, accepting the same ``?limit=``/``?since=`` query surface
        as the daemon's and the replicas' (shared
        ``obs/http.parse_trace_query``; 400 on garbage, like them)."""
        from k8s_gpu_device_plugin_tpu.obs.http import (
            parse_trace_query,
            traces_payload,
        )

        try:
            limit, since = parse_trace_query(request.query)
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)
        return web.json_response(
            traces_payload(self.tracer, limit=limit, since_us=since)
        )

    async def _debug_trace_one(self, request: web.Request) -> web.Response:
        from k8s_gpu_device_plugin_tpu.obs.http import trace_detail_payload

        payload = trace_detail_payload(
            self.tracer, request.match_info["trace_id"]
        )
        if payload is None:
            return web.json_response({"error": "trace not in buffer"},
                                     status=404)
        return web.json_response(payload)

    async def _fleet_trace_one(self, request: web.Request) -> web.Response:
        """``GET /fleet/debug/traces/{id}``: pull the trace's span
        fragments from every replica's ``/debug/traces/{id}`` plus the
        router's own ring and stitch them into ONE Perfetto document —
        one process row per replica, the merge summary (per-track span
        counts, orphan fragments, unreachable replicas) under the
        ``fleet`` key."""
        tid = request.match_info["trace_id"]
        fragments: list = []
        own = self.tracer.get_trace(tid)
        if own is not None:
            fragments.append(("router", own))
        unreachable: list[str] = []
        for rid, status, text in await self._fan_out_get(
            f"/debug/traces/{tid}"
        ):
            if status is None:
                # a dead replica's fragments died with it: the stitch
                # reports the hole instead of failing the whole fetch
                unreachable.append(rid)
                continue
            if status == 404:
                continue  # that replica never saw the trace
            if status != 200:
                # an ERRORING replica (500 behind a live socket, a 400)
                # is a hole in the stitch like a dead one — reported,
                # never a silently narrowed trace
                unreachable.append(rid)
                continue
            try:
                payload = json.loads(text)
            except ValueError:
                unreachable.append(rid)
                continue
            fragments.append((rid, spans_from_chrome(payload)))
        stitched = stitched_trace_payload(fragments)
        if stitched is None:
            return web.json_response(
                {"error": "trace not in any replica's buffer",
                 "unreachable": unreachable},
                status=404,
            )
        stitched["fleet"]["unreachable"] = unreachable
        return web.json_response(stitched)

    async def _fleet_metrics(self, request: web.Request) -> web.Response:
        """``GET /fleet/metrics``: scrape every replica's ``/metrics``,
        re-label each series with ``replica="<id>"``, and append the
        fleet aggregates. Content negotiation forwards: an OpenMetrics
        scraper gets OpenMetrics from the replicas (exemplars intact)
        and back out; everyone else gets the classic text format."""
        openmetrics = "application/openmetrics-text" in request.headers.get(
            "Accept", ""
        )
        headers = (
            {"Accept": "application/openmetrics-text; version=1.0.0"}
            if openmetrics else {}
        )
        scrapes: list = []
        errors: list[str] = []
        for rid, status, text in await self._fan_out_get(
            "/metrics", headers=headers
        ):
            if status != 200:
                errors.append(rid)
                continue
            scrapes.append((rid, text))
        plugin_scrapes: "list | None" = None
        plugin_errors: "list[str] | None" = None
        if self.plugins:
            # the plugin plane federates alongside: its /metrics serves
            # the classic format (no exemplars plane-side), which the
            # relabeler merges into either output format
            plugin_scrapes, plugin_errors = [], []
            for node, status, text in await self._plugin_fan_out_get(
                "/metrics"
            ):
                if status != 200 or text is None:
                    plugin_errors.append(node)
                    continue
                plugin_scrapes.append((node, text))
        body = federate_metrics(scrapes, openmetrics=openmetrics,
                                scrape_errors=errors,
                                plugin_scrapes=plugin_scrapes,
                                plugin_scrape_errors=plugin_errors)
        if openmetrics:
            from prometheus_client.openmetrics.exposition import (
                CONTENT_TYPE_LATEST,
            )

            return web.Response(
                text=body, headers={"Content-Type": CONTENT_TYPE_LATEST}
            )
        return web.Response(text=body, content_type="text/plain")

    async def _fleet_events(self, request: web.Request) -> web.Response:
        """``GET /fleet/events``: the journal, oldest-first; ``?since=``
        (a seq) + ``?limit=`` page it forward through the same
        parse_trace_query surface as the trace planes (400 on
        garbage)."""
        from k8s_gpu_device_plugin_tpu.obs.http import parse_trace_query

        try:
            limit, since = parse_trace_query(
                request.query, since_desc="event seq"
            )
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)
        payload = self.journal.events_payload(limit=limit, since=since)
        if not self.plugins:
            return web.json_response(payload)
        # merge the plugin allocation journals in: fleet events first
        # (by their seq), then each node's events in spec order (by
        # that node's own seq) — deterministic, never wall-clock. The
        # ``plane`` discriminator is stamped at merge time so neither
        # journal stores a field only this endpoint needs; ``since`` /
        # ``limit`` forward to each plugin journal (their own seq
        # spaces — one cursor idiom, per-plane cursors).
        for e in payload["events"]:
            e["plane"] = "fleet"
        query = []
        if limit is not None:
            query.append(f"limit={limit}")
        if since is not None:
            query.append(f"since={since}")
        qs = ("?" + "&".join(query)) if query else ""
        plugin_errors: list[str] = []
        for node, status, text in await self._plugin_fan_out_get(
            f"/debug/allocations{qs}"
        ):
            if status != 200 or text is None:
                plugin_errors.append(node)
                continue
            try:
                data = json.loads(text).get("data") or {}
            except (ValueError, AttributeError):
                plugin_errors.append(node)
                continue
            for e in data.get("events", ()):
                e["plane"] = "plugin"
                e["node"] = node
                payload["events"].append(e)
        payload["returned"] = len(payload["events"])
        payload["plugin_nodes"] = [node for node, _ in self.plugins]
        if plugin_errors:
            payload["plugin_errors"] = plugin_errors
        return web.json_response(payload)

    async def _fleet_requests(self, request: web.Request) -> web.Response:
        if self._recorder is None:
            return web.json_response(
                {"error": "router timelines disabled (start without "
                          "--timelinesOff)"},
                status=404,
            )
        return web.json_response(self._recorder.request_stats())

    async def _fleet_request_one(
        self, request: web.Request
    ) -> web.Response:
        if self._recorder is None:
            return web.json_response(
                {"error": "router timelines disabled (start without "
                          "--timelinesOff)"},
                status=404,
            )
        try:
            rid = int(request.match_info["rid"])
        except ValueError:
            return web.json_response(
                {"error": "rid must be an integer"}, status=400
            )
        record = self._recorder.get(rid)
        if record is None:
            return web.json_response(
                {"error": "request not in the timeline buffer"}, status=404
            )
        return web.json_response(record)


def _main(argv: list[str] | None = None) -> int:
    """CLI: route two HTTP API surfaces across N replica backends."""
    import argparse

    parser = argparse.ArgumentParser(prog="tpu-replica-router")
    parser.add_argument("--replicas", required=True,
                        help="comma list of replica backends: "
                        "[id=]http://host:port,... (id defaults to "
                        "host:port, matching the replica's own "
                        "--replicaId default)")
    parser.add_argument("--port", type=int, default=8100)
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--policy", default="affinity",
                        choices=["affinity", "rr"],
                        help="'affinity' (default) routes each request's "
                        "bucket-aligned token-prefix path onto a "
                        "consistent-hash ring with bounded-load spill, "
                        "so shared-prefix tenants land where their "
                        "prefix cache lives; 'rr' round-robins (the "
                        "serve-bench A/B arm)")
    parser.add_argument("--loadFactor", type=float, default=1.25,
                        help="bounded-load spill: a ring candidate "
                        "already carrying more than this times the "
                        "fleet's mean in-flight count spills to the "
                        "next candidate")
    parser.add_argument("--healthIntervalS", type=float, default=1.0,
                        help="replica /v1/health poll cadence")
    parser.add_argument("--deadAfter", type=int, default=3,
                        help="consecutive health/proxy failures before a "
                        "replica is routed around (any success revives)")
    parser.add_argument("--drainTimeoutS", type=float, default=120.0,
                        help="POST /fleet/drain/{replica} gives up (504, "
                        "drained:false) after this long")
    parser.add_argument("--warmSpares", type=int, default=0,
                        help="hold the LAST N --replicas entries off the "
                        "ring as warm standbys: registered and health-"
                        "polled but unrouted, promoted into the ring "
                        "(affinity keys remapped) when an active "
                        "replica is marked dead — surfaced on "
                        "/fleet/health and tpu_router_promotions_total")
    parser.add_argument("--fleetRestartBudget", type=int, default=3,
                        help="mid-stream replica deaths the router may "
                        "resume per rolling --fleetRestartWindowS (one "
                        "charge per replica DEATH, however many streams "
                        "it carried): within budget, journaled native "
                        "SSE streams splice onto the next ring candidate "
                        "through the resume seam with zero re-emitted "
                        "tokens; past it (or with 0) streams end with "
                        "the structured error frame — never a silent "
                        "truncation")
    parser.add_argument("--fleetRestartWindowS", type=float, default=300.0,
                        help="rolling window for --fleetRestartBudget")
    parser.add_argument("--promptBuckets", default="",
                        help="comma list of prompt-bucket boundaries "
                        "for the affinity key (default: the batcher's "
                        "DEFAULT_PROMPT_BUCKETS ladder). MUST match the "
                        "replicas' effective ladder — custom buckets or "
                        "a small --maxLen trimming it — or affinity "
                        "keys cut where no cache ever promotes")
    parser.add_argument("--adapterNames", default="",
                        help="comma list of LoRA adapter names the "
                        "replicas serve (--loraAdapters there): a "
                        "request selecting a listed adapter folds it "
                        "into the affinity key so the adapter's "
                        "traffic lands where its stacks are already "
                        "HBM-resident; unlisted/base requests route "
                        "exactly as without this flag")
    parser.add_argument("--headerTimeoutS", type=float, default=300.0,
                        help="bound the header phase of a dispatch so a "
                        "wedged replica (socket accepts, never answers) "
                        "fails over like a connection failure within "
                        "the timeout instead of hanging the client "
                        "forever; the default sits above a non-streamed "
                        "generate's cold-compile worst case (headers "
                        "arrive only at completion — minutes); 0 "
                        "restores unbounded")
    parser.add_argument("--faults", default="",
                        help="seeded fault injection (serving/faults.py) "
                        "for the router-side points router.connect / "
                        "router.midstream, e.g. 'router.connect:nth=2'; "
                        "also read from TPU_SERVING_FAULTS; empty = "
                        "disarmed")
    parser.add_argument("--tracing", action="store_true",
                        help="span tracing: router spans propagate to "
                        "the replicas via traceparent; the router's own "
                        "ring serves GET /debug/traces and feeds the "
                        "stitched GET /fleet/debug/traces/{id}")
    parser.add_argument("--journalEvents", type=int, default=1024,
                        help="fleet event journal ring size (GET "
                        "/fleet/events: failover, 429 cooldown, drain/"
                        "undrain, warm-spare promotion, stream resume, "
                        "rolling-restart phases, budget exhaustion)")
    parser.add_argument("--timelinesOff", action="store_true",
                        help="disable router-side request timelines + "
                        "the flight recorder (GET /fleet/debug/"
                        "requests): the proxy hot path then pays only "
                        "is-not-None guards")
    parser.add_argument("--plugins", default="",
                        help="device-plugin control planes to federate: "
                        "comma list of [id=]http://host:port (id "
                        "defaults to host:port). Their /metrics joins "
                        "GET /fleet/metrics with node= relabeling plus "
                        "fleet chip aggregates, and their allocation "
                        "journals join GET /fleet/events with "
                        "plane=\"plugin\"; empty = replica-only fleet")
    parser.add_argument("--slowStreamMs", type=float, default=0.0,
                        help="flight-recorder SLO threshold: streams "
                        "whose router wall time reaches this are "
                        "retained alongside the always-retained "
                        "resumed/failed-over/error streams (0 = only "
                        "those)")
    parser.add_argument("--roles", default="",
                        help="disaggregated serving roles: whitespace/"
                        "semicolon-separated 'role=id,id' groups over "
                        "the --replicas ids, e.g. "
                        "'prefill=r0,r1 decode=r2'. Unlisted replicas "
                        "stay 'any' (serve both). When any role is "
                        "assigned, long prompts prefill on a prefill-"
                        "capable replica and their KV pages transfer "
                        "to a decode worker at the first token")
    parser.add_argument("--disaggMinPrompt", type=int, default=64,
                        help="prompts at least this many tokens long "
                        "take the disaggregated prefill->transfer->"
                        "decode path (shorter ones route straight to "
                        "decode-capable replicas); only meaningful "
                        "with --roles")
    args = parser.parse_args(argv)

    if args.tracing:
        from k8s_gpu_device_plugin_tpu.obs.prom import SpanMetrics
        from k8s_gpu_device_plugin_tpu.obs.trace import configure
        from prometheus_client import REGISTRY as _SPAN_REGISTRY

        SpanMetrics(registry=_SPAN_REGISTRY).install(configure(enabled=True))

    from prometheus_client import REGISTRY

    buckets = None
    if args.promptBuckets:
        try:
            buckets = tuple(
                int(b) for b in args.promptBuckets.split(",") if b.strip()
            )
        except ValueError:
            raise SystemExit(
                f"--promptBuckets {args.promptBuckets!r}: expected a "
                "comma list of integers"
            ) from None

    from k8s_gpu_device_plugin_tpu.serving.faults import FaultPlane

    fault_plane = FaultPlane.from_cli(args.faults)

    plugins: "list[tuple[str, str]]" = []
    for entry in (e.strip() for e in args.plugins.split(",")):
        if not entry:
            continue
        if "=" in entry:
            node, _, url = entry.partition("=")
        else:
            url = entry
            node = url.split("://", 1)[-1].rstrip("/")
        plugins.append((node.strip(), url.strip().rstrip("/")))

    fleet = FleetRegistry.from_spec(args.replicas, dead_after=args.deadAfter)
    router = ReplicaRouter(
        fleet, host=args.host, port=args.port, policy=args.policy,
        prompt_buckets=buckets,
        load_factor=args.loadFactor,
        health_interval_s=args.healthIntervalS,
        drain_timeout_s=args.drainTimeoutS,
        header_timeout_s=args.headerTimeoutS,
        warm_spares=args.warmSpares,
        fleet_restart_budget=args.fleetRestartBudget,
        fleet_restart_window_s=args.fleetRestartWindowS,
        journal_events=args.journalEvents,
        timelines=not args.timelinesOff,
        slow_stream_ms=args.slowStreamMs,
        registry=REGISTRY, metrics=RouterMetrics(registry=REGISTRY),
        faults=fault_plane,
        roles=args.roles or None,
        disagg_min_prompt=args.disaggMinPrompt,
        plugins=plugins,
        adapter_names=tuple(
            n.strip() for n in args.adapterNames.split(",") if n.strip()
        ),
    )

    async def serve():
        stop = asyncio.Event()
        import signal

        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await router.run(stop)

    asyncio.run(serve())
    return 0


if __name__ == "__main__":
    raise SystemExit(_main())
