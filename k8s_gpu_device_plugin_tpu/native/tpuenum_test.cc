// C++ unit test for the enumeration core, run against a synthetic
// $TPUENUM_ROOT tree (no hardware). `make test`.

#include "tpuenum.h"

#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/stat.h>

#include <fstream>
#include <string>

static int failures = 0;
#define CHECK(cond)                                                   \
  do {                                                                \
    if (!(cond)) {                                                    \
      fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      ++failures;                                                     \
    }                                                                 \
  } while (0)

static void WriteFile(const std::string& path, const std::string& content) {
  std::ofstream f(path);
  f << content;
}

static std::string MakeFakeHost(int chips) {
  char tmpl[] = "/tmp/tpuenum_test_XXXXXX";
  std::string root = mkdtemp(tmpl);
  mkdir((root + "/dev").c_str(), 0755);
  mkdir((root + "/sys").c_str(), 0755);
  mkdir((root + "/sys/class").c_str(), 0755);
  mkdir((root + "/sys/class/accel").c_str(), 0755);
  mkdir((root + "/etc").c_str(), 0755);
  WriteFile(root + "/etc/machine-id", "deadbeefcafe\n");
  for (int i = 0; i < chips; ++i) {
    WriteFile(root + "/dev/accel" + std::to_string(i), "");
    const std::string base =
        root + "/sys/class/accel/accel" + std::to_string(i);
    mkdir(base.c_str(), 0755);
    mkdir((base + "/device").c_str(), 0755);
    WriteFile(base + "/device/numa_node", i < chips / 2 ? "0\n" : "1\n");
    WriteFile(base + "/device/device", "0x0062\n");  // v5p
  }
  return root;
}

int main() {
  const std::string root = MakeFakeHost(4);
  setenv("TPUENUM_ROOT", root.c_str(), 1);

  CHECK(tpuenum_chip_count() == 4);

  TpuChipInfo infos[8];
  const int n = tpuenum_enumerate(infos, 8);
  CHECK(n == 4);
  for (int i = 0; i < n; ++i) {
    CHECK(infos[i].index == i);
    CHECK(strncmp(infos[i].path, "/dev/accel", 10) == 0);
    CHECK(strncmp(infos[i].uuid, "TPU-", 4) == 0);
    CHECK(strcmp(infos[i].generation, "v5p") == 0);
    CHECK(infos[i].numa_node == (i < 2 ? 0 : 1));
  }
  // UUIDs distinct & stable
  CHECK(strcmp(infos[0].uuid, infos[1].uuid) != 0);
  TpuChipInfo again[8];
  tpuenum_enumerate(again, 8);
  CHECK(strcmp(infos[0].uuid, again[0].uuid) == 0);

  char gen[16];
  CHECK(tpuenum_generation(gen, sizeof(gen)) == 3);
  CHECK(strcmp(gen, "v5p") == 0);

  // Empty root (no devices)
  setenv("TPUENUM_ROOT", "/nonexistent_tpuenum", 1);
  CHECK(tpuenum_chip_count() == 0);
  setenv("TPUENUM_ROOT", root.c_str(), 1);

  // internal_edges: a 2x2 block in a 2x4 mesh has 4 edges
  const int32_t coords[] = {0, 0, 0, 1, 1, 0, 1, 1};
  const int32_t bounds[] = {2, 4};
  CHECK(tpuenum_internal_edges(coords, 4, bounds, 2) == 4);
  // a 1x4 row has 3 edges
  const int32_t row[] = {0, 0, 0, 1, 0, 2, 0, 3};
  CHECK(tpuenum_internal_edges(row, 4, bounds, 2) == 3);
  // scattered corners: 0 edges
  const int32_t corners[] = {0, 0, 1, 3};
  CHECK(tpuenum_internal_edges(corners, 2, bounds, 2) == 0);
  // bad args
  CHECK(tpuenum_internal_edges(nullptr, 1, bounds, 2) == -1);
  CHECK(tpuenum_internal_edges(coords, 4, bounds, 9) == -1);

  // torus wraparound: a full column of a 4x4 torus closes into a ring
  const int32_t sq_bounds[] = {4, 4};
  const int32_t wrap_yes[] = {1, 1};
  const int32_t col[] = {0, 0, 1, 0, 2, 0, 3, 0};
  CHECK(tpuenum_internal_edges_wrap(col, 4, sq_bounds, nullptr, 2) == 3);
  CHECK(tpuenum_internal_edges_wrap(col, 4, sq_bounds, wrap_yes, 2) == 4);
  // boundary pair joined only by the wrap link
  const int32_t ends[] = {0, 0, 3, 0};
  CHECK(tpuenum_internal_edges_wrap(ends, 2, sq_bounds, wrap_yes, 2) == 1);
  CHECK(tpuenum_internal_edges_wrap(ends, 2, sq_bounds, nullptr, 2) == 0);
  // extent-2 axis never gains a wrap edge (same physical link)
  const int32_t pair[] = {0, 0, 1, 0};
  const int32_t small_bounds[] = {2, 4};
  CHECK(tpuenum_internal_edges_wrap(pair, 2, small_bounds, wrap_yes, 2) == 1);

  if (failures == 0) {
    printf("tpuenum_test: all checks passed\n");
    return 0;
  }
  fprintf(stderr, "tpuenum_test: %d failures\n", failures);
  return 1;
}
