// Native data loader: parallel gather of token windows from a packed
// corpus (the C++ runtime component backing data/pipeline.py, the way
// tpuenum.cc backs device enumeration).
//
// The Python MemmapSource slices B windows from an np.memmap serially on
// the main thread: on a cold TB-scale corpus every slice is a chain of
// page faults, and the uint16->int32 widening runs single-threaded. This
// library mmaps the file once and gathers all B windows with a worker
// pool — page faults overlap across threads and the widening is
// parallel — into one caller-owned contiguous int32 buffer (exactly the
// array the trainer feeds to jax.device_put).
//
// Deliberately dependency-free C++17 + POSIX (mmap/pread), bound via
// ctypes (data/native_loader.py); windows are (start, len) pairs the
// Python side computes, so the deterministic sampling recipe stays in
// ONE place and the native path is bit-identical to the Python one.

#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <thread>
#include <vector>

namespace {

struct Corpus {
  const uint8_t* base = nullptr;  // mmap'ed file
  size_t bytes = 0;
  int fd = -1;
  int dtype_code = 0;  // 2 = uint16, 4 = uint32 (element width in bytes)
};

size_t elem_width(int dtype_code) { return static_cast<size_t>(dtype_code); }

}  // namespace

extern "C" {

// Open a packed token file. dtype_code: 2 (uint16) or 4 (uint32).
// Returns an opaque handle (heap pointer) or null on failure.
void* dataload_open(const char* path, int dtype_code) {
  if (dtype_code != 2 && dtype_code != 4) return nullptr;
  int fd = ::open(path, O_RDONLY | O_CLOEXEC);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size <= 0) {
    ::close(fd);
    return nullptr;
  }
  void* map = ::mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ,
                     MAP_PRIVATE, fd, 0);
  if (map == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  // Access is random-start windows: telling the kernel WILLNEED over the
  // whole mapping would queue readahead of the entire (possibly TB-scale)
  // file and thrash page cache. Disable whole-file readahead; each gather
  // schedules WILLNEED for exactly the windows it is about to touch.
  ::madvise(map, static_cast<size_t>(st.st_size), MADV_RANDOM);
  auto* c = new Corpus();
  c->base = static_cast<const uint8_t*>(map);
  c->bytes = static_cast<size_t>(st.st_size);
  c->fd = fd;
  c->dtype_code = dtype_code;
  return c;
}

// Number of tokens in the corpus (0 on null handle).
int64_t dataload_len(void* handle) {
  if (handle == nullptr) return 0;
  auto* c = static_cast<Corpus*>(handle);
  return static_cast<int64_t>(c->bytes / elem_width(c->dtype_code));
}

// Gather n_rows windows of row_len tokens each, widening to int32.
// starts[i] is a TOKEN offset; every window [starts[i], starts[i]+row_len)
// must lie inside the corpus — returns the number of rows gathered
// (== n_rows on success; 0 on any out-of-range row, leaving `out`
// unspecified). `threads` <= 0 picks a default.
int32_t dataload_gather(void* handle, const int64_t* starts, int32_t n_rows,
                        int32_t row_len, int32_t* out, int32_t threads) {
  if (handle == nullptr || starts == nullptr || out == nullptr ||
      n_rows <= 0 || row_len <= 0) {
    return 0;
  }
  auto* c = static_cast<Corpus*>(handle);
  const int64_t n_tokens = dataload_len(handle);
  for (int32_t i = 0; i < n_rows; ++i) {
    if (starts[i] < 0 || starts[i] + row_len > n_tokens) return 0;
  }
  // Schedule readahead for exactly the windows this gather touches (the
  // mapping itself is MADV_RANDOM, so the kernel won't read ahead on its
  // own). madvise wants page-aligned starts; lengths may be unaligned.
  {
    const long page = ::sysconf(_SC_PAGESIZE);
    const size_t pmask = page > 0 ? static_cast<size_t>(page) - 1 : 4095;
    const size_t width = elem_width(c->dtype_code);
    for (int32_t i = 0; i < n_rows; ++i) {
      const size_t lo = static_cast<size_t>(starts[i]) * width;
      const size_t hi = lo + static_cast<size_t>(row_len) * width;
      const size_t alo = lo & ~pmask;
      ::madvise(const_cast<uint8_t*>(c->base) + alo, hi - alo, MADV_WILLNEED);
    }
  }
  int nthreads = threads > 0 ? threads
                             : static_cast<int>(
                                   std::thread::hardware_concurrency());
  if (nthreads < 1) nthreads = 1;
  if (nthreads > n_rows) nthreads = n_rows;
  if (nthreads > 16) nthreads = 16;

  std::atomic<int32_t> next{0};
  auto worker = [&]() {
    for (;;) {
      const int32_t i = next.fetch_add(1, std::memory_order_relaxed);
      if (i >= n_rows) return;
      int32_t* dst = out + static_cast<size_t>(i) * row_len;
      const size_t off = static_cast<size_t>(starts[i]);
      if (c->dtype_code == 2) {
        const uint16_t* src =
            reinterpret_cast<const uint16_t*>(c->base) + off;
        for (int32_t j = 0; j < row_len; ++j) dst[j] = src[j];
      } else {
        const uint32_t* src =
            reinterpret_cast<const uint32_t*>(c->base) + off;
        for (int32_t j = 0; j < row_len; ++j) {
          dst[j] = static_cast<int32_t>(src[j]);
        }
      }
    }
  };
  if (nthreads == 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    pool.reserve(nthreads);
    for (int t = 0; t < nthreads; ++t) pool.emplace_back(worker);
    for (auto& th : pool) th.join();
  }
  return n_rows;
}

void dataload_close(void* handle) {
  if (handle == nullptr) return;
  auto* c = static_cast<Corpus*>(handle);
  ::munmap(const_cast<uint8_t*>(c->base), c->bytes);
  ::close(c->fd);
  delete c;
}

}  // extern "C"
