// TPU chip enumeration & ICI topology core — implementation.
//
// Enumerates TPU chips from device nodes and sysfs without touching the TPU
// runtime (no PjRt client, no libtpu load — the daemon must never hold the
// single-client runtime lock workload pods need).
//
// Sources scanned, in order:
//   1. $TPUENUM_ROOT/dev/accel<N>          (TPU v4+ "accel"/gasket driver)
//   2. $TPUENUM_ROOT/dev/vfio/<N>          (VFIO-attached chips, v5e pods)
// Per-chip metadata from sysfs:
//   /sys/class/accel/accel<N>/device/numa_node
//   /sys/class/accel/accel<N>/device/device   (PCI device id -> generation)
// Stable UUIDs are derived from /etc/machine-id + chip index (FNV-1a), the
// same role NVML UUIDs played for the reference (device/device.go:37-43).

#include "tpuenum.h"

#include <dirent.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <algorithm>
#include <fstream>
#include <set>
#include <sstream>
#include <string>
#include <vector>

namespace {

std::string Root() {
  const char* root = getenv("TPUENUM_ROOT");
  return root ? std::string(root) : std::string();
}

// PCI device ids of Google TPU generations (vendor 0x1ae0), as exposed by
// the accel driver. Best-effort public table; unknown ids yield "".
struct GenEntry {
  uint32_t device_id;
  const char* name;
};
constexpr GenEntry kGenerations[] = {
    {0x0027, "v2"}, {0x0037, "v3"}, {0x005e, "v4"},
    {0x0062, "v5p"}, {0x0063, "v5e"}, {0x006f, "v6e"},
};

std::string ReadTrimmed(const std::string& path) {
  std::ifstream f(path);
  if (!f.good()) return "";
  std::string s;
  std::getline(f, s);
  while (!s.empty() && (s.back() == '\n' || s.back() == '\r' || s.back() == ' '))
    s.pop_back();
  return s;
}

bool DirEntries(const std::string& dir, std::vector<std::string>* out) {
  DIR* d = opendir(dir.c_str());
  if (d == nullptr) return false;
  while (dirent* e = readdir(d)) {
    const std::string name = e->d_name;
    if (name != "." && name != "..") out->push_back(name);
  }
  closedir(d);
  return true;
}

struct RawChip {
  int index;
  std::string path;        // absolute device node path (without root prefix)
  std::string sysfs_base;  // sysfs dir for this chip ("" if none)
};

// Numeric suffix of `name` after `prefix`, or -1.
int NumSuffix(const std::string& name, const std::string& prefix) {
  if (name.rfind(prefix, 0) != 0 || name.size() == prefix.size()) return -1;
  const std::string digits = name.substr(prefix.size());
  if (!std::all_of(digits.begin(), digits.end(),
                   [](char c) { return c >= '0' && c <= '9'; }))
    return -1;
  return atoi(digits.c_str());
}

std::vector<RawChip> ScanChips() {
  const std::string root = Root();
  std::vector<RawChip> chips;
  std::set<int> seen;

  // 1) accel driver nodes.
  std::vector<std::string> names;
  if (DirEntries(root + "/dev", &names)) {
    for (const auto& name : names) {
      const int idx = NumSuffix(name, "accel");
      if (idx < 0 || seen.count(idx)) continue;
      seen.insert(idx);
      RawChip chip;
      chip.index = idx;
      chip.path = "/dev/" + name;
      chip.sysfs_base = root + "/sys/class/accel/" + name + "/device";
      chips.push_back(chip);
    }
  }

  // 2) VFIO nodes (numeric entries under /dev/vfio, excluding the control
  //    node "vfio"). Only used when no accel nodes exist — a host exposes
  //    chips through one driver. Metadata (NUMA node, PCI device id) is
  //    recovered through the IOMMU group's member device in sysfs:
  //    /sys/kernel/iommu_groups/<N>/devices/<pci-addr> is a (symlinked)
  //    PCI device dir carrying numa_node + device like the accel path.
  if (chips.empty()) {
    names.clear();
    if (DirEntries(root + "/dev/vfio", &names)) {
      std::vector<int> groups;
      for (const auto& name : names) {
        const int idx = NumSuffix(name, "");
        if (idx >= 0) groups.push_back(idx);
      }
      std::sort(groups.begin(), groups.end());
      int logical = 0;
      for (int group : groups) {
        RawChip chip;
        chip.index = logical++;
        chip.path = "/dev/vfio/" + std::to_string(group);
        const std::string group_dir =
            root + "/sys/kernel/iommu_groups/" + std::to_string(group) + "/devices";
        std::vector<std::string> members;
        if (DirEntries(group_dir, &members) && !members.empty()) {
          std::sort(members.begin(), members.end());
          chip.sysfs_base = group_dir + "/" + members[0];
        }
        chips.push_back(chip);
      }
    }
  }

  std::sort(chips.begin(), chips.end(),
            [](const RawChip& a, const RawChip& b) { return a.index < b.index; });
  return chips;
}

std::string DetectGeneration(const std::vector<RawChip>& chips,
                             int32_t* source /* may be null */) {
  if (source != nullptr) *source = TPUENUM_GEN_UNKNOWN;
  for (const auto& chip : chips) {
    if (chip.sysfs_base.empty()) continue;
    const std::string id_s = ReadTrimmed(chip.sysfs_base + "/device");
    if (id_s.empty()) continue;
    const uint32_t id = strtoul(id_s.c_str(), nullptr, 16);
    for (const auto& gen : kGenerations) {
      if (gen.device_id == id) {
        if (source != nullptr) *source = TPUENUM_GEN_PCI;
        return gen.name;
      }
    }
  }
  // Fallback: the TPU VM environment often states the type directly. An
  // env-derived generation is a CLAIM, not a measurement — callers should
  // surface it loudly (a wrong value skews every MFU/HBM figure derived
  // from the generation table).
  const char* accel_type = getenv("TPU_ACCELERATOR_TYPE");
  if (accel_type != nullptr) {
    const std::string s(accel_type);
    const size_t dash = s.find('-');
    if (source != nullptr) *source = TPUENUM_GEN_ENV;
    return dash == std::string::npos ? s : s.substr(0, dash);
  }
  return "";
}

// sysfs attribute names probed for per-chip memory size, in preference
// order. Best-effort forward-compat: current accel/gasket drivers expose
// none of these (callers then fill from the generation table); a driver
// that does expose capacity gets the measured value.
const char* kHbmAttrs[] = {"hbm_bytes", "memory_size", "mem_size"};

int64_t ReadHbmBytes(const std::string& sysfs_base) {
  if (sysfs_base.empty()) return 0;
  for (const char* attr : kHbmAttrs) {
    const std::string s = ReadTrimmed(sysfs_base + "/" + attr);
    if (s.empty()) continue;
    const long long v = strtoll(s.c_str(), nullptr, 10);
    if (v > 0) return static_cast<int64_t>(v);
  }
  return 0;
}

// FNV-1a 64-bit over machine-id + index for stable, distinct UUIDs.
uint64_t Fnv1a(const std::string& data) {
  uint64_t h = 1469598103934665603ull;
  for (unsigned char c : data) {
    h ^= c;
    h *= 1099511628211ull;
  }
  return h;
}

void FillUuid(char* out, size_t out_len, const std::string& machine_id, int index) {
  const uint64_t h = Fnv1a(machine_id + "/" + std::to_string(index));
  snprintf(out, out_len, "TPU-%08x-%04x-%04x-%04x-%08x%04x",
           static_cast<uint32_t>(h >> 32),
           static_cast<uint32_t>((h >> 16) & 0xffff),
           static_cast<uint32_t>(h & 0xffff),
           static_cast<uint32_t>((h >> 48) & 0xffff), static_cast<uint32_t>(h),
           static_cast<uint32_t>(index & 0xffff));
}

}  // namespace

extern "C" {

int32_t tpuenum_chip_count(void) {
  return static_cast<int32_t>(ScanChips().size());
}

int32_t tpuenum_enumerate(TpuChipInfo* out, int32_t max) {
  if (out == nullptr || max < 0) return -1;
  const std::string root = Root();
  const std::vector<RawChip> chips = ScanChips();
  const std::string gen = DetectGeneration(chips, nullptr);
  std::string machine_id = ReadTrimmed(root + "/etc/machine-id");
  if (machine_id.empty()) machine_id = "tpuhost";

  const int32_t n = std::min<int32_t>(max, static_cast<int32_t>(chips.size()));
  for (int32_t i = 0; i < n; ++i) {
    const RawChip& chip = chips[i];
    TpuChipInfo* info = &out[i];
    memset(info, 0, sizeof(*info));
    info->index = chip.index;
    info->numa_node = -1;
    info->hbm_bytes = ReadHbmBytes(chip.sysfs_base);
    if (!chip.sysfs_base.empty()) {
      const std::string numa = ReadTrimmed(chip.sysfs_base + "/numa_node");
      if (!numa.empty()) info->numa_node = atoi(numa.c_str());
    }
    snprintf(info->path, sizeof(info->path), "%s", chip.path.c_str());
    snprintf(info->generation, sizeof(info->generation), "%s", gen.c_str());
    FillUuid(info->uuid, sizeof(info->uuid), machine_id, chip.index);
  }
  return n;
}

int32_t tpuenum_generation(char* out, int32_t max) {
  if (out == nullptr || max <= 0) return 0;
  const std::string gen = DetectGeneration(ScanChips(), nullptr);
  snprintf(out, static_cast<size_t>(max), "%s", gen.c_str());
  return static_cast<int32_t>(strlen(out));
}

int32_t tpuenum_generation_source(void) {
  int32_t source = TPUENUM_GEN_UNKNOWN;
  DetectGeneration(ScanChips(), &source);
  return source;
}

int32_t tpuenum_internal_edges(const int32_t* coords, int32_t n,
                               const int32_t* bounds, int32_t dims) {
  return tpuenum_internal_edges_wrap(coords, n, bounds, nullptr, dims);
}

int32_t tpuenum_internal_edges_wrap(const int32_t* coords, int32_t n,
                                    const int32_t* bounds, const int32_t* wrap,
                                    int32_t dims) {
  if (coords == nullptr || bounds == nullptr || n < 0 || dims <= 0 || dims > 3)
    return -1;
  std::set<std::vector<int32_t>> cells;
  for (int32_t i = 0; i < n; ++i) {
    cells.insert(std::vector<int32_t>(coords + i * dims, coords + (i + 1) * dims));
  }
  int32_t edges = 0;
  for (const auto& cell : cells) {
    for (int32_t axis = 0; axis < dims; ++axis) {
      std::vector<int32_t> neighbor = cell;
      neighbor[axis] += 1;  // count each edge once (positive direction)
      if (neighbor[axis] >= bounds[axis]) {
        // Torus closure: the +1 step off the boundary lands on cell 0. Only
        // a real extra link when the ring has > 2 cells (at 2, forward and
        // "wrap" are the same physical link, already counted).
        if (wrap == nullptr || wrap[axis] == 0 || bounds[axis] <= 2) continue;
        neighbor[axis] = 0;
      }
      if (cells.count(neighbor)) ++edges;
    }
  }
  return edges;
}

}  // extern "C"
