// TPU chip enumeration & ICI topology core — C ABI.
//
// Native replacement for the reference's cgo surface (go-nvml device
// handles, go-nvlib traversal, go-gpuallocator topology scoring; see
// SURVEY.md §2 native table). Consumed from Python via ctypes
// (k8s_gpu_device_plugin_tpu/device/native.py).
//
// Design constraint (SURVEY §7 hard part #1): libtpu is single-client —
// enumeration must NOT create a PjRt client or otherwise take the TPU
// runtime lock. Everything here reads device nodes and sysfs only.
//
// Testability: all filesystem access is rooted at $TPUENUM_ROOT (default
// ""), so tests point the library at a synthetic /dev + /sys tree.

#ifndef TPUENUM_H_
#define TPUENUM_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct TpuChipInfo {
  int32_t index;
  int32_t numa_node;      // -1 if unknown
  int32_t coord[3];       // ICI mesh coordinate; all-zero if driver-unknown
  int64_t hbm_bytes;      // 0 if unknown (caller fills from generation table)
  char uuid[64];          // stable id: machine-id + chip index
  char path[64];          // /dev/accel<N> or /dev/vfio/<N>
  char generation[16];    // "v4"/"v5e"/"v5p"/"v6e" or "" if unknown
} TpuChipInfo;

// Number of TPU chips visible on this host (accel + vfio device nodes).
int32_t tpuenum_chip_count(void);

// Fill up to `max` entries; returns number written, or -1 on error.
int32_t tpuenum_enumerate(TpuChipInfo* out, int32_t max);

// Host TPU generation name into `out` (NUL-terminated, truncated to `max`).
// Returns length written, 0 if unknown.
int32_t tpuenum_generation(char* out, int32_t max);

// Where the generation name came from. PCI-id detection is a measurement;
// the TPU_ACCELERATOR_TYPE env fallback is an unverified claim, and callers
// should surface non-PCI sources loudly (a wrong generation skews every
// MFU/HBM figure derived from the per-generation spec table).
#define TPUENUM_GEN_UNKNOWN 0
#define TPUENUM_GEN_PCI 1
#define TPUENUM_GEN_ENV 2
int32_t tpuenum_generation_source(void);

// ICI edges internal to the chip set `coords` (len = n*dims, row-major)
// within a mesh of shape `bounds` (len = dims). Neighbors differ by 1 on one
// axis (no wraparound). Returns edge count, or -1 on bad arguments.
// This is the scoring kernel behind aligned allocation (the go-gpuallocator
// analogue); Python falls back to its own implementation if absent.
int32_t tpuenum_internal_edges(const int32_t* coords, int32_t n,
                               const int32_t* bounds, int32_t dims);

// Torus-aware variant: `wrap` (len = dims, may be NULL = no wrap) flags axes
// whose ICI closes into a ring — v5e/v6e 4x4-and-larger slices, v4/v5p
// cube-multiple slices (OCS wraparound). A wrap edge on an axis exists only
// when that axis extent is > 2 (at extent 2 the "wrap" link is the same
// physical link counted forward). Returns edge count, or -1 on bad args.
int32_t tpuenum_internal_edges_wrap(const int32_t* coords, int32_t n,
                                    const int32_t* bounds, const int32_t* wrap,
                                    int32_t dims);

#ifdef __cplusplus
}  // extern "C"
#endif

#endif  // TPUENUM_H_
