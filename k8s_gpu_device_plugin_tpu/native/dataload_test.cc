// Native data-loader test: open/len/gather/close round trip, bounds
// rejection, and multi-threaded gather determinism. Runs in `make test`
// and under ASan+UBSan in `make san-test` (SURVEY §5 sanitizer row).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include <unistd.h>

#include <vector>

extern "C" {
void* dataload_open(const char* path, int dtype_code);
int64_t dataload_len(void* handle);
int32_t dataload_gather(void* handle, const int64_t* starts, int32_t n_rows,
                        int32_t row_len, int32_t* out, int32_t threads);
void dataload_close(void* handle);
}

#define CHECK(cond)                                                      \
  do {                                                                   \
    if (!(cond)) {                                                       \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,       \
                   #cond);                                               \
      std::exit(1);                                                      \
    }                                                                    \
  } while (0)

int main() {
  // write a corpus of 1000 uint16 tokens: token[i] = i
  char path[] = "/tmp/dataload_test_XXXXXX";
  int fd = mkstemp(path);
  CHECK(fd >= 0);
  std::vector<uint16_t> tokens(1000);
  for (size_t i = 0; i < tokens.size(); ++i) {
    tokens[i] = static_cast<uint16_t>(i);
  }
  CHECK(write(fd, tokens.data(), tokens.size() * 2) ==
        static_cast<ssize_t>(tokens.size() * 2));
  close(fd);

  CHECK(dataload_open(path, 3) == nullptr);          // bad dtype
  CHECK(dataload_open("/nonexistent", 2) == nullptr);

  void* h = dataload_open(path, 2);
  CHECK(h != nullptr);
  CHECK(dataload_len(h) == 1000);

  // gather 4 windows of 16, single- and multi-threaded: identical, and
  // each value equals its global token index
  const int64_t starts[4] = {0, 17, 500, 984};
  std::vector<int32_t> out1(4 * 16), out8(4 * 16);
  CHECK(dataload_gather(h, starts, 4, 16, out1.data(), 1) == 4);
  CHECK(dataload_gather(h, starts, 4, 16, out8.data(), 8) == 4);
  CHECK(std::memcmp(out1.data(), out8.data(), out1.size() * 4) == 0);
  for (int r = 0; r < 4; ++r) {
    for (int j = 0; j < 16; ++j) {
      CHECK(out1[r * 16 + j] == static_cast<int32_t>(starts[r]) + j);
    }
  }

  // out-of-range rows reject the whole gather
  const int64_t bad[1] = {985};  // 985 + 16 > 1000
  CHECK(dataload_gather(h, bad, 1, 16, out1.data(), 1) == 0);
  const int64_t neg[1] = {-1};
  CHECK(dataload_gather(h, neg, 1, 16, out1.data(), 1) == 0);
  CHECK(dataload_gather(nullptr, starts, 4, 16, out1.data(), 1) == 0);

  dataload_close(h);
  dataload_close(nullptr);  // must be a no-op
  unlink(path);
  std::puts("dataload_test OK");
  return 0;
}
