"""Structured logging with per-level rotated JSON files.

Reference: modules/log/log.go — a zap wrapper writing per-level JSON files
(``app-{error,warn,info,debug}.log``) through lumberjack rotation
(100MB x 60 backups x 30 days, compressed; log.go:131-146), a tee of four
level-filtered cores (log.go:148-184), and an optional colored console in dev
mode (log.go:173-180).

This rebuild keeps the operational contract (same file names, same JSON field
names ``level/ts/caller/msg``, same rotation budget) on the stdlib ``logging``
stack, and fixes the reference's quirk at log.go:113 where error output was
routed over stdout instead of stderr.
"""

from __future__ import annotations

import gzip
import json
import logging
import logging.handlers
import os
import sys
from dataclasses import dataclass, field

from k8s_gpu_device_plugin_tpu.obs.trace import current_trace_ids

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
}

# Reference rotation budget (modules/log/log.go:91-93).
MAX_BYTES = 100 * 1024 * 1024
BACKUP_COUNT = 60


def parse_level(name: str) -> int:
    """Parse a level name, defaulting to INFO (reference log.go:258-273)."""
    return _LEVELS.get(name.strip().lower(), logging.INFO)


class JsonFormatter(logging.Formatter):
    """One JSON object per line: {"level", "ts", "caller", "msg", ...extras}."""

    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "level": record.levelname.lower(),
            "ts": round(record.created, 6),
            "caller": f"{record.filename}:{record.lineno}",
            "msg": record.getMessage(),
        }
        # Trace correlation: prefer the ids TraceContextFilter stamped at
        # emit time (a handler may format much later — queue handlers,
        # test captures); fall back to the ambient span for records that
        # bypassed the project logger's filter chain.
        trace_id = getattr(record, "trace_id", None)
        span_id = getattr(record, "span_id", None)
        if trace_id is None:
            ids = current_trace_ids()
            if ids is not None:
                trace_id, span_id = ids
        if trace_id is not None:
            entry["trace_id"] = trace_id
            entry["span_id"] = span_id
        if record.exc_info and record.exc_info[0] is not None:
            entry["exc"] = self.formatException(record.exc_info)
        extra = getattr(record, "fields", None)
        if isinstance(extra, dict):
            entry.update(extra)
        return json.dumps(entry, default=str)


class ConsoleFormatter(logging.Formatter):
    """Dev-mode human console line (≙ zap's colored console, log.go:173-180):

    ``HH:MM:SS.mmm LEVEL caller  msg  k=v k=v``, level colorized when the
    stream is a terminal (or ``color`` is forced). Files always stay JSON —
    this formatter is console-only sugar.
    """

    _COLORS = {
        logging.DEBUG: "\x1b[35m",     # magenta
        logging.INFO: "\x1b[34m",      # blue
        logging.WARNING: "\x1b[33m",   # yellow
        logging.ERROR: "\x1b[31m",     # red
        logging.CRITICAL: "\x1b[31m",
    }
    _RESET = "\x1b[0m"

    def __init__(self, color: bool | None = None) -> None:
        super().__init__()
        self._color = color

    def format(self, record: logging.LogRecord) -> str:
        level = record.levelname
        color = self._color
        if color is None:
            color = getattr(sys.stderr, "isatty", lambda: False)()
        if color:
            code = self._COLORS.get(record.levelno, "")
            level = f"{code}{level}{self._RESET}"
        ts = self.formatTime(record, "%H:%M:%S") + f".{int(record.msecs):03d}"
        line = (
            f"{ts} {level:<7} {record.filename}:{record.lineno}  "
            f"{record.getMessage()}"
        )
        extra = getattr(record, "fields", None)
        if isinstance(extra, dict) and extra:
            kv = " ".join(f"{k}={v}" for k, v in extra.items())
            line = f"{line}  {kv}"
        if record.exc_info and record.exc_info[0] is not None:
            line = f"{line}\n{self.formatException(record.exc_info)}"
        return line


class TraceContextFilter(logging.Filter):
    """Stamp the ambient trace/span ids onto every record at EMIT time.

    Logger filters run in the emitting call stack, where the contextvar
    still holds the active span; handlers may format later (rotation,
    queue handlers, test captures) from another context entirely. One
    ContextVar read per record when tracing is off/idle."""

    def filter(self, record: logging.LogRecord) -> bool:
        if not hasattr(record, "trace_id"):
            ids = current_trace_ids()
            if ids is not None:
                record.trace_id, record.span_id = ids
        return True


class _ExactLevelFilter(logging.Filter):
    """Admit records of exactly one level (the per-level tee, log.go:148-170)."""

    def __init__(self, level: int, and_above: bool = False) -> None:
        super().__init__()
        self._level = level
        self._and_above = and_above

    def filter(self, record: logging.LogRecord) -> bool:
        if self._and_above:
            return record.levelno >= self._level
        return record.levelno == self._level


class GzipRotatingFileHandler(logging.handlers.RotatingFileHandler):
    """RotatingFileHandler that gzips rolled files (lumberjack Compress=true)."""

    def rotation_filename(self, default_name: str) -> str:
        return default_name + ".gz"

    def rotate(self, source: str, dest: str) -> None:
        try:
            with open(source, "rb") as fsrc, gzip.open(dest, "wb") as fdst:
                while chunk := fsrc.read(1 << 20):
                    fdst.write(chunk)
            os.remove(source)
        except OSError:  # rotation must never take the daemon down
            pass


@dataclass
class LogConfig:
    """Reference ``LogConfig`` knobs (modules/log/log.go + config/config.go:13)."""

    level: str = "debug"
    file_dir: str | None = None  # None => console only
    console: bool = True
    dev_mode: bool = False       # human console lines instead of JSON
    name: str = "tpu-device-plugin"
    max_bytes: int = MAX_BYTES
    backup_count: int = BACKUP_COUNT
    extra_fields: dict = field(default_factory=dict)


# Per-level file tee: (filename suffix, exact level) — log.go:131-146.
_FILE_LEVELS = [
    ("error", logging.ERROR),
    ("warn", logging.WARNING),
    ("info", logging.INFO),
    ("debug", logging.DEBUG),
]

_logger: logging.Logger | None = None


def init_logger(cfg: LogConfig | None = None) -> logging.Logger:
    """Build (or rebuild) the global logger (reference log.InitLogger, log.go:66)."""
    global _logger
    cfg = cfg or LogConfig()
    logger = logging.getLogger(cfg.name)
    logger.setLevel(parse_level(cfg.level))
    logger.propagate = False
    for h in list(logger.handlers):
        logger.removeHandler(h)
        h.close()
    # idempotent across re-inits: exactly one trace-context stamper
    for f in list(logger.filters):
        if isinstance(f, TraceContextFilter):
            logger.removeFilter(f)
    logger.addFilter(TraceContextFilter())

    formatter = JsonFormatter()
    if cfg.file_dir:
        os.makedirs(cfg.file_dir, exist_ok=True)
        for suffix, level in _FILE_LEVELS:
            if level < logger.level:
                continue
            handler = GzipRotatingFileHandler(
                os.path.join(cfg.file_dir, f"app-{suffix}.log"),
                maxBytes=cfg.max_bytes,
                backupCount=cfg.backup_count,
            )
            # error file collects >= ERROR (incl. fatal); others are exact-level.
            handler.addFilter(_ExactLevelFilter(level, and_above=level == logging.ERROR))
            handler.setFormatter(formatter)
            logger.addHandler(handler)

    if cfg.console or not cfg.file_dir:
        console = logging.StreamHandler(sys.stderr)
        console.setFormatter(ConsoleFormatter() if cfg.dev_mode else formatter)
        logger.addHandler(console)

    _logger = logger
    return logger


def get_logger() -> logging.Logger:
    """The process-global logger (reference ``log.Logger``, log.go:25)."""
    global _logger
    if _logger is None:
        _logger = init_logger()
    return _logger


def with_fields(logger: logging.Logger, **fields) -> logging.LoggerAdapter:
    """Attach structured fields to every record (zap's With)."""

    class _Adapter(logging.LoggerAdapter):
        def process(self, msg, kwargs):
            extra = kwargs.setdefault("extra", {})
            merged = dict(fields)
            merged.update(extra.get("fields", {}))
            extra["fields"] = merged
            return msg, kwargs

    return _Adapter(logger, {})
