"""Shared modules (reference: modules/{log,util,watch,version})."""

from k8s_gpu_device_plugin_tpu.utils.latch import Latch
from k8s_gpu_device_plugin_tpu.utils.envelope import failed, success
from k8s_gpu_device_plugin_tpu.utils.version import VERSION

__all__ = ["Latch", "success", "failed", "VERSION"]
