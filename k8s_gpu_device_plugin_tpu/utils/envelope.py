"""JSON response envelope (reference: modules/util/http.go:3-15).

The reference wraps every HTTP payload as ``{"code": 200|500, "data": ...,
"msg": "..."}``; `success` and `failed` mirror that contract so operators'
tooling carries over unchanged.
"""

from __future__ import annotations

from typing import Any


def success(data: Any = None, msg: str = "success") -> dict[str, Any]:
    return {"code": 200, "data": data, "msg": msg}


def failed(msg: str, code: int = 500, data: Any = None) -> dict[str, Any]:
    return {"code": code, "data": data, "msg": msg}
