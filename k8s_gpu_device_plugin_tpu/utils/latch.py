"""Idempotent readiness latch.

Reference: ``util.CloseOnce`` (modules/util/util.go:10-14) — a channel closed
exactly once, used to delay the HTTP server until the plugin manager has
registered with the kubelet (main.go:63-71, plugin/manager.go:72).

This version is usable from both sync code and asyncio: ``set()`` is
idempotent and thread-safe; waiters can block (``wait``) or await
(``wait_async``).
"""

from __future__ import annotations

import asyncio
import threading


class Latch:
    """A one-shot, idempotent readiness signal."""

    def __init__(self) -> None:
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._async_waiters: list[tuple[asyncio.AbstractEventLoop, asyncio.Event]] = []

    def set(self) -> None:
        """Open the latch. Safe to call any number of times from any thread."""
        with self._lock:
            if self._event.is_set():
                return
            self._event.set()
            waiters, self._async_waiters = self._async_waiters, []
        for loop, event in waiters:
            loop.call_soon_threadsafe(event.set)

    def is_set(self) -> bool:
        return self._event.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        return self._event.wait(timeout)

    async def wait_async(self) -> None:
        with self._lock:
            if self._event.is_set():
                return
            loop = asyncio.get_running_loop()
            event = asyncio.Event()
            self._async_waiters.append((loop, event))
        await event.wait()
