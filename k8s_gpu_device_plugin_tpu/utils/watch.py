"""Filesystem and signal watching.

Reference: modules/watch/watch.go — ``Files(...)`` builds an fsnotify watcher
over a path list (watch.go:11-26); the manager uses it to detect the kubelet
restarting (re-creation of ``kubelet.sock``, plugin/manager.go:59,80-84).
``Signals(...)`` (watch.go:29-34) wraps signal.Notify.

Instead of a third-party fsnotify dependency this uses the Linux ``inotify``
syscalls directly through ctypes (the platform the kubelet device-plugin API
exists on is Linux), with a polling fallback for non-Linux dev machines.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import errno
import os
import select
import signal
import struct
import threading
from dataclasses import dataclass
from typing import Callable, Iterable

# inotify event masks (linux/inotify.h)
IN_CREATE = 0x00000100
IN_DELETE = 0x00000200
IN_MOVED_TO = 0x00000080
IN_MODIFY = 0x00000002
IN_ATTRIB = 0x00000004
IN_DELETE_SELF = 0x00000400

_EVENT_HDR = struct.Struct("iIII")  # wd, mask, cookie, len


@dataclass(frozen=True)
class FileEvent:
    path: str      # watched directory
    name: str      # entry name within it ("" for self events)
    mask: int

    @property
    def full_path(self) -> str:
        return os.path.join(self.path, self.name) if self.name else self.path

    @property
    def is_create(self) -> bool:
        return bool(self.mask & (IN_CREATE | IN_MOVED_TO))


class FileWatcher:
    """Watch directories for entry create/delete/modify events.

    Usage mirrors the reference's fsnotify watcher: construct over paths, then
    iterate ``events()`` (blocking generator) or poll ``poll(timeout)``.
    """

    def __init__(self, paths: Iterable[str]) -> None:
        self._paths = [str(p) for p in paths]
        self._wd_to_path: dict[int, str] = {}
        self._libc = None
        self._fd = -1
        self._closed = False
        self._start()

    def _start(self) -> None:
        try:
            libc_name = ctypes.util.find_library("c") or "libc.so.6"
            libc = ctypes.CDLL(libc_name, use_errno=True)
            fd = libc.inotify_init1(os.O_NONBLOCK)
            if fd < 0:
                raise OSError(ctypes.get_errno(), "inotify_init1")
            mask = IN_CREATE | IN_DELETE | IN_MOVED_TO | IN_MODIFY | IN_DELETE_SELF
            for path in self._paths:
                wd = libc.inotify_add_watch(fd, path.encode(), mask)
                if wd < 0:
                    err = ctypes.get_errno()
                    os.close(fd)
                    raise OSError(err, f"inotify_add_watch({path})")
                self._wd_to_path[wd] = path
            self._libc, self._fd = libc, fd
        except (OSError, AttributeError):
            # Non-Linux or restricted environment: fall back to polling.
            self._libc, self._fd = None, -1
            self._snapshots = {p: self._snapshot(p) for p in self._paths}

    @staticmethod
    def _snapshot(path: str) -> dict[str, float]:
        try:
            out = {}
            for name in os.listdir(path):
                try:
                    out[name] = os.stat(os.path.join(path, name)).st_mtime
                except OSError:
                    pass
            return out
        except OSError:
            return {}

    def fileno(self) -> int:
        return self._fd

    def poll(self, timeout: float | None = None) -> list[FileEvent]:
        """Return pending events, waiting up to ``timeout`` seconds."""
        if self._closed:
            return []
        if self._fd >= 0:
            ready, _, _ = select.select([self._fd], [], [], timeout)
            if not ready:
                return []
            return self._drain()
        # polling fallback
        import time

        time.sleep(min(timeout or 0.5, 0.5))
        events: list[FileEvent] = []
        for path in self._paths:
            old, new = self._snapshots.get(path, {}), self._snapshot(path)
            for name in new.keys() - old.keys():
                events.append(FileEvent(path, name, IN_CREATE))
            for name in old.keys() - new.keys():
                events.append(FileEvent(path, name, IN_DELETE))
            for name in new.keys() & old.keys():
                if new[name] != old[name]:
                    events.append(FileEvent(path, name, IN_MODIFY))
            self._snapshots[path] = new
        return events

    def _drain(self) -> list[FileEvent]:
        events: list[FileEvent] = []
        try:
            data = os.read(self._fd, 64 * 1024)
        except OSError as e:
            if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                return events
            raise
        offset = 0
        while offset + _EVENT_HDR.size <= len(data):
            wd, mask, _cookie, name_len = _EVENT_HDR.unpack_from(data, offset)
            offset += _EVENT_HDR.size
            raw = data[offset : offset + name_len]
            offset += name_len
            name = raw.split(b"\0", 1)[0].decode(errors="replace")
            path = self._wd_to_path.get(wd, "")
            events.append(FileEvent(path, name, mask))
        return events

    def close(self) -> None:
        self._closed = True
        if self._fd >= 0:
            os.close(self._fd)
            self._fd = -1

    def __enter__(self) -> "FileWatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def signals(
    handler: Callable[[int], None],
    signums: Iterable[int] = (signal.SIGHUP, signal.SIGINT, signal.SIGTERM, signal.SIGQUIT),
) -> None:
    """Install a handler for shutdown signals (reference watch.go:29-34)."""
    for signum in signums:
        signal.signal(signum, lambda s, _frame: handler(s))


class SignalLatch:
    """Collects the first received signal and wakes waiters (main.go:83-110)."""

    def __init__(self, signums: Iterable[int] = (signal.SIGINT, signal.SIGTERM)) -> None:
        self.received: int | None = None
        self._event = threading.Event()
        signals(self._on_signal, signums)

    def _on_signal(self, signum: int) -> None:
        if self.received is None:
            self.received = signum
        self._event.set()

    def wait(self, timeout: float | None = None) -> int | None:
        self._event.wait(timeout)
        return self.received
