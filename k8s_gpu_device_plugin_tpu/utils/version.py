"""Framework version (reference: modules/version/version.go:4)."""

VERSION = "0.1.0"
