"""HTTP control plane server.

Reference: server/server.go (echo server with Recover/CORS/request-logger/
Prometheus middleware, async start + ctx shutdown, 30s read timeout at :46)
+ router/api.go routes:

- ``GET /``        -> version string            (api.go:40-42)
- ``GET /metrics`` -> Prometheus exposition     (api.go:32)
- ``GET /health``  -> static ok                 (api.go:45-47)
- ``GET /restart`` -> PluginManager.Restart     (api.go:50-54)

Design deltas from the reference, on purpose:
- routes register on the app instance, not a process-global mutable registry
  (router/router.go:9-19 double-registers if Run is called twice);
- the server waits on the readiness latch before binding, same behavior as
  main.go:128 but owned by the server itself;
- restart is delivered through the manager's asyncio event (no shared-bool
  race, see plugin/manager.py).
"""

from __future__ import annotations

import asyncio
import logging
import time

from aiohttp import web
from prometheus_client import REGISTRY, generate_latest, CONTENT_TYPE_LATEST

from k8s_gpu_device_plugin_tpu.config import Config
from k8s_gpu_device_plugin_tpu.metrics import DeviceMetrics, HttpMetrics
from k8s_gpu_device_plugin_tpu.metrics.runtime_metrics import usage_reader_from_config
from k8s_gpu_device_plugin_tpu.obs.http import (
    profile_payload,
    route_label,
    trace_detail_payload,
    traces_payload,
)
from k8s_gpu_device_plugin_tpu.obs.trace import (
    TRACEPARENT_HEADER,
    format_traceparent,
    get_tracer,
    parse_traceparent,
)
from k8s_gpu_device_plugin_tpu.plugin.manager import PluginManager
from k8s_gpu_device_plugin_tpu.utils.envelope import failed, success
from k8s_gpu_device_plugin_tpu.utils.latch import Latch
from k8s_gpu_device_plugin_tpu.utils.log import get_logger
from k8s_gpu_device_plugin_tpu.utils.version import VERSION

READ_TIMEOUT_SECONDS = 30.0  # server/server.go:46


class Server:
    """aiohttp control plane bound to ``cfg.web_listen_address``."""

    def __init__(
        self,
        cfg: Config,
        manager: PluginManager,
        ready: Latch,
        logger: logging.Logger | None = None,
        registry=REGISTRY,
        usage_reader=None,
        profiler=None,
    ) -> None:
        self.cfg = cfg
        self.manager = manager
        self.ready = ready
        self.log = logger or get_logger()
        self.registry = registry
        # optional benchmark.profiler.Profiler (main.py --benchmark):
        # /debug/profile serves its live BlockSampler summary
        self.profiler = profiler
        self.tracer = get_tracer()
        # span-duration histograms (obs/prom.py) ride this registry only
        # when tracing is on at construction — a disabled tracer never
        # produces spans, so the listener would be dead weight
        self.span_metrics = None
        if self.tracer.enabled:
            from k8s_gpu_device_plugin_tpu.obs.prom import SpanMetrics

            self.span_metrics = SpanMetrics(registry=registry).install(
                self.tracer
            )
        self.http_metrics = HttpMetrics(registry=registry)
        # ``usage_reader`` lets main.py share ONE reader (one gRPC channel
        # set) between these gauges and the manager's health assessor —
        # two independent readers would double-scrape every endpoint and
        # serially burn two RPC timeouts during a wedge.
        self.device_metrics = DeviceMetrics(
            usage_reader=usage_reader or usage_reader_from_config(cfg),
            registry=registry,
        )
        self.routes = {
            "/", "/health", "/metrics", "/restart",
            "/debug/traces", "/debug/traces/{trace_id}", "/debug/profile",
            "/debug/allocations", "/debug/topology",
        }
        self.app = self._build_app()
        self._runner: web.AppRunner | None = None
        self.port: int | None = None  # actual bound port (useful when 0)

    def _build_app(self) -> web.Application:
        # Outermost first: recovery+access-log wraps everything (≙ the
        # reference wiring Recover and the request logger before metrics,
        # server/server.go:40-43).
        app = web.Application(
            middlewares=[
                self._recovery_middleware,
                self.http_metrics.aiohttp_middleware(self.routes),
                self._cors_middleware,
            ]
        )
        app.router.add_get("/", self._version)
        app.router.add_get("/health", self._health)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/restart", self._restart)
        app.router.add_get("/debug/traces", self._debug_traces)
        app.router.add_get("/debug/traces/{trace_id}", self._debug_trace_one)
        app.router.add_get("/debug/profile", self._debug_profile)
        app.router.add_get("/debug/allocations", self._debug_allocations)
        app.router.add_get("/debug/topology", self._debug_topology)
        return app

    # --- handlers (≙ router/api.go) ---

    async def _version(self, request: web.Request) -> web.Response:
        return web.json_response(success(f"tpu-device-plugin version: {VERSION}"))

    async def _health(self, request: web.Request) -> web.Response:
        return web.json_response(success("ok"))

    async def _metrics(self, request: web.Request) -> web.Response:
        # refresh device gauges from the live (health-applied) device sets
        self.device_metrics.update_inventory(self.manager.live_chip_map())
        backend = self.manager.backend
        self.device_metrics.set_generation_source(
            backend.host_topology().generation.name,
            getattr(backend, "generation_source", backend.name),
        )
        # usage scrape does blocking gRPC calls (up to 1s/port on a hung
        # workload endpoint) -> keep the event loop (health probes, kubelet
        # RPCs) responsive by scraping in a worker thread
        await asyncio.get_running_loop().run_in_executor(
            None, self.device_metrics.update_usage
        )
        body = generate_latest(self.registry)
        return web.Response(body=body, headers={"Content-Type": CONTENT_TYPE_LATEST})

    async def _restart(self, request: web.Request) -> web.Response:
        self.manager.restart()
        return web.json_response(success("restart scheduled"))

    # --- observability debug surface (obs/) ---

    async def _debug_traces(self, request: web.Request) -> web.Response:
        from k8s_gpu_device_plugin_tpu.obs.http import parse_trace_query

        try:
            limit, since = parse_trace_query(request.query)
        except ValueError as e:
            return web.json_response(failed(str(e)), status=400)
        return web.json_response(
            success(traces_payload(self.tracer, limit=limit, since_us=since))
        )

    async def _debug_trace_one(self, request: web.Request) -> web.Response:
        trace_id = request.match_info["trace_id"]
        payload = trace_detail_payload(self.tracer, trace_id)
        if payload is None:
            return web.json_response(
                failed(f"trace {trace_id!r} not in buffer"), status=404
            )
        # raw Chrome/Perfetto trace-event JSON, NOT enveloped: the body
        # must load in chrome://tracing / ui.perfetto.dev as saved
        return web.json_response(payload)

    async def _debug_profile(self, request: web.Request) -> web.Response:
        payload = profile_payload(self.profiler)
        if payload is None:
            return web.json_response(
                failed("profiling not enabled (start with benchmark: true)"),
                status=404,
            )
        return web.json_response(success(payload))

    async def _debug_allocations(self, request: web.Request) -> web.Response:
        """The allocation journal (plugin/journal.py): every Allocate,
        preferred-allocation decision, and chip-health transition as a
        sequenced event. Shares the ``?limit=``/``?since=`` surface with
        /debug/traces — here ``since`` means event seq."""
        from k8s_gpu_device_plugin_tpu.obs.http import parse_trace_query

        try:
            limit, since = parse_trace_query(
                request.query, since_desc="event seq"
            )
        except ValueError as e:
            return web.json_response(failed(str(e)), status=400)
        return web.json_response(
            success(self.manager.journal.events_payload(limit=limit, since=since))
        )

    async def _debug_topology(self, request: web.Request) -> web.Response:
        """Chip map + ICI links + ownership: the physical grid this host
        advertises, which device (and which live allocation) owns each
        chip, and the torus edges between them."""
        topo = self.manager.backend.host_topology()
        owners = self.manager.journal.owners()
        # health + device membership from the live (health-applied) sets
        chip_health: dict[int, str] = {}
        chip_device: dict[int, dict] = {}
        devices: dict[str, list] = {}
        for resource, chips in sorted(self.manager.live_chip_map().items()):
            rows = []
            for chip in chips.iter_sorted():
                rows.append({
                    "id": chip.id,
                    "health": chip.health,
                    "chip_indices": list(chip.chip_indices),
                    "coords": [list(c) for c in chip.coords],
                })
                for idx in chip.chip_indices:
                    chip_health[idx] = chip.health
                    chip_device[idx] = {"resource": resource, "id": chip.id}
            devices[resource] = rows
        coords = topo.coords()
        links: set = set()
        for coord in coords:
            a = topo.index_of(coord)
            for n in topo.neighbors(coord):
                b = topo.index_of(n)
                links.add((min(a, b), max(a, b)))
        return web.json_response(success({
            "generation": topo.generation.name,
            "bounds": list(topo.bounds),
            "num_chips": topo.num_chips,
            "chips": [
                {
                    "index": topo.index_of(coord),
                    "coord": list(coord),
                    "health": chip_health.get(topo.index_of(coord), ""),
                    "device": chip_device.get(topo.index_of(coord)),
                    "owner": owners.get(topo.index_of(coord)),
                }
                for coord in coords
            ],
            "links": [list(pair) for pair in sorted(links)],
            "devices": devices,
        }))

    # --- middleware (≙ echo Recover + request logger, server/server.go:40-43) ---

    @web.middleware
    async def _recovery_middleware(self, request: web.Request, handler):
        """Structured access log for every request; unexpected handler
        exceptions become an enveloped 500 with a stack trace in the log
        instead of aiohttp's bare error page. With tracing enabled, each
        request runs under a span (joining the caller's W3C
        ``traceparent`` when present), so the access-log record carries
        the trace/span ids and the response echoes a ``traceparent``."""
        if not self.tracer.enabled:
            return await self._handle_logged(request, handler, None)
        remote = parse_traceparent(request.headers.get(TRACEPARENT_HEADER))
        # span name carries the CANONICAL route (bounded — it becomes a
        # histogram label in obs/prom.py); the raw path rides as an attr
        with self.tracer.span(
            f"{request.method} {route_label(request)}", component="http",
            parent=remote, method=request.method, path=request.path,
        ) as span:
            return await self._handle_logged(request, handler, span)

    async def _handle_logged(self, request: web.Request, handler, span):
        start = time.monotonic()
        try:
            response = await handler(request)
        except web.HTTPException as http_err:
            response = http_err  # deliberate status (404 etc.): log + pass on
        except Exception:  # noqa: BLE001 - the recovery seam by definition
            self.log.exception(
                "handler panic recovered",
                extra={"fields": {"method": request.method, "path": request.path}},
            )
            response = web.json_response(failed("internal server error"), status=500)
            # this response short-circuits the inner CORS middleware
            self._apply_cors(response)
        self.log.info(
            "http request",
            extra={"fields": {
                "method": request.method,
                "path": request.path,
                "status": response.status,
                "remote": request.remote,
                "duration_ms": round((time.monotonic() - start) * 1000, 2),
            }},
        )
        if span is not None:
            span.set(status_code=response.status)
            response.headers[TRACEPARENT_HEADER] = format_traceparent(span)
        if isinstance(response, web.HTTPException):
            raise response
        return response

    # --- middleware (≙ hand-rolled CORS, server/server.go:77-96) ---

    @staticmethod
    def _apply_cors(response) -> None:
        response.headers["Access-Control-Allow-Origin"] = "*"
        response.headers["Access-Control-Allow-Methods"] = "GET,OPTIONS"
        response.headers["Access-Control-Allow-Headers"] = "Content-Type"

    @web.middleware
    async def _cors_middleware(self, request: web.Request, handler):
        if request.method == "OPTIONS":
            response = web.Response(status=204)
        else:
            response = await handler(request)
        self._apply_cors(response)
        return response

    # --- lifecycle (≙ Server.Run, server/server.go:55-68) ---

    async def run(self, stop_event: asyncio.Event) -> None:
        """Wait for readiness, bind, serve until ``stop_event``."""
        await self.ready.wait_async()
        host, port = self.cfg.listen_addr
        self._runner = web.AppRunner(
            self.app, keepalive_timeout=READ_TIMEOUT_SECONDS
        )
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        actual = self._runner.addresses[0] if self._runner.addresses else (host, port)
        self.port = actual[1]
        self.log.info(
            "http control plane listening",
            extra={"fields": {"addr": f"{actual[0]}:{actual[1]}",
                              "routes": sorted(self.routes)}},
        )
        try:
            await stop_event.wait()
        finally:
            await self._runner.cleanup()
            self._runner = None
            if self.span_metrics is not None:
                # detach the tracer listener so a later server (tests,
                # daemon restart) can register the same collector names
                self.span_metrics.close()
                self.span_metrics = None
