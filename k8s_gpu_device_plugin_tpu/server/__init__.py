"""HTTP control plane (reference: server/, router/, middleware/)."""

from k8s_gpu_device_plugin_tpu.server.server import Server

__all__ = ["Server"]
