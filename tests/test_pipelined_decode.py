"""The pipelined decode loop (pipeline_depth=1) vs the synchronous one.

The pipeline's claim is exact, not approximate: dispatching step t+1
before reading step t back must be INVISIBLE in the outputs — token
streams AND per-token logprobs bit-identical to pipeline_depth=0 across
every scheduling event that can interleave with an in-flight step
(admission, retirement by stop sequence / budget / EOS, cancellation,
chunked prefill, slot reuse, seeded sampling). On top of identity, the
steady-state loop must hold its device-array caches stable (the
zero-per-step-H2D design), flush the in-flight step on membership
changes (the stale-token attribution hazard), and show the overlap in
the opt-in per-step trace spans.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.batching import ContinuousBatcher
from k8s_gpu_device_plugin_tpu.models.generate import generate
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig, init_params
from k8s_gpu_device_plugin_tpu.models.sampling import Sampler


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(n_layers=2)
    params = init_params(jax.random.key(0), cfg)
    return cfg, params


def _prompt(key, n, cfg):
    return jax.random.randint(
        jax.random.key(key), (n,), 1, cfg.vocab_size, jnp.int32
    ).tolist()


def _oracle(params, prompt, cfg, max_new):
    out = generate(
        params, jnp.asarray([prompt], jnp.int32), cfg, max_new=max_new
    )
    return np.asarray(out)[0].tolist()


def _streams(cb):
    """{rid: (tokens, logprobs)} for every retired request."""
    return {
        rid: (list(req.out), list(req.out_logp))
        for rid, req in cb.done_requests.items()
    }


# --- bit-identity scenarios -------------------------------------------------
#
# Each scenario drives a fresh batcher (the depth is the only difference)
# and returns its full {rid: (tokens, logprobs)} map; the test asserts
# depth-0 and depth-1 agree EXACTLY — same compiled step, same inputs,
# so equality is bitwise, floats included.


def _scenario_bucketed_churn(params, cfg, depth):
    """More requests than slots through bucketed prefill: every
    retirement (budget) frees a slot for the next admission while a
    step is in flight."""
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, prompt_buckets=(4, 8, 16),
        pipeline_depth=depth,
    )
    for key, plen, new in [(1, 5, 6), (2, 12, 4), (3, 3, 8), (4, 9, 5)]:
        cb.submit(_prompt(key, plen, cfg), max_new=new)
    cb.run()
    return _streams(cb)


def _scenario_chunked_midstream(params, cfg, depth):
    """Chunked prefill interleaving with decode, plus a midstream
    submission landing while a step is in flight."""
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, chunked_prefill=4,
        pipeline_depth=depth,
    )
    cb.submit(_prompt(10, 4, cfg), max_new=10)
    for _ in range(3):
        cb.step()
    cb.submit(_prompt(11, 13, cfg), max_new=5)
    cb.submit(_prompt(12, 7, cfg), max_new=6)
    cb.run()
    return _streams(cb)


def _scenario_stop_sequences(params, cfg, depth):
    """Stop-sequence retirement: the matched request must not grow an
    extra token out of the in-flight step; its neighbor is untouched."""
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, chunked_prefill=4,
        pipeline_depth=depth,
    )
    p = _prompt(20, 5, cfg)
    oracle = _oracle(cb.params, p, cfg, 8)
    cb.submit(p, max_new=8, stop=[[oracle[1], oracle[2]]])
    cb.submit(_prompt(21, 6, cfg), max_new=7)
    cb.run()
    return _streams(cb)


def _scenario_cancel_and_reuse(params, cfg, depth):
    """Deterministic cancellation mid-decode, then the freed slot is
    reused — the stale in-flight token must vanish, not leak into the
    next occupant. The cancelled stream's LENGTH is timing (the host
    sees one fewer token when the last step is still in flight), so it
    is prefix-checked here and excluded from the cross-mode equality;
    the successor in the reused slot must be bit-identical."""
    p1 = _prompt(30, 5, cfg)
    cb = ContinuousBatcher(
        params, cfg, n_slots=1, max_len=64, prompt_buckets=(8,),
        pipeline_depth=depth,
    )
    r1 = cb.submit(p1, max_new=12)
    for _ in range(4):
        cb.step()
    cb.cancel(r1)
    cb.submit(_prompt(31, 6, cfg), max_new=5)
    cb.run()
    streams = _streams(cb)
    got, _ = streams.pop(r1)
    assert 1 <= len(got) < 12
    assert got == _oracle(params, p1, cfg, 12)[: len(got)]
    return streams


def _scenario_eos(params, cfg, depth):
    """EOS retirement with a queued successor into the same slot."""
    p = _prompt(40, 5, cfg)
    oracle = _oracle(params, p, cfg, 6)
    cb = ContinuousBatcher(
        params, cfg, n_slots=1, max_len=64, prompt_buckets=(8,),
        eos_id=oracle[1], pipeline_depth=depth,
    )
    cb.submit(p, max_new=6)
    cb.submit(_prompt(41, 7, cfg), max_new=6)
    cb.run()
    return _streams(cb)


def _scenario_seeded_sampled(params, cfg, depth):
    """Seeded sampled requests (their draw index now lives on device):
    the i-th draw must use fold_in(key(seed), i) with the TRUE i even
    when dispatched ahead of the host's token count."""
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, chunked_prefill=4,
        pipeline_depth=depth,
    )
    cb.submit(_prompt(50, 5, cfg), max_new=6,
              sampler=Sampler(temperature=0.9, top_k=20), seed=7)
    cb.submit(_prompt(51, 9, cfg), max_new=8,
              sampler=Sampler(temperature=1.1, top_p=0.9), seed=123)
    cb.submit(_prompt(52, 6, cfg), max_new=5)  # greedy neighbor
    cb.run()
    return _streams(cb)


SCENARIOS = {
    "bucketed_churn": _scenario_bucketed_churn,
    "chunked_midstream": _scenario_chunked_midstream,
    "stop_sequences": _scenario_stop_sequences,
    "cancel_and_reuse": _scenario_cancel_and_reuse,
    "eos": _scenario_eos,
    "seeded_sampled": _scenario_seeded_sampled,
}


@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_pipeline_bit_identical_to_sync(setup, name):
    cfg, params = setup
    sync = SCENARIOS[name](params, cfg, 0)
    pipe = SCENARIOS[name](params, cfg, 1)
    assert set(sync) == set(pipe)
    for rid in sync:
        assert pipe[rid][0] == sync[rid][0], (name, rid, "tokens")
        assert pipe[rid][1] == sync[rid][1], (name, rid, "logprobs")


# --- pipeline mechanics -----------------------------------------------------


def test_pipeline_depth_validation(setup):
    cfg, params = setup
    for bad in (-1, 2):
        with pytest.raises(ValueError, match="pipeline_depth"):
            ContinuousBatcher(params, cfg, n_slots=1, max_len=32,
                              prompt_buckets=(8,), pipeline_depth=bad)


def test_speculative_batcher_rides_the_pipeline(setup):
    """The old opt-out is gone: acceptance counts live ON DEVICE
    (lengths/budget advance inside the jitted round), so round t+1 can
    dispatch before round t's readback — the subclass honors the
    requested depth and defaults to the pipelined loop. Depth 0-vs-1
    stream exactness is pinned in tests/test_spec_fastpath.py."""
    from k8s_gpu_device_plugin_tpu.models.spec_batching import (
        SpeculativeBatcher,
    )

    cfg, params = setup
    draft_cfg = LlamaConfig.tiny(n_layers=1)
    draft_params = init_params(jax.random.key(9), draft_cfg)
    sb = SpeculativeBatcher(
        params, cfg, draft_params, draft_cfg,
        n_slots=2, max_len=64, gamma=2, chunked_prefill=8,
        pipeline_depth=1,
    )
    assert sb.pipeline_depth == 1
    sb0 = SpeculativeBatcher(
        params, cfg, draft_params, draft_cfg,
        n_slots=2, max_len=64, gamma=2, chunked_prefill=8,
        pipeline_depth=0,
    )
    assert sb0.pipeline_depth == 0


def test_steady_state_reuses_cached_device_arrays(setup):
    """Zero per-step H2D: once every slot is decoding, the membership
    mask / knobs / seeds caches must be the SAME device arrays step
    after step (they rebuild only on admit/retire/cancel) and no step
    may leave the pipeline empty."""
    cfg, params = setup
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, prompt_buckets=(8,),
        pipeline_depth=1,
    )
    cb.submit(_prompt(60, 5, cfg), max_new=32, seed=5,
              sampler=Sampler(temperature=0.8))
    cb.submit(_prompt(61, 6, cfg), max_new=32)
    while cb.pending or cb.prefilling:
        cb.step()
    cb.step()  # prime the pipeline + build every cache
    allowed0 = cb._batch_allowed()
    knobs0 = cb._batch_knobs()
    seeds0 = cb._batch_seeds()
    for _ in range(5):
        cb.step()
        assert cb._inflight is not None  # one step always in flight
        assert cb._batch_allowed() is allowed0
        assert cb._batch_knobs() is knobs0
        assert cb._batch_seeds() is seeds0
    # a membership change (cancel) invalidates all of them at once
    cb.cancel(next(iter(cb.running.values())).rid)
    assert cb._allowed_cache is None and cb._knobs_cache is None
    assert cb._seeds_cache is None


def test_slot_reuse_flushes_inflight_but_saturation_does_not(setup):
    """The flush rule is exactly as narrow as the hazard: re-admitting a
    slot the in-flight dispatch counted as live flushes first (counted
    by the pipeline_flushes metric); admissions into fresh slots — and a
    saturated queue with no free slot — stay pipelined, flush-free."""
    from prometheus_client import CollectorRegistry

    from k8s_gpu_device_plugin_tpu.metrics.serving_metrics import (
        ServingMetrics,
    )

    cfg, params = setup
    reg = CollectorRegistry()
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, prompt_buckets=(8,),
        pipeline_depth=1, metrics=ServingMetrics(registry=reg),
    )

    def flushes():
        return reg.get_sample_value("tpu_serving_pipeline_flushes_total")

    cb.submit(_prompt(70, 5, cfg), max_new=16)
    cb.step()                      # admit + dispatch: one step in flight
    assert cb._inflight is not None
    cb.submit(_prompt(71, 5, cfg), max_new=4)   # queued for a FRESH slot
    cb.step()                      # no reuse hazard -> no flush
    assert flushes() == 0
    cb.submit(_prompt(72, 5, cfg), max_new=4)   # all slots busy: queued
    cb.step()                      # saturation: still no flush
    assert flushes() == 0

    # now force the hazard: cancel a running request AFTER its slot was
    # included in the in-flight dispatch, so the next admission reuses it
    victim = next(iter(cb.running.values())).rid
    cb.cancel(victim)
    cb.step()                      # pending + freed live slot -> flush
    assert flushes() >= 1
    cb.run()
    # every surviving stream still oracle-exact (no stale-token leak
    # into the reused slot)
    for rid, req in cb.done_requests.items():
        assert req.out == _oracle(params, req.prompt, cfg, req.max_new)[
            : len(req.out)
        ]


def test_budget_exhaustion_is_gated_on_device(setup):
    """The device-side budget counter, not the host, stops emission: two
    raw decode_step dispatches with the slot still ALLOWED emit a real
    token then the -1 sentinel once the budget hits 0 — the property
    that makes dispatch-ahead safe past any budget boundary."""
    from k8s_gpu_device_plugin_tpu.models.batching import decode_step

    cfg, params = setup
    cb = ContinuousBatcher(
        params, cfg, n_slots=1, max_len=64, prompt_buckets=(8,),
        pipeline_depth=1,
    )
    cb.submit(_prompt(80, 5, cfg), max_new=2)
    cb._admit()  # prefill emits token 1 of 2 -> device budget 1
    allowed = jnp.ones((1,), bool)  # the host gate stays OPEN throughout
    state, e1, _ = decode_step(
        cb.params, cb.state, allowed, jnp.int32(-1), cfg, cb._batch_knobs()
    )
    state, e2, _ = decode_step(
        cb.params, state, allowed, jnp.int32(-1), cfg, cb._batch_knobs()
    )
    assert int(jax.device_get(e1)[0]) >= 0      # budget 1: real token
    assert int(jax.device_get(e2)[0]) == -1     # budget 0: gated on device
    assert int(jax.device_get(state.budget)[0]) == 0


def test_budget_drain_skips_the_wasted_dispatch(setup):
    """When budgets prove the in-flight step retires every running
    request, step() reads it WITHOUT dispatching ahead — the drain ends
    with an empty pipeline instead of a whole-batch -1 compute."""
    cfg, params = setup
    cb = ContinuousBatcher(
        params, cfg, n_slots=2, max_len=64, prompt_buckets=(8,),
        pipeline_depth=1,
    )
    r1 = cb.submit(_prompt(81, 5, cfg), max_new=3)
    r2 = cb.submit(_prompt(82, 6, cfg), max_new=3)
    cb.run()
    assert len(cb.done[r1]) == 3 and len(cb.done[r2]) == 3
    assert cb._inflight is None  # no stale step burned at the drain


def test_eos_lag_token_is_dropped_from_inflight(setup):
    """EOS retirement is NOT host-predictable, so the pipeline does
    dispatch one step past it — that step's emission for the retired
    slot must be the -1 sentinel (the device deactivated the slot) and
    must never reach the stream."""
    cfg, params = setup
    p = _prompt(83, 5, cfg)
    oracle = _oracle(params, p, cfg, 8)
    cb = ContinuousBatcher(
        params, cfg, n_slots=1, max_len=64, prompt_buckets=(8,),
        eos_id=oracle[1], pipeline_depth=1,
    )
    rid = cb.submit(p, max_new=8)
    cb.run()
    assert cb.done[rid] == oracle[:2]  # stopped AT the eos token
    assert cb._inflight is not None    # the unpredicted dispatch dangles
    assert int(jax.device_get(cb._inflight[1])[0]) == -1


def test_trace_steps_show_dispatch_ahead_of_readback(setup):
    """Opt-in per-step spans: decode_dispatch for step t+1 must START
    before decode_readback for step t (the overlap, visible in obs/)."""
    from k8s_gpu_device_plugin_tpu.obs.trace import configure

    cfg, params = setup
    tr = configure(enabled=True)
    tr.clear()
    try:
        cb = ContinuousBatcher(
            params, cfg, n_slots=1, max_len=64, prompt_buckets=(8,),
            pipeline_depth=1, trace_steps=True,
        )
        cb.submit(_prompt(90, 5, cfg), max_new=6)
        cb.run()
        spans = []
        for summary in tr.traces():
            spans.extend(tr.get_trace(summary["trace_id"]) or [])
        dispatch = {
            s["attrs"]["step"]: s for s in spans
            if s["name"] == "decode_dispatch"
        }
        readback = {
            s["attrs"]["step"]: s for s in spans
            if s["name"] == "decode_readback"
        }
        assert dispatch and readback
        for step, rb in readback.items():
            nxt = dispatch.get(step + 1)
            if nxt is not None:
                assert nxt["start_us"] <= rb["start_us"], step
    finally:
        tr.enabled = False
        tr.clear()


def test_sync_mode_emits_no_step_spans(setup):
    """pipeline_depth=0 never dispatches ahead: no decode_dispatch spans
    even with trace_steps on (the sync path is the old loop)."""
    from k8s_gpu_device_plugin_tpu.obs.trace import configure

    cfg, params = setup
    tr = configure(enabled=True)
    tr.clear()
    try:
        cb = ContinuousBatcher(
            params, cfg, n_slots=1, max_len=64, prompt_buckets=(8,),
            pipeline_depth=0, trace_steps=True,
        )
        cb.submit(_prompt(91, 5, cfg), max_new=3)
        cb.run()
        names = set()
        for summary in tr.traces():
            names |= {
                s["name"] for s in (tr.get_trace(summary["trace_id"]) or [])
            }
        assert "decode_dispatch" not in names
        assert "decode_readback" not in names
    finally:
        tr.enabled = False
        tr.clear()


# --- threaded serving-engine stress -----------------------------------------


def test_engine_threaded_stress_with_pipeline(setup):
    """The serving engine with the pipeline ON under concurrent load:
    12 requests over 3 slots submitted from interleaved asyncio tasks,
    two cancelled mid-flight; every surviving stream equals its
    dedicated-generate oracle and the engine stays alive."""
    from k8s_gpu_device_plugin_tpu.serving.server import (
        InferenceEngine,
        drain_queue,
    )

    cfg, params = setup
    engine = InferenceEngine(
        params, cfg, n_slots=3, max_len=64, chunked_prefill=8,
        pipeline_depth=1,
    )
    assert engine.cb.pipeline_depth == 1
    prompts = {i: _prompt(700 + i, 4 + (i % 5), cfg) for i in range(12)}

    async def body():
        async def one(i):
            await asyncio.sleep(0.002 * (i % 4))  # stagger admissions
            eid, q = engine.submit(prompts[i], max_new=4 + (i % 3))
            if i in (5, 9):
                await asyncio.sleep(0.01)
                engine.cancel(eid)
            toks, _, _err = await drain_queue(q)
            return i, toks

        return dict(await asyncio.gather(*(one(i) for i in range(12))))

    try:
        results = asyncio.run(asyncio.wait_for(body(), timeout=300))
    finally:
        engine.shutdown()
    assert not engine._dead.is_set()
    for i, toks in results.items():
        want = _oracle(params, prompts[i], cfg, 4 + (i % 3))
        if i in (5, 9):  # cancelled: any prefix of the oracle is legal
            assert toks == want[: len(toks)]
        else:
            assert toks == want, i
