"""Checkpoint/resume for training workloads (SURVEY §5: checkpointing lives
in the benchmark workloads, not the daemon).

Validates on the virtual 8-device CPU mesh: shardings survive the round
trip, training continues bit-identically after restore, retention and
cadence policies hold.
"""

import jax
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.checkpoint import (
    TrainCheckpointer,
    abstract_like,
)
from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig
from k8s_gpu_device_plugin_tpu.models.train import (
    init_train_state,
    make_optimizer,
    make_train_step,
    synthetic_batch,
)
from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec, make_mesh


@pytest.fixture(scope="module")
def setup():
    cfg = LlamaConfig.tiny(n_layers=2)
    mesh = make_mesh(MeshSpec.for_devices(8, tp=2, sp=2))
    optimizer = make_optimizer(total_steps=10)

    # the train step DONATES its input state, so every test takes a fresh
    # state from this factory rather than sharing one live tree
    def make_state():
        return init_train_state(jax.random.key(0), cfg, mesh, optimizer)

    step_fn = make_train_step(cfg, mesh, optimizer)
    batch = synthetic_batch(jax.random.key(1), cfg, 4, 64, mesh)
    return cfg, mesh, optimizer, make_state, step_fn, batch


def test_optimizer_moments_share_param_shardings():
    """ZeRO correctness: adam mu/nu must carry the fsdp param shardings
    (zeros_like has no data dependence, so GSPMD would otherwise leave them
    unsharded); scalars are mesh-replicated so checkpoint restore never
    produces single-device committed leaves."""
    cfg = LlamaConfig.tiny(n_layers=2)
    mesh = make_mesh(MeshSpec.for_devices(8, tp=2, fsdp=4))
    opt = make_optimizer(total_steps=10)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)

    adam = next(
        x
        for x in jax.tree.leaves(
            state["opt_state"], is_leaf=lambda n: hasattr(n, "mu")
        )
        if hasattr(x, "mu")
    )
    for name, p in state["params"]["layers"].items():
        assert adam.mu["layers"][name].sharding.spec == p.sharding.spec, name
        assert adam.nu["layers"][name].sharding.spec == p.sharding.spec, name
    assert len(adam.count.sharding.device_set) == 8
    assert len(state["step"].sharding.device_set) == 8


def _leaves_equal(a, b) -> bool:
    fa, fb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(fa) == len(fb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(fa, fb)
    )


def test_save_restore_roundtrip_preserves_shardings(tmp_path, setup):
    _, _, _, make_state, step_fn, batch = setup
    state1, _ = step_fn(make_state(), batch)

    with TrainCheckpointer(str(tmp_path / "ckpt"), save_interval=1) as ckpt:
        assert ckpt.save(state1)
        ckpt.wait()
        assert ckpt.latest_step() == 1
        restored = ckpt.restore(abstract_like(state1))

    assert _leaves_equal(state1, restored)
    # shardings preserved leaf-for-leaf, not just values
    for orig, rest in zip(jax.tree.leaves(state1), jax.tree.leaves(restored)):
        assert orig.sharding.is_equivalent_to(rest.sharding, orig.ndim)


def test_resume_continues_bit_identically(tmp_path, setup):
    _, _, _, make_state, step_fn, batch = setup
    batch2 = dict(batch)

    # run 2 steps straight through
    s_a, _ = step_fn(make_state(), batch)
    s_ab, m_ab = step_fn(s_a, batch2)

    # run 1 step, checkpoint, restore, run the 2nd step
    s_b, _ = step_fn(make_state(), batch)
    with TrainCheckpointer(str(tmp_path / "ckpt2"), save_interval=1) as ckpt:
        ckpt.save(s_b)
        ckpt.wait()
        resumed, was_resumed = ckpt.restore_or_pass(abstract_like(s_b))
        assert was_resumed
    s_resumed, m_resumed = step_fn(resumed, batch2)

    assert int(jax.device_get(s_resumed["step"])) == 2
    assert float(m_resumed["loss"]) == float(m_ab["loss"])
    assert _leaves_equal(s_ab["params"], s_resumed["params"])


def test_restore_or_pass_without_checkpoint(tmp_path, setup):
    _, _, _, make_state, _, _ = setup
    state = make_state()
    with TrainCheckpointer(str(tmp_path / "empty")) as ckpt:
        out, resumed = ckpt.restore_or_pass(state)
        assert not resumed
        assert out is state
        with pytest.raises(FileNotFoundError):
            ckpt.restore(abstract_like(state))


def test_retention_and_cadence(tmp_path, setup):
    _, _, _, make_state, step_fn, batch = setup
    with TrainCheckpointer(
        str(tmp_path / "keep"), max_to_keep=2, save_interval=2
    ) as ckpt:
        s = make_state()
        for _ in range(5):
            s, _ = step_fn(s, batch)
            ckpt.save(s)
        ckpt.wait()
        steps = ckpt.all_steps()
        # cadence 2 => steps 2 and 4 saved (1,3,5 skipped); retention 2 keeps both
        assert steps == [2, 4]
        # force overrides cadence
        ckpt.save(s, force=True)
        ckpt.wait()
        assert ckpt.latest_step() == 5
        assert len(ckpt.all_steps()) <= 2  # retention pruned the oldest
