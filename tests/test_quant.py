"""Int8 quantized matmul (ops/quant.py): numerics, grads, training.

CPU-verifiable semantics for the MXU double-rate path: the forward product
must track the f32 product within quantization error, the straight-through
backward must match the unquantized matmul's grads, and an int8 tiny-config
train run must still reduce loss.
"""

import jax
import jax.numpy as jnp

from k8s_gpu_device_plugin_tpu.ops.quant import int8_matmul, quantize_int8


def test_quantize_roundtrip_error_bounded():
    x = jax.random.normal(jax.random.key(0), (64, 128), jnp.float32)
    q, scale = quantize_int8(x, axis=-1)
    assert q.dtype == jnp.int8
    deq = q.astype(jnp.float32) * scale
    # max error is half an int8 step of the per-row scale
    assert float(jnp.max(jnp.abs(deq - x) / scale)) <= 0.5 + 1e-3


def test_int8_matmul_tracks_f32_product():
    kx, kw = jax.random.split(jax.random.key(1))
    x = jax.random.normal(kx, (8, 32, 256), jnp.bfloat16)
    w = jax.random.normal(kw, (256, 128), jnp.bfloat16)
    y = int8_matmul(x, w)
    ref = jnp.dot(x.astype(jnp.float32), w.astype(jnp.float32))
    assert y.dtype == x.dtype
    rel = jnp.linalg.norm(y.astype(jnp.float32) - ref) / jnp.linalg.norm(ref)
    assert float(rel) < 0.02  # ~1% quantization noise at K=256


def test_int8_matmul_grads_are_straight_through():
    kx, kw = jax.random.split(jax.random.key(2))
    x = jax.random.normal(kx, (4, 64), jnp.bfloat16)
    w = jax.random.normal(kw, (64, 32), jnp.bfloat16)

    def loss_q(x, w):
        return jnp.sum(jnp.tanh(int8_matmul(x, w).astype(jnp.float32)))

    def loss_ref(x, w):
        return jnp.sum(jnp.tanh(jnp.dot(x, w).astype(jnp.float32)))

    gx, gw = jax.grad(loss_q, argnums=(0, 1))(x, w)
    rx, rw = jax.grad(loss_ref, argnums=(0, 1))(x, w)
    assert gx.dtype == x.dtype and gw.dtype == w.dtype
    # straight-through grads differ from ref only through the (quantized)
    # tanh inputs — directions must agree closely
    cos = jnp.sum(gx.astype(jnp.float32) * rx.astype(jnp.float32)) / (
        jnp.linalg.norm(gx.astype(jnp.float32))
        * jnp.linalg.norm(rx.astype(jnp.float32))
    )
    assert float(cos) > 0.99
    cos_w = jnp.sum(gw.astype(jnp.float32) * rw.astype(jnp.float32)) / (
        jnp.linalg.norm(gw.astype(jnp.float32))
        * jnp.linalg.norm(rw.astype(jnp.float32))
    )
    assert float(cos_w) > 0.99


def test_int8_train_step_reduces_loss():
    from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig
    from k8s_gpu_device_plugin_tpu.models.train import (
        init_train_state, make_optimizer, make_train_step, synthetic_batch)
    from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec, make_mesh

    cfg = LlamaConfig.tiny(n_layers=2, quant="int8")
    mesh = make_mesh(MeshSpec.for_devices(1), jax.devices()[:1])
    opt = make_optimizer(learning_rate=1e-2, warmup_steps=1, total_steps=30)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    batch = synthetic_batch(jax.random.key(1), cfg, 4, 64, mesh)
    step = make_train_step(cfg, mesh, opt)
    state, first = step(state, batch)
    for _ in range(20):
        state, metrics = step(state, batch)
    assert float(metrics["loss"]) < float(first["loss"])


def test_int8_expert_matmul_tracks_f32():
    from k8s_gpu_device_plugin_tpu.ops.quant import int8_expert_matmul

    kx, kw = jax.random.split(jax.random.key(3))
    x = jax.random.normal(kx, (4, 16, 128), jnp.bfloat16)   # (E,M,D)
    w = jax.random.normal(kw, (4, 128, 64), jnp.bfloat16)   # (E,D,F)
    y = int8_expert_matmul(x, w)
    ref = jnp.einsum(
        "emd,edf->emf", x.astype(jnp.float32), w.astype(jnp.float32)
    )
    rel = jnp.linalg.norm(y.astype(jnp.float32) - ref) / jnp.linalg.norm(ref)
    assert float(rel) < 0.03
    # grads flow and keep operand dtypes
    gx, gw = jax.grad(
        lambda x, w: jnp.sum(int8_expert_matmul(x, w).astype(jnp.float32)),
        argnums=(0, 1),
    )(x, w)
    assert gx.shape == x.shape and gw.shape == w.shape
    assert gx.dtype == x.dtype and gw.dtype == w.dtype


def test_int8_moe_train_step_reduces_loss():
    from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig
    from k8s_gpu_device_plugin_tpu.models.train import (
        init_train_state, make_optimizer, make_train_step, synthetic_batch)
    from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec, make_mesh

    cfg = LlamaConfig.tiny(n_layers=2, n_experts=4, quant="int8")
    mesh = make_mesh(MeshSpec.for_devices(1), jax.devices()[:1])
    opt = make_optimizer(learning_rate=1e-2, warmup_steps=1, total_steps=30)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    batch = synthetic_batch(jax.random.key(1), cfg, 4, 64, mesh)
    step = make_train_step(cfg, mesh, opt)
    state, first = step(state, batch)
    for _ in range(20):
        state, metrics = step(state, batch)
    assert float(metrics["loss"]) < float(first["loss"])


def test_int8_loss_curve_tracks_bf16():
    """Numerics honesty for the int8 path: over a short tiny-config run the
    int8 loss curve must track bf16 closely (straight-through bf16 grads
    keep optimization directions; only forward activations are quantized)."""
    from k8s_gpu_device_plugin_tpu.models.llama import LlamaConfig
    from k8s_gpu_device_plugin_tpu.models.train import (
        init_train_state, make_optimizer, make_train_step, synthetic_batch)
    from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec, make_mesh

    losses = {}
    for quant in ("none", "int8"):
        cfg = LlamaConfig.tiny(n_layers=2, quant=quant)
        mesh = make_mesh(MeshSpec.for_devices(1), jax.devices()[:1])
        opt = make_optimizer(learning_rate=3e-3, warmup_steps=2, total_steps=40)
        state = init_train_state(jax.random.key(0), cfg, mesh, opt)
        batch = synthetic_batch(jax.random.key(1), cfg, 4, 64, mesh)
        step = make_train_step(cfg, mesh, opt)
        for _ in range(30):
            state, metrics = step(state, batch)
        losses[quant] = float(metrics["loss"])
    # same data, same init, same lr: final losses within 5% relative
    assert abs(losses["int8"] - losses["none"]) / losses["none"] < 0.05, losses
