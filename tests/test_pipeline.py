"""Pipeline parallelism (pp) tests on the virtual 8-device CPU mesh.

Oracle: the looped GSPMD pipeline (parallel/pipeline.py) is algebraically
the same computation as the plain lax.scan over layers, so the pipelined
forward must match the unpipelined one bit-for-bit on identical params
(only collective scheduling differs).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_gpu_device_plugin_tpu.models.llama import (
    LlamaConfig,
    forward,
    init_params,
)
from k8s_gpu_device_plugin_tpu.models.train import (
    init_train_state,
    make_optimizer,
    make_train_step,
    synthetic_batch,
)
from k8s_gpu_device_plugin_tpu.parallel.mesh import MeshSpec, make_mesh
from k8s_gpu_device_plugin_tpu.parallel.pipeline import (
    pipeline_blocks,
    stack_for_stages,
    unstack_stages,
)


def require_devices(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


def test_stack_unstack_roundtrip():
    layers = {"w": jnp.arange(24.0).reshape(4, 3, 2)}
    stacked = stack_for_stages(layers, 2)
    assert stacked["w"].shape == (2, 2, 3, 2)
    # stage 0 holds layers [0, 1], stage 1 holds [2, 3]
    np.testing.assert_array_equal(
        np.asarray(stacked["w"][0]), np.asarray(layers["w"][:2])
    )
    round_tripped = unstack_stages(stacked)
    np.testing.assert_array_equal(
        np.asarray(round_tripped["w"]), np.asarray(layers["w"])
    )


def test_stack_rejects_indivisible():
    with pytest.raises(ValueError, match="not divisible"):
        stack_for_stages({"w": jnp.zeros((5, 2))}, 2)


def test_pipeline_blocks_matches_sequential():
    require_devices(2)
    mesh = make_mesh(MeshSpec.for_devices(2, pp=2), jax.devices()[:2])
    n_stages, layers_per_stage = 2, 3
    key = jax.random.key(0)
    w = jax.random.normal(key, (n_stages * layers_per_stage, 8, 8)) * 0.1
    x = jax.random.normal(jax.random.key(1), (4, 5, 8))

    def apply_layer(h, wi):
        return jnp.tanh(h @ wi), None

    expected, _ = jax.lax.scan(apply_layer, x, w)

    def stage_fn(stage_w, h):
        h, _ = jax.lax.scan(apply_layer, h, stage_w)
        # per-stage aux: mean activation, to check masked accumulation too
        return h, {"mean_act": jnp.mean(h)}

    stage_params = stack_for_stages({"w": w}, n_stages)["w"]
    with mesh:
        got, aux = jax.jit(
            lambda p, x: pipeline_blocks(
                stage_fn, p, x, n_stages=n_stages, n_microbatches=2
            )
        )(stage_params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-6)
    assert np.isfinite(float(aux["mean_act"]))


def test_pipeline_aux_ignores_fill_and_drain_garbage():
    """Aux leaves must equal sum-over-stages averaged over microbatches of
    LIVE microbatch contributions only — bubble ticks contribute nothing."""
    require_devices(2)
    mesh = make_mesh(MeshSpec.for_devices(2, pp=2), jax.devices()[:2])
    n_stages, M = 2, 4
    w = jnp.zeros((n_stages, 1, 1))  # params unused
    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1, 1)

    def stage_fn(stage_w, h):
        # aux = 1 per (stage, live microbatch): total = pp * M / M = pp.
        # Garbage ticks would inflate this (zeros state -> still aux 1).
        return h, {"count": jnp.float32(1.0)}

    with mesh:
        _, aux = jax.jit(
            lambda p, x: pipeline_blocks(
                stage_fn, p, x, n_stages=n_stages, n_microbatches=M
            )
        )(w, x)
    assert float(aux["count"]) == pytest.approx(n_stages)


def test_pipeline_rejects_bad_microbatch():
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_blocks(
            lambda p, h: (h, {}),
            jnp.zeros((2, 1)),
            jnp.zeros((5, 4, 8)),
            n_stages=2,
            n_microbatches=2,
        )


@pytest.fixture(scope="module")
def pp_setup():
    require_devices(8)
    cfg = LlamaConfig.tiny(n_layers=4, n_microbatches=4)
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(
        jax.random.key(1), (8, 32), 0, cfg.vocab_size, jnp.int32
    )
    ref = forward(params, tokens, cfg)
    return cfg, params, tokens, ref


def test_pp_forward_matches_unpipelined(pp_setup):
    cfg, params, tokens, ref = pp_setup
    mesh = make_mesh(MeshSpec.for_devices(8, pp=2, tp=2), jax.devices())
    pparams = {**params, "layers": stack_for_stages(params["layers"], 2)}
    got = jax.jit(lambda p, t: forward(p, t, cfg, mesh))(pparams, tokens)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), atol=2e-2, rtol=2e-2
    )


def test_pp_composes_with_ring_attention(pp_setup):
    cfg, params, tokens, ref = pp_setup
    cfg = LlamaConfig.tiny(n_layers=4, n_microbatches=2, attn_impl="ring")
    mesh = make_mesh(MeshSpec.for_devices(8, pp=2, sp=2, tp=2), jax.devices())
    pparams = {**params, "layers": stack_for_stages(params["layers"], 2)}
    got = jax.jit(lambda p, t: forward(p, t, cfg, mesh))(pparams, tokens)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), atol=2e-2, rtol=2e-2
    )


def test_pp_train_step_runs_and_loss_finite():
    require_devices(8)
    cfg = LlamaConfig.tiny(n_layers=4, n_microbatches=4)
    mesh = make_mesh(MeshSpec.for_devices(8, pp=2, tp=2), jax.devices())
    opt = make_optimizer(total_steps=10)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    # layer leaves are stage-stacked and sharded over pp
    assert state["params"]["layers"]["wq"].shape[0] == 2
    batch = synthetic_batch(jax.random.key(1), cfg, 8, 32, mesh)
    step = make_train_step(cfg, mesh, opt)
    state, metrics = step(state, batch)
    assert jnp.isfinite(metrics["loss"])
    assert jnp.isfinite(metrics["grad_norm"])


def test_pp_moe_forward_and_aux():
    """MoE through the pipeline on a pp x ep x tp mesh: logits match the
    unpipelined reference and router aux losses come out finite/positive."""
    require_devices(8)
    from k8s_gpu_device_plugin_tpu.models.llama import forward_with_aux

    cfg = LlamaConfig.tiny(n_layers=4, n_experts=4, n_microbatches=2)
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(
        jax.random.key(1), (8, 32), 0, cfg.vocab_size, jnp.int32
    )
    ref_logits, ref_aux = forward_with_aux(params, tokens, cfg)

    mesh = make_mesh(MeshSpec.for_devices(8, pp=2, ep=2, tp=2), jax.devices())
    pparams = {**params, "layers": stack_for_stages(params["layers"], 2)}
    got, aux = jax.jit(
        lambda p, t: forward_with_aux(p, t, cfg, mesh)
    )(pparams, tokens)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref_logits), atol=2e-2, rtol=2e-2
    )
    assert set(aux) == set(ref_aux)
    for key in aux:
        # per-microbatch router stats, not bit-identical to full-batch ones
        assert np.isfinite(float(aux[key]))
        np.testing.assert_allclose(
            float(aux[key]), float(ref_aux[key]), rtol=0.25
        )


def test_pp_moe_train_step_sp_pp_ep():
    """n_experts>0, pp>1 training step composed with sp and ep on the
    8-device CPU mesh (all four of tp x sp x pp x ep >= 2 needs 16 devices;
    dryrun_multichip(16) covers that composition)."""
    require_devices(8)
    cfg = LlamaConfig.tiny(
        n_layers=4, n_experts=4, n_microbatches=2, attn_impl="ring"
    )
    mesh = make_mesh(MeshSpec.for_devices(8, pp=2, ep=2, sp=2), jax.devices())
    opt = make_optimizer(total_steps=10)
    state = init_train_state(jax.random.key(0), cfg, mesh, opt)
    batch = synthetic_batch(jax.random.key(1), cfg, 8, 32, mesh)
    step = make_train_step(cfg, mesh, opt)
    state, metrics = step(state, batch)
    assert jnp.isfinite(metrics["loss"])
    assert jnp.isfinite(metrics["grad_norm"])
    assert "moe_load_balance" in metrics
