"""Chip model & AnnotatedID tests (≙ device/devices.go:88-265 table tests)."""

import pytest

from k8s_gpu_device_plugin_tpu.device.chip import (
    HEALTHY,
    UNHEALTHY,
    AnnotatedID,
    Chip,
    Chips,
)


def make_chip(i: int, health: str = HEALTHY) -> Chip:
    return Chip(
        id=f"TPU-{i:04d}",
        index=i,
        paths=(f"/dev/accel{i}",),
        coords=((i % 2, i // 2),),
        generation="v5e",
        total_memory=16 << 30,
        health=health,
        chip_indices=(i,),
    )


def test_annotated_id_roundtrip():
    aid = AnnotatedID("TPU-abc", 3)
    assert str(aid) == "TPU-abc::3"
    parsed = AnnotatedID.parse("TPU-abc::3")
    assert parsed == aid


def test_annotated_id_detection():
    assert AnnotatedID.is_annotated("TPU-abc::0")
    assert not AnnotatedID.is_annotated("TPU-abc")
    assert not AnnotatedID.is_annotated("TPU-abc::x")
    assert not AnnotatedID.is_annotated("::3")
    assert AnnotatedID.any_annotated(["a", "b::1"])
    assert not AnnotatedID.any_annotated(["a", "b"])


def test_annotated_parse_rejects_plain():
    with pytest.raises(ValueError):
        AnnotatedID.parse("TPU-abc")


def test_chips_set_ops():
    chips = Chips.of([make_chip(i) for i in range(4)])
    assert chips.contains("TPU-0000", "TPU-0003")
    assert not chips.contains("TPU-0000", "nope")
    assert chips.get_by_index(2).id == "TPU-0002"
    assert chips.get_by_index(9) is None

    sub = chips.subset(["TPU-0001", "TPU-0002", "missing"])
    assert sub.ids() == ["TPU-0001", "TPU-0002"]

    diff = chips.difference(sub)
    assert diff.ids() == ["TPU-0000", "TPU-0003"]
    assert chips.indices() == [0, 1, 2, 3]


def test_chips_paths_deduped_ordered():
    chips = Chips.of([make_chip(1), make_chip(0)])
    assert chips.all_paths() == ["/dev/accel0", "/dev/accel1"]


def test_chips_healthy_filter():
    chips = Chips.of([make_chip(0), make_chip(1, UNHEALTHY)])
    assert chips.healthy().ids() == ["TPU-0000"]


def test_physical_ids_collapse_replicas():
    chips = Chips.of(
        [
            Chip(
                id=str(AnnotatedID(f"TPU-{i}", r)),
                index=i,
                paths=(),
                coords=(),
                generation="v5e",
                total_memory=0,
                replicas=2,
            )
            for i in range(2)
            for r in range(2)
        ]
    )
    assert sorted(chips.physical_ids()) == ["TPU-0", "TPU-1"]


def test_aligned_allocation_supported():
    whole = Chips.of([make_chip(0)])
    assert whole.aligned_allocation_supported()
    sliced = Chips.of(
        [
            Chip(
                id="S",
                index=0,
                paths=(),
                coords=((0, 0), (0, 1)),
                generation="v5e",
                total_memory=0,
                slice_profile="1x2",
            )
        ]
    )
    assert not sliced.aligned_allocation_supported()
