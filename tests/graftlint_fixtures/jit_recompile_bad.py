# ruff: noqa
"""Firing fixture: jit call sites that defeat the compile cache."""
from functools import partial

import jax


def step(x):
    return x


def per_call(x):
    return jax.jit(lambda v: v + 1)(x)  # BAD: built-and-invoked, cache dies


def in_loop(xs):
    out = []
    for x in xs:
        f = jax.jit(step)  # BAD: rebuilt (empty cache) every iteration
        out.append(f(x))
    return out


class Engine:
    @jax.jit
    def decode(self, state):  # BAD: jit over a method hashes/traces self
        return state

    def make(self):
        @partial(jax.jit, static_argnames=("cfg",))
        def inner(x):  # BAD: static names a missing param; closes over self
            return x + self.bias

        return inner


@partial(jax.jit, static_argnames=("shapes",))
def bad_static(x, shapes: list = []):  # BAD: unhashable static default
    return x


@partial(jax.jit, static_argnums=(1,))
def bad_argnum(x, cfgs: dict = {}):  # BAD: positional static, unhashable
    return x


@partial(jax.jit, static_argnums=(5,))
def bad_argnum_range(x):  # BAD: argnum points past the signature
    return x
