# ruff: noqa
"""Non-firing twin: cached device residents, uploads outside hot paths."""
import jax.numpy as jnp


class Batcher:
    def _decode_dispatch(self, allowed):  # graftlint: hot-path
        return self.step(self._knobs_cache, allowed)

    def step(self, *args):  # graftlint: hot-path
        return args

    def _invalidate(self):
        # membership-change path, not a hot path: uploads are fine here
        self._knobs_cache = jnp.asarray([1.0, 0.0, 1.0, 0.0])


def scatter_rows(cache, row, p):  # graftlint: hot-path=traced
    # runs INSIDE another function's jit: arange is a trace-time
    # constant here, not a per-step transfer
    idx = jnp.arange(p)
    return cache, row, idx
