# ruff: noqa
"""Non-firing twin: cached device residents, uploads outside hot paths."""
import jax.numpy as jnp


class Batcher:
    def _decode_dispatch(self, allowed):  # graftlint: hot-path
        return self.step(self._knobs_cache, allowed)

    def step(self, *args):  # graftlint: hot-path
        return args

    def _step_inner(self):  # graftlint: hot-path
        # the page table is a cached device resident (uploaded at
        # admission by _install_pages below): reading it is free
        return self.step(self._pages_cache)

    def _prefill_grow_row(self, slot):  # graftlint: hot-path
        # streaming chunk-prefill steady state: the grown table row is
        # a cached device resident (committed by _grow_slot_pages
        # below); the hot path does only host FREE-LIST MATH — window
        # arithmetic for out-of-window recycling candidates — which
        # never touches the device
        dead = max(0, (self._pos - self._window + 1) // self._page_size)
        self._recycle_lo = dead
        return self.step(self._pages_cache, slot)

    def _decode_dispatch_gathered(self, sel):  # graftlint: hot-path
        # gathered multi-LoRA steady state: the compact stacks are
        # cached device residents (committed by _ensure_gathered below
        # only when the batch's active-adapter set changes) — the hot
        # path just reads them
        return self.step(self._lora_stacks_cache, sel)

    def _invalidate(self):
        # membership-change path, not a hot path: uploads are fine here
        self._knobs_cache = jnp.asarray([1.0, 0.0, 1.0, 0.0])

    def _ensure_gathered(self, active):
        # sel-rebuild seam, not a hot path: regathering the compact
        # adapter stacks on an active-set CHANGE is the contract (zero
        # per-step work once the set is stable)
        import jax

        self._lora_stacks_cache = jax.device_put(self._host_blocks)

    def _install_pages(self, row, sharding):
        # admission-time path, not a hot path: committing the (tp-
        # replicated) page-table row onto the mesh here is the contract
        import jax

        self._pages_cache = jax.device_put(row, sharding)


def scatter_rows(cache, row, p):  # graftlint: hot-path=traced
    # runs INSIDE another function's jit: arange is a trace-time
    # constant here, not a per-step transfer
    idx = jnp.arange(p)
    return cache, row, idx


def serving_cache_attention(q, k, v, length, pages):  # graftlint: hot-path=traced
    # the unified-kernel dispatch seam (ops/attention.py): traced inside
    # the serving jits, so broadcasting the base positions with a
    # constructor is a trace-time constant — the kernel's scalar-
    # prefetch operand, not a per-step upload
    base = jnp.full((q.shape[0],), length)
    return q, k, v, base, pages
