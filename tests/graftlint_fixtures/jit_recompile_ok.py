# ruff: noqa
"""Non-firing twin: module-scope wrappers, factories, hashable statics."""
from functools import partial

import jax


@partial(jax.jit, static_argnames=("n",))
def step(x, n: int = 1):
    return x * n


_jitted = jax.jit(lambda v: v + 1)  # module scope: built exactly once


def factory(cfg):
    def inner(x):
        return x

    # factory pattern: the wrapper persists with the caller, its cache
    # lives as long as the returned callable does
    return jax.jit(inner)


def drive(xs):
    f = jax.jit(step)  # built once BEFORE the loop
    return [f(x) for x in xs]
