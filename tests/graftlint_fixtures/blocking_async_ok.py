# ruff: noqa
"""Non-firing twin: awaits and executor hops only."""
import asyncio


async def handler(request, embedder, ids):
    await asyncio.sleep(0.1)
    loop = asyncio.get_running_loop()
    vec = await loop.run_in_executor(None, embedder.embed, ids)
    item = await request.queue.get()
    await request.stop_event.wait()  # asyncio.Event: the awaited twin
    return vec, item
