# ruff: noqa
"""Non-firing twin: awaits and executor hops only."""
import asyncio


async def handler(request, embedder, ids):
    await asyncio.sleep(0.1)
    loop = asyncio.get_running_loop()
    vec = await loop.run_in_executor(None, embedder.embed, ids)
    item = await request.queue.get()
    await request.stop_event.wait()  # asyncio.Event: the awaited twin
    return vec, item


async def proxy_handler(request, replica, session):
    """The router proxy done right (serving/router.py): async client,
    async backoff — the event loop keeps every other stream moving."""
    raw = await request.read()
    resp = await session.post(f"{replica.url}{request.path}", data=raw)
    if resp.status == 429:
        await asyncio.sleep(1.0)     # async Retry-After backoff
        resp = await session.post(f"{replica.url}{request.path}", data=raw)
    return await resp.read()
