# ruff: noqa
"""Firing fixture: page retains with no reachable release."""


class Holder:
    def grab(self, n):
        self.pool.alloc(n)  # BAD: result discarded at refcount 1

    def window(self, req, n):
        pages = self.pool.alloc(n)
        self.report()  # BAD: can raise before ownership is recorded
        req._pages = pages

    def orphan(self, n):
        pages = self.pool.alloc(n)
        return None  # BAD: returns WITHOUT the retained pages

    def stash(self, n):
        # BAD (at the ledger level): nothing ever reads '_lost' and
        # decrefs, so the ledger is never drained
        self._lost = self.pool.alloc(n)

    def report(self):
        pass
