# ruff: noqa
"""Firing fixture: engine-owned state touched from handler contexts."""


class Batcher:
    def __init__(self):
        self.running = {}  # owner: engine
        self.pool = None   # owner: engine

    def kv_stats(self):
        return {"pages_free": 0}


class Server:
    def __init__(self, cb):
        self.cb = cb

    async def health(self, request):
        return {
            "active": len(self.cb.running),           # OK: atomic len
            "slots": list(self.cb.running.values()),  # BAD: iteration races
            "free": self.cb.pool.free_pages,          # BAD: pool internals
        }

    def stats(self):  # graftlint: cross-thread
        return dict(self.cb.running)  # BAD: cross-thread dict copy
