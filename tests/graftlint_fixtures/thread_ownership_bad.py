# ruff: noqa
"""Firing fixture: engine-owned state touched from handler contexts."""


class Batcher:
    def __init__(self):
        self.running = {}  # owner: engine
        self.pool = None   # owner: engine

    def kv_stats(self):
        return {"pages_free": 0}


class Scheduler:
    """The serving/scheduler.py shape: policy ledgers are engine-thread
    state; consumers must go through the sched_stats() snapshot."""

    def __init__(self):
        self._tenants = {}     # owner: engine
        self.rejections = {}   # owner: engine

    def sched_stats(self):
        return {"tenants": {k: dict(v) for k, v in list(self._tenants.items())}}


class Recorder:
    """The obs/attribution.py shape: the flight-recorder ring and the
    recent-timeline ring are engine-written; HTTP readers must go
    through the slow_stats()/request_stats() snapshots."""

    def __init__(self):
        self._slow_ring = []  # owner: engine
        self._recent = []     # owner: engine

    def slow_stats(self):
        return {"requests": [dict(r) for r in list(self._slow_ring)]}


class Supervisor:
    """The serving/supervisor.py shape: the crash-recovery ledgers are
    engine-thread state; /v1/health must use the stats() snapshot."""

    def __init__(self):
        self._restart_times = []   # owner: engine
        self._last_crash = None    # owner: engine

    def stats(self):
        return {"restarts": len(list(self._restart_times))}


class FleetRegistry:
    """The serving/fleet.py shape: the replica map is mutated by the
    health poller and the proxy-failure paths; handlers must read it
    through the fleet_stats() snapshot accessor, never recompute
    per-replica state inline."""

    def __init__(self):
        self._replicas = {}  # owner: engine

    def fleet_stats(self):
        return {"replicas": {k: dict(v) for k, v in
                             list(self._replicas.items())}}


class Journal:
    """The plugin/journal.py shape: the two-tier event rings and the
    live-ownership table are manager-loop state; the HTTP handlers
    (/debug/allocations, /debug/topology) must go through the
    events_payload()/owners() snapshots."""

    def __init__(self):
        self._events = []  # owner: engine
        self._owners = {}  # owner: engine

    def events_payload(self):
        return {"events": [dict(e) for e in list(self._events)]}


class Server:
    def __init__(self, cb, sched, rec, sup, fleet, journal):
        self.cb = cb
        self.sched = sched
        self.rec = rec
        self.sup = sup
        self.fleet = fleet
        self.journal = journal

    async def health(self, request):
        return {
            "active": len(self.cb.running),           # OK: atomic len
            "slots": list(self.cb.running.values()),  # BAD: iteration races
            "free": self.cb.pool.free_pages,          # BAD: pool internals
            "tenants": dict(self.sched._tenants),     # BAD: ledger copy races
            "restarts": len(self.sup._restart_times),  # OK: atomic len
            "crash": self.sup._last_crash,            # BAD: ledger read
        }

    async def fleet_health(self, request):
        # BAD: recomputing per-replica state inline while the poller
        # mutates the registry (the PR-15 /fleet/health fix's shape)
        return {
            "alive": [r for r in self.fleet._replicas.values()],
            "total": len(self.fleet._replicas),  # OK: atomic len
        }

    async def allocations(self, request):
        return {
            "resident": len(self.journal._events),     # OK: atomic len
            "events": list(self.journal._events),      # BAD: ring iteration races
            "owners": dict(self.journal._owners),      # BAD: table copy races
        }

    async def slow(self, request):
        return list(self.rec._slow_ring)  # BAD: ring iteration races

    def stats(self):  # graftlint: cross-thread
        return dict(self.cb.running)  # BAD: cross-thread dict copy

    def overload(self):  # graftlint: cross-thread
        return self.sched.rejections["queue_full"]  # BAD: ledger read

    def crashes(self):  # graftlint: cross-thread
        return list(self.sup._restart_times)  # BAD: ledger iteration races
