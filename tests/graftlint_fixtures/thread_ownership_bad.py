# ruff: noqa
"""Firing fixture: engine-owned state touched from handler contexts."""


class Batcher:
    def __init__(self):
        self.running = {}  # owner: engine
        self.pool = None   # owner: engine

    def kv_stats(self):
        return {"pages_free": 0}


class Scheduler:
    """The serving/scheduler.py shape: policy ledgers are engine-thread
    state; consumers must go through the sched_stats() snapshot."""

    def __init__(self):
        self._tenants = {}     # owner: engine
        self.rejections = {}   # owner: engine

    def sched_stats(self):
        return {"tenants": {k: dict(v) for k, v in list(self._tenants.items())}}


class Server:
    def __init__(self, cb, sched):
        self.cb = cb
        self.sched = sched

    async def health(self, request):
        return {
            "active": len(self.cb.running),           # OK: atomic len
            "slots": list(self.cb.running.values()),  # BAD: iteration races
            "free": self.cb.pool.free_pages,          # BAD: pool internals
            "tenants": dict(self.sched._tenants),     # BAD: ledger copy races
        }

    def stats(self):  # graftlint: cross-thread
        return dict(self.cb.running)  # BAD: cross-thread dict copy

    def overload(self):  # graftlint: cross-thread
        return self.sched.rejections["queue_full"]  # BAD: ledger read
